"""Fabric under injected faults (ISSUE 15 satellite): peer send/recv
faults ride the shared reconnect backoff to recovery, an armed takeover
failpoint cannot stop a takeover, and the full multi-process harness
proves the SIGKILL story — takeover recall 1.0, fabric-wide accounting,
rejoin handback without double-processing."""

import threading
import time

import pytest

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable
from banjax_tpu.fabric.router import FabricRouter
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.backoff import reconnect_backoff


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def _recording_backoff(delays):
    return reconnect_backoff(
        cap=0.2, base=0.01, sleep=lambda d: delays.append(d) or False
    )


def _echo_node():
    return FabricNode("127.0.0.1", 0, handlers={
        wire.T_PING: lambda p: (wire.T_PONG, {}),
    }).start()


def test_send_fault_backs_off_then_reconnects():
    """fabric.send armed for 2 fires: the first two attempts fault, the
    backoff waits between tries, the third succeeds — same capped
    jittered policy as the kafka/tailer loops."""
    node = _echo_node()
    delays = []
    client = PeerClient(
        "p", "127.0.0.1", node.port, send_timeout_ms=500,
        max_attempts=3, backoff=_recording_backoff(delays),
    )
    try:
        failpoints.arm("fabric.send", count=2)
        rtype, _ = client.request(wire.T_PING, {})
        assert rtype == wire.T_PONG
        assert failpoints.fired_count("fabric.send") == 2
        assert len(delays) == 2          # one backoff wait per failed try
        assert delays[1] > 0             # exponential: still positive
        # recovery resets the policy: next request is first-try clean
        delays.clear()
        client.request(wire.T_PING, {})
        assert delays == []
    finally:
        client.close()
        node.stop()


def test_recv_fault_tears_connection_then_client_recovers():
    """fabric.recv armed once: the node drops the connection exactly
    like a torn network; the client's next attempt reconnects and
    completes inside the same request() call."""
    node = _echo_node()
    delays = []
    client = PeerClient(
        "p", "127.0.0.1", node.port, send_timeout_ms=500,
        max_attempts=3, backoff=_recording_backoff(delays),
    )
    try:
        client.request(wire.T_PING, {})  # warm connection established
        failpoints.arm("fabric.recv", count=1)
        rtype, _ = client.request(wire.T_PING, {})
        assert rtype == wire.T_PONG
        assert failpoints.fired_count("fabric.recv") == 1
        assert len(delays) >= 1          # the retry waited before reconnect
    finally:
        client.close()
        node.stop()


def test_send_fault_exhausting_budget_raises_peer_unavailable():
    node = _echo_node()
    client = PeerClient(
        "p", "127.0.0.1", node.port, send_timeout_ms=500,
        max_attempts=2, backoff=_recording_backoff([]),
    )
    try:
        failpoints.arm("fabric.send")    # unlimited: every attempt faults
        with pytest.raises(PeerUnavailable):
            client.request(wire.T_PING, {})
    finally:
        client.close()
        node.stop()


def test_takeover_fault_cannot_stop_the_takeover():
    """fabric.takeover armed: the failpoint fires inside mark_dead but
    the takeover must still complete — range moved, journal replayed,
    counters bumped.  Losing a takeover would orphan a keyspace range."""
    ring = ConsistentHashRing(["w0", "w1"], vnodes=64)

    class _DeadPeer:
        peer_id, host, port = "w1", "127.0.0.1", 0
        breaker = type("B", (), {"state": "open"})()

        def request(self, ftype, payload):
            raise PeerUnavailable("w1 gone")

        def connect_to(self, host, port):
            pass

    local = []
    stats = FabricStats()
    router = FabricRouter(
        "w0", ring, {"w0": None, "w1": _DeadPeer()},
        lambda ls: local.extend(ls) or len(ls),
        stats=stats, takeover_grace_ms=0.0,
    )
    # seed w1's journal so the takeover has something to replay
    lines = [f"1000.0 10.2.{i >> 8}.{i & 255} GET h GET / HTTP/1.1 ua -"
             for i in range(256)]
    # force-journal through routing while w1 still answers
    held = []

    class _LivePeer(_DeadPeer):
        def request(self, ftype, payload):
            held.extend(payload["lines"])
            return wire.T_ACK, {}

    router.peers["w1"] = _LivePeer()
    router.route(lines)
    assert held
    router.peers["w1"] = _DeadPeer()
    failpoints.arm("fabric.takeover", count=1)
    router.mark_dead("w1", reason="chaos")
    assert failpoints.fired_count("fabric.takeover") == 1
    peek = stats.peek()
    assert peek["FabricTakeovers"] == 1
    assert peek["FabricReplayedLines"] == len(held)
    assert set(held) <= set(local)       # sole survivor re-derived all
    assert "w1" not in router.alive


def test_breaker_open_fails_fast_without_socket_attempts():
    """A dead peer's breaker opens after the retry budget; subsequent
    requests fail fast (PeerUnavailable) without burning the timeout —
    the property that keeps a takeover from stalling the feed path."""
    delays = []
    client = PeerClient(
        "ghost", "127.0.0.1", 1, send_timeout_ms=100, max_attempts=2,
        backoff=_recording_backoff(delays),
    )
    for _ in range(2):                   # drive the breaker open
        with pytest.raises(PeerUnavailable):
            client.request(wire.T_PING, {})
    assert not client.breaker.allow()
    n_delays = len(delays)
    with pytest.raises(PeerUnavailable, match="breaker"):
        client.request(wire.T_PING, {})
    assert len(delays) == n_delays       # no new connect/backoff burned


def test_node_survives_oversized_frame_without_desync():
    """A sabotage-sized frame fails that connection loudly; the node
    keeps serving fresh connections."""
    import socket as _socket

    node = _echo_node()
    try:
        raw = _socket.create_connection(("127.0.0.1", node.port), 1.0)
        raw.sendall(wire._HEADER.pack(wire.MAX_FRAME_BYTES + 1, wire.T_PING))
        raw.close()
        client = PeerClient("p", "127.0.0.1", node.port,
                            send_timeout_ms=500)
        try:
            assert client.request(wire.T_PING, {})[0] == wire.T_PONG
        finally:
            client.close()
    finally:
        node.stop()


def test_sigkill_mid_scenario_takeover_and_rejoin_handback():
    """The full fault story through REAL processes (reduced scale; the
    scale-1.0 pass lives in tests/soak/test_fabric_soak.py): SIGKILL a
    shard mid-scenario → successor takeover with recall 1.0 and the
    fabric-wide admitted == processed + shed ledger, then rejoin →
    range handback without double-processing."""
    from banjax_tpu.fabric.harness import run_fabric

    report = run_fabric(
        n_workers=2, shape="flash_crowd", seed=20260804, scale=0.5,
        kill=True, rejoin=True,
    )
    bad = [k for k, ok in report["invariants"].items() if not ok]
    bad += [
        f"rejoin.{k}"
        for k, ok in report["rejoin"]["invariants"].items() if not ok
    ]
    assert not bad, f"{bad}\n{report}"
    assert report["recall"] == 1.0 and report["oracle_bans"] > 0
    assert report["fed_lines"] == report["acked_lines"]
    assert report["takeover"]["victim"] == report["killed"] == "w1"
    assert report["rejoin"]["invariants"]["wave_exactly_once"]


# ---------------------------------------------------------------------------
# gossip failpoints (ISSUE 16 satellite 2): partitioned probes, a slow
# node faked with an ack sleep, and a dropped membership update — each
# armed at the REAL instrumented site over real sockets
# ---------------------------------------------------------------------------


def _gossip_pair(interval_ms=80.0, suspect_ms=600.0):
    """Two SwimMembership tables backed by real FabricNodes; the probe
    loops are NOT started — tests drive tick() by hand."""
    from banjax_tpu.fabric.membership import SwimMembership

    a = SwimMembership("wa", "127.0.0.1", 0, gossip_interval_ms=interval_ms,
                       suspect_timeout_ms=suspect_ms, rng_seed=1)
    b = SwimMembership("wb", "127.0.0.1", 0, gossip_interval_ms=interval_ms,
                       suspect_timeout_ms=suspect_ms, rng_seed=2)
    node_a = FabricNode("127.0.0.1", 0, handlers={
        wire.T_GOSSIP_PING: a.handle_ping,
        wire.T_GOSSIP_PING_REQ: a.handle_ping_req,
    }).start()
    node_b = FabricNode("127.0.0.1", 0, handlers={
        wire.T_GOSSIP_PING: b.handle_ping,
        wire.T_GOSSIP_PING_REQ: b.handle_ping_req,
    }).start()
    a._members["wa"].port = node_a.port
    b._members["wb"].port = node_b.port
    a.seed({"wb": ("127.0.0.1", node_b.port)})
    b.seed({"wa": ("127.0.0.1", node_a.port)})
    return a, node_a, b, node_b


def test_gossip_ping_drop_suspects_then_digest_refutes_on_heal():
    """fabric.gossip.ping armed (full partition): every outgoing probe
    — direct AND the indirect relays — is dropped, so the target goes
    SUSPECT.  Disarming heals the link; the next probe carries the
    suspicion in its digest, the target refutes it by incarnation bump,
    and the ack digest clears the suspicion at the prober."""
    from banjax_tpu.fabric.membership import ALIVE, SUSPECT

    a, node_a, b, node_b = _gossip_pair()
    try:
        failpoints.arm("fabric.gossip.ping")
        a.tick()
        assert a.status_of("wb") == SUSPECT
        assert failpoints.fired_count("fabric.gossip.ping") >= 1
        failpoints.disarm("fabric.gossip.ping")
        a.tick()  # probe rides through; wb sees its own suspicion
        assert a.status_of("wb") == ALIVE
        assert b.describe()["incarnation"] >= 1  # the refutation bump
        assert a.describe()["members"]["wb"]["incarnation"] >= 1
        assert a.describe()["suspects"] == []
    finally:
        node_a.stop()
        node_b.stop()


def test_gossip_ack_sleep_fakes_slow_node_suspect_then_refute():
    """fabric.gossip.ack armed with mode=sleep longer than the probe
    timeout: the target is alive but answers too late, so the prober
    suspects it — the exact slow-node shape the churn harness drives.
    Once the failpoint is disarmed the next round refutes."""
    from banjax_tpu.fabric.membership import ALIVE, SUSPECT

    a, node_a, b, node_b = _gossip_pair(interval_ms=80.0)
    try:
        # probe timeout is max(0.05, interval)=0.08s; sleep well past it
        failpoints.arm("fabric.gossip.ack", mode="sleep", delay_s=0.4)
        a.tick()
        assert a.status_of("wb") == SUSPECT
        failpoints.disarm("fabric.gossip.ack")
        deadline = threading.Event()
        deadline.wait(0.5)  # let the slept handler threads drain
        a.tick()
        assert a.status_of("wb") == ALIVE
        assert b.describe()["incarnation"] >= 1
    finally:
        node_a.stop()
        node_b.stop()


def test_membership_update_drop_healed_by_gossip_redelivery():
    """fabric.membership.update armed once: the receiver drops exactly
    one digest merge (it never learns about wc), then the next probe
    re-delivers the same rumor and it lands — gossip's at-least-once
    delivery heals a dropped update with no special-casing."""
    from banjax_tpu.fabric.membership import ALIVE

    a, node_a, b, node_b = _gossip_pair()
    try:
        a.merge([["wc", ALIVE, 0, "127.0.0.1", 9]])  # a alone knows wc
        failpoints.arm("fabric.membership.update", count=1)
        a.tick()  # b's merge of the ping digest is the one that drops
        assert b.status_of("wc") is None
        assert failpoints.fired_count("fabric.membership.update") == 1
        a.tick()  # re-delivery on the next round
        # b now knows wc (possibly already suspected: wc's address is
        # dead, so a may have started suspecting it — the point here is
        # that the dropped rumor arrived, not wc's health)
        assert b.status_of("wc") is not None
    finally:
        node_a.stop()
        node_b.stop()


def test_client_stop_event_short_circuits_retries():
    stop = threading.Event()
    stop.set()
    client = PeerClient(
        "ghost", "127.0.0.1", 1, send_timeout_ms=100, max_attempts=3,
        stop=stop, backoff=_recording_backoff([]),
    )
    with pytest.raises(PeerUnavailable):
        client.request(wire.T_PING, {})


# ---------------------------------------------------------------------------
# wire v2 transport failpoints (ISSUE 18): fabric.frame.corrupt +
# fabric.ring.stall
# ---------------------------------------------------------------------------


def _sink_node(sink):
    def h_lines(payload):
        sink.extend(payload.get("lines", []))
        ack = {"n": len(payload.get("lines", []))}
        if "seq" in payload:
            ack["seq"] = payload["seq"]
        return wire.T_ACK, ack

    def h_lines_v2(fr):
        sink.extend(fr.lines)
        return wire.T_ACK, {"seq": fr.seq, "n": len(fr.lines)}

    return FabricNode("127.0.0.1", 0, handlers={
        wire.T_LINES: h_lines, wire.T_LINES_V2: h_lines_v2,
    }).start()


@pytest.mark.parametrize("v2", [True, False])
def test_frame_corrupt_is_loud_then_retransmit_heals(caplog, v2):
    """fabric.frame.corrupt armed once: the flipped byte must fail
    decode LOUDLY on the peer (never deliver silently garbled lines),
    the node drops the connection, and the pipe's reconnect+retransmit
    lands every line anyway — in both wire encodings."""
    import logging

    from banjax_tpu.fabric.peer import LinePipe

    sink = []
    node = _sink_node(sink)
    pipe = LinePipe("p", "127.0.0.1", node.port, node_id="a",
                    send_timeout_ms=500, wire_v2=v2)
    try:
        pipe.submit(["warmup line"])     # handshake + first clean frame
        assert pipe.flush(10)
        failpoints.arm("fabric.frame.corrupt", count=1)
        groups = [[f"corrupt-run-{g}-{i}" for i in range(4)]
                  for g in range(5)]
        with caplog.at_level(logging.ERROR, logger="banjax_tpu.fabric.node"):
            for g in groups:
                pipe.submit(g)
            assert pipe.flush(20)
        assert failpoints.fired_count("fabric.frame.corrupt") == 1
        assert not pipe.dead
        # loud on the receiving side
        assert any("malformed frame" in r.message for r in caplog.records)
        # nothing garbled was ever delivered, nothing was lost
        sent = {ln for g in groups for ln in g} | {"warmup line"}
        assert sent <= set(sink)
        assert set(sink) <= sent
    finally:
        failpoints.disarm()
        pipe.close()
        node.stop()


def test_ring_stall_breaker_fast_fails_to_peer_unavailable():
    """fabric.ring.stall armed unlimited on an shm pipe: every transmit
    attempt faults at the ring, the retry budget burns down, and the
    pipe dies into PeerUnavailable — the router's takeover trigger —
    instead of wedging the routing thread behind a stuck ring."""
    from banjax_tpu.fabric.peer import LinePipe

    sink = []
    node = _sink_node(sink)
    pipe = LinePipe("p", "127.0.0.1", node.port, node_id="a",
                    send_timeout_ms=200, max_attempts=2, shm=True,
                    backoff=_recording_backoff([]))
    try:
        pipe.submit(["ring warmup"])     # rings attach on a clean send
        assert pipe.flush(10)
        assert pipe.transport == "shm"
        failpoints.arm("fabric.ring.stall")
        pipe.submit(["stalled"])
        deadline = time.monotonic() + 15
        while not pipe.dead and time.monotonic() < deadline:
            time.sleep(0.02)
        assert pipe.dead
        assert failpoints.fired_count("fabric.ring.stall") >= 1
        with pytest.raises(PeerUnavailable):
            pipe.submit(["after the breaker tripped"])
    finally:
        failpoints.disarm()
        pipe.close()
        node.stop()
