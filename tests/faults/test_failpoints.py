"""Failpoint mechanics: disarmed no-op, counted arming, spec parsing."""

import pytest

from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.failpoints import FaultInjected


@pytest.fixture(autouse=True)
def _clean():
    failpoints.disarm()
    yield
    failpoints.disarm()


def test_disarmed_is_a_noop():
    failpoints.check("never.armed")  # must not raise


def test_armed_raises_oserror_subclass():
    failpoints.arm("x", message="boom")
    with pytest.raises(FaultInjected) as ei:
        failpoints.check("x")
    assert isinstance(ei.value, OSError)  # the tailer's retry loop contract
    assert "boom" in str(ei.value)


def test_count_limits_fires_then_passes():
    failpoints.arm("x", count=2)
    for _ in range(2):
        with pytest.raises(FaultInjected):
            failpoints.check("x")
    failpoints.check("x")  # exhausted → no-op
    assert failpoints.fired_count("x") == 2
    assert not failpoints.is_armed("x")


def test_disarm_one_and_all():
    failpoints.arm("a")
    failpoints.arm("b")
    failpoints.disarm("a")
    failpoints.check("a")
    with pytest.raises(FaultInjected):
        failpoints.check("b")
    failpoints.disarm()
    failpoints.check("b")


def test_spec_parsing_good_and_bad_entries():
    failpoints.arm_from_spec(
        "one=error:2; two ;bad=mode?; worse=error:xx;=skipme"
    )
    assert failpoints.is_armed("one")
    assert failpoints.is_armed("two")  # bare name = unlimited error
    assert not failpoints.is_armed("bad")
    assert not failpoints.is_armed("worse")
