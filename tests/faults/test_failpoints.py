"""Failpoint mechanics: disarmed no-op, counted arming, spec parsing."""

import pytest

from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.failpoints import FaultInjected


@pytest.fixture(autouse=True)
def _clean():
    failpoints.disarm()
    yield
    failpoints.disarm()


def test_disarmed_is_a_noop():
    failpoints.check("never.armed")  # must not raise


def test_armed_raises_oserror_subclass():
    failpoints.arm("x", message="boom")
    with pytest.raises(FaultInjected) as ei:
        failpoints.check("x")
    assert isinstance(ei.value, OSError)  # the tailer's retry loop contract
    assert "boom" in str(ei.value)


def test_count_limits_fires_then_passes():
    failpoints.arm("x", count=2)
    for _ in range(2):
        with pytest.raises(FaultInjected):
            failpoints.check("x")
    failpoints.check("x")  # exhausted → no-op
    assert failpoints.fired_count("x") == 2
    assert not failpoints.is_armed("x")


def test_disarm_one_and_all():
    failpoints.arm("a")
    failpoints.arm("b")
    failpoints.disarm("a")
    failpoints.check("a")
    with pytest.raises(FaultInjected):
        failpoints.check("b")
    failpoints.disarm()
    failpoints.check("b")


def test_spec_parsing_good_and_bad_entries():
    failpoints.arm_from_spec(
        "one=error:2; two ;bad=mode?; worse=error:xx;=skipme"
    )
    assert failpoints.is_armed("one")
    assert failpoints.is_armed("two")  # bare name = unlimited error
    assert not failpoints.is_armed("bad")
    assert not failpoints.is_armed("worse")


def test_probability_is_seeded_and_deterministic():
    """p<1 fires from a per-failpoint seeded RNG: two armings with the
    same seed replay the same fire pattern; the count is only consumed
    on a fire."""
    def pattern():
        failpoints.arm("p.point", count=None, probability=0.5, seed=42)
        out = []
        for _ in range(32):
            try:
                failpoints.check("p.point")
                out.append(True)
            except failpoints.FaultInjected:
                out.append(False)
        failpoints.disarm("p.point")
        return out

    a, b = pattern(), pattern()
    assert a == b
    assert any(not x for x in a) and any(x for x in a)  # both outcomes


def test_probability_miss_does_not_consume_count():
    failpoints.arm("p.count", count=1, probability=0.0)
    for _ in range(10):
        failpoints.check("p.count")  # never fires, never decrements
    assert failpoints.fired_count("p.count") == 0
    assert failpoints.is_armed("p.count")
    failpoints.disarm("p.count")


def test_spec_probability_suffix_and_snapshot():
    failpoints.arm_from_spec("a.point=error:3@0.25;b.point=error")
    try:
        snap = {fp["name"]: fp for fp in failpoints.snapshot()}
        assert snap["a.point"]["probability"] == 0.25
        assert snap["a.point"]["count"] == 3
        assert snap["a.point"]["fired"] == 0
        assert snap["b.point"]["probability"] == 1.0
        assert snap["b.point"]["count"] is None
    finally:
        failpoints.disarm()
    assert failpoints.snapshot() == []


def test_known_sites_cover_the_instrumented_tree():
    import subprocess

    # every check("...") call site in the tree is a declared KNOWN_SITE
    out = subprocess.run(
        ["grep", "-rho", r'failpoints\.check("[^"]*")', "banjax_tpu/"],
        capture_output=True, text=True, cwd=str(
            __import__("pathlib").Path(__file__).resolve().parents[2]
        ),
    ).stdout
    sites = {line.split('"')[1] for line in out.splitlines()}
    assert sites, "grep found no instrumented sites"
    assert sites <= set(failpoints.KNOWN_SITES), (
        sites - set(failpoints.KNOWN_SITES)
    )
