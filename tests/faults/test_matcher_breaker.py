"""The acceptance scenario for the resilience tentpole, end to end:

with a failpoint forcing matcher device errors, the breaker trips, batches
keep flowing through the CPU reference matcher (no line errors, bans still
fire), /healthz reports the matcher DEGRADED and the metrics line carries
the breaker keys; after disarming, the half-open probe succeeds, the
breaker closes, and /healthz reports healthy again.
"""

import io
import json
import time

import pytest
import requests

from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import CLOSED, OPEN

BASE = "http://localhost:8081"


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def _lines(n, path="/blockme"):
    now = time.time()
    return [
        f"{now:.6f} 7.7.7.{i} GET example.com GET {path} HTTP/1.1 ua"
        for i in range(n)
    ]


def _healthz():
    r = requests.get(f"{BASE}/healthz", timeout=5)
    return r.status_code, r.json()


def test_breaker_trip_fallback_healthz_and_recovery(app_factory):
    app = app_factory("banjax-config-test-tpu-breaker.yaml")

    # 1. healthy device path: the TPU matcher (xla backend) serves a batch
    results = app._consume_lines(_lines(4))
    matcher = app._matcher
    assert matcher.breaker.state == CLOSED
    assert all(not r.error for r in results)
    assert all(r.rule_results and r.rule_results[0].regex_match
               for r in results)
    code, snap = _healthz()
    assert code == 200
    assert snap["status"] == "healthy"
    assert snap["components"]["matcher"]["status"] == "healthy"
    assert snap["components"]["tailer"]["status"] == "healthy"

    # 2. force device errors; threshold is 2 → two batches trip it OPEN.
    #    every batch still produces full results via the CPU reference
    #    matcher: no line errors, the block rule still matches and bans
    failpoints.arm("matcher.device")
    for _ in range(2):
        results = app._consume_lines(_lines(3))
        assert all(not r.error for r in results)
        assert all(
            r.rule_results
            and r.rule_results[0].regex_match
            and r.rule_results[0].rate_limit_result.exceeded
            for r in results
        )
    assert matcher.breaker.state == OPEN
    assert matcher.fallback_batches >= 2

    # 3. observable degradation: /healthz (200 — still serving!) and the
    #    additive metrics keys
    code, snap = _healthz()
    assert code == 200
    assert snap["status"] == "degraded"
    assert snap["components"]["matcher"]["status"] == "degraded"
    assert "breaker" in snap["components"]["matcher"]["detail"]
    line = matcher.stats.snapshot(None, matcher)
    assert line["MatcherBreakerState"] == "open"
    assert line["MatcherBreakerTrips"] >= 1
    assert line["MatcherCpuFallbackBatches"] >= 2

    # 4. while OPEN the device path is not even attempted
    fired_before = failpoints.fired_count("matcher.device")
    results = app._consume_lines(_lines(2))
    assert all(not r.error for r in results)
    assert failpoints.fired_count("matcher.device") == fired_before

    # 5. disarm + recovery window (0.05 s in the fixture): the half-open
    #    probe batch runs the device path again and closes the breaker
    failpoints.disarm("matcher.device")
    time.sleep(0.08)
    results = app._consume_lines(_lines(3))
    assert all(not r.error for r in results)
    assert matcher.breaker.state == CLOSED
    code, snap = _healthz()
    assert code == 200
    assert snap["status"] == "healthy"
    assert snap["components"]["matcher"]["status"] == "healthy"


def test_metrics_line_carries_health_keys(app_factory):
    from banjax_tpu.obs.metrics import write_metrics_line
    from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
    from banjax_tpu.decisions.rate_limit import (
        FailedChallengeRateLimitStates,
        RegexRateLimitStates,
    )

    app = app_factory("banjax-config-test-tpu-breaker.yaml")
    app._consume_lines(_lines(1))
    out = io.StringIO()
    write_metrics_line(
        out, DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(), FailedChallengeRateLimitStates(),
        health=app.health,
    )
    line = json.loads(out.getvalue())
    assert line["HealthStatus"] == "healthy"
    assert line["Health_matcher"] == "healthy"
    assert line["Health_tailer"] == "healthy"
