"""The shmstate spinlock is stealable: a SIGKILLed worker that died
holding a slot lock can no longer wedge every survivor whose probe chain
crosses that slot (ADVICE r5, medium)."""

import subprocess
import time
import types

import pytest

from banjax_tpu.native import shm

pytestmark = pytest.mark.skipif(
    not shm.available(), reason="no C compiler for native shmstate"
)

CFG = types.SimpleNamespace(
    too_many_failed_challenges_interval_seconds=10,
    too_many_failed_challenges_threshold=6,
)


def _dead_pid():
    p = subprocess.Popen(["sleep", "0"])
    p.wait()
    return p.pid


@pytest.fixture()
def table():
    t = shm.ShmFailedChallengeStates(capacity=1024)
    yield t
    t.set_steal_ns(50 * 1000 * 1000)  # restore the default for later tests
    t.close()
    t.unlink()


def test_dead_owner_lock_is_stolen_immediately(table):
    dead = _dead_pid()
    # every slot locked by the dead "worker": whatever slot the key hashes
    # to, fc_apply must steal its way through instead of spinning forever
    for i in range(table.capacity):
        table._test_lock_slot(i, dead)
    t0 = time.monotonic()
    result = table.apply("9.9.9.9", CFG)
    elapsed = time.monotonic() - t0
    # pre-fix this spun forever; dead-owner detection is immediate (well
    # under the 50 ms wall-clock steal bound)
    assert elapsed < 5.0
    assert result.match_type is not None
    # and the table still works normally afterwards
    assert table.apply("9.9.9.9", CFG).match_type is not None


def test_live_owner_lock_is_stolen_after_bounded_spin(table):
    import os

    table.set_steal_ns(2 * 1000 * 1000)  # 2 ms bound for the test
    for i in range(table.capacity):
        table._test_lock_slot(i, os.getpid())  # "live" owner: ourselves
    t0 = time.monotonic()
    result = table.apply("8.8.8.8", CFG)
    elapsed = time.monotonic() - t0
    assert result.match_type is not None
    # one probe slot needed stealing at the 2 ms bound; far under a second
    assert elapsed < 2.0


def test_lock_word_holds_owner_pid(table):
    import os

    # fc_apply locks with our pid and must fully release on the way out
    table.apply("7.7.7.7", CFG)
    owners = {table._test_slot_owner(i) for i in range(table.capacity)}
    assert owners == {0}
    # planting a tag round-trips through the test hook
    table._test_lock_slot(3, os.getpid())
    assert table._test_slot_owner(3) == os.getpid()
    table._test_lock_slot(3, 0)
