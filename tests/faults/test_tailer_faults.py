"""Tailer resilience: open failures (injected via the tailer.open
failpoint) retry with backoff and recover; a failed rotation reopen cannot
strand the follow loop."""

import os
import threading
import time

import pytest

from banjax_tpu.ingest.tailer import LogTailer
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.backoff import Backoff
from banjax_tpu.resilience.health import HealthRegistry, HealthStatus


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def _wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_open_failures_backoff_then_recover(tmp_path):
    path = tmp_path / "access.log"
    path.write_text("")  # file exists; the failpoint is the failure
    got = []
    got_any = threading.Event()

    def on_lines(batch):
        got.extend(batch)
        got_any.set()

    sleeps = []
    backoff = Backoff(base=0.25, cap=1.0, jitter=0.0,
                      sleep=lambda d: (sleeps.append(d), False)[1])
    registry = HealthRegistry()
    health = registry.register("tailer")
    failpoints.arm("tailer.open", count=3)
    tailer = LogTailer(str(path), on_lines, backoff=backoff, health=health)
    tailer.start()
    try:
        # three injected open failures → three backoff sleeps, then the
        # tailer starts (opened = past the seek-to-EOF) and reports healthy
        assert _wait_for(lambda: len(sleeps) >= 3)
        assert sleeps[:3] == [0.25, 0.5, 1.0]
        assert tailer.opened.wait(5.0)
        assert health.effective_status()[0] == HealthStatus.HEALTHY
        with open(path, "a") as f:
            f.write("hello line\n")
        assert got_any.wait(5.0)
        assert got == ["hello line"]
    finally:
        tailer.stop()


def test_failed_rotation_reopen_retries_instead_of_stranding(tmp_path):
    path = tmp_path / "access.log"
    path.write_text("")
    got = []
    batches = threading.Event()

    def on_lines(batch):
        got.extend(batch)
        batches.set()

    backoff = Backoff(base=0.01, cap=0.02, jitter=0.0)
    tailer = LogTailer(str(path), on_lines, backoff=backoff)
    tailer.start()
    try:
        # lines written before the tailer's open+seek-to-EOF would be
        # skipped by design; wait for the readiness signal first
        assert tailer.opened.wait(5.0)
        with open(path, "a") as f:
            f.write("one\n")
        assert batches.wait(5.0)

        # rotate while every reopen fails: the follow loop must fall back
        # into the retry loop (pre-resilience code died on a closed file)
        failpoints.arm("tailer.open", count=5)
        os.rename(path, tmp_path / "access.log.1")
        path.write_text("two\n")
        batches.clear()
        assert batches.wait(10.0), "tailer never recovered from rotation"
        assert got == ["one", "two"]
    finally:
        tailer.stop()
