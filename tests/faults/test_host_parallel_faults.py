"""Parallel-host-path faults: shard failures and resolve-ahead aborts.

The sharded encode pool and the depth-2 resolve-ahead drain add two new
failure boundaries; both must degrade per-batch/per-chunk, never wedge
the pool, the order turns, or the accounting invariant
(admitted == processed + shed + drain errors).
"""

import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.pipeline import scheduler as sched_mod
from banjax_tpu.resilience import failpoints
from tests.mock_banner import MockBanner

RULES_YAML = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: r1
    regex: 'GET /attack.*'
    interval: 5
    hits_per_interval: 0
"""


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


@pytest.fixture(autouse=True)
def _small_shards(monkeypatch):
    monkeypatch.setattr(sched_mod, "_MIN_SHARD_LINES", 8)


class _Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self.lines = []
        self.results = []

    def __call__(self, lines, results):
        with self._lock:
            self.lines.extend(lines)
            if results is not None:
                self.results.extend(results)


def build(device_windows=False, **cfg_overrides):
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = device_windows
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    states = RegexRateLimitStates()
    banner = MockBanner()
    m = TpuMatcher(cfg, banner, StaticDecisionLists(cfg), states)
    return m, banner


def run_stream(m, n_chunks=12, chunk=25, **sched_kw):
    now = time.time()
    sink = _Sink()
    sched = PipelineScheduler(
        lambda: m, on_results=sink, now_fn=lambda: now, **sched_kw
    )
    sched.start()
    lines = []
    for c in range(n_chunks):
        batch = [
            f"{now:.6f} 9.9.{c}.{i} GET h.com GET /attack HTTP/1.1 ua -"
            for i in range(chunk)
        ]
        lines.extend(batch)
        sched.submit(batch)
    assert sched.flush(120)
    sched.stop()
    return lines, sink, sched


def assert_accounted(sched, sink, lines):
    s = sched.stats
    assert s.admitted_lines == len(lines)
    assert s.admitted_lines == (
        s.processed_lines + s.shed_lines + s.drain_error_lines
    )
    assert len(sink.results) == s.processed_lines


def test_encode_shard_failpoint_fails_batch_not_pool(caplog):
    """A failing shard worker (pipeline.encode_shard) fails only its
    batch — which then drains GENERICALLY, losing nothing — and the pool
    keeps sharding later batches."""
    m, banner = build()
    failpoints.arm("pipeline.encode_shard", count=2)
    lines, sink, sched = run_stream(m, encode_workers=3)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)  # zero lost
    assert len(banner.regex_ban_logs) == len(lines)
    # the pool survived: with the failpoint exhausted, a second stream
    # through a fresh scheduler (same matcher) shards normally
    lines2, sink2, sched2 = run_stream(m, encode_workers=3)
    assert_accounted(sched2, sink2, lines2)
    assert sched2.stats.encode_sharded_batches > 0, (
        "pool never recovered after the shard fault"
    )


def test_encode_shard_failpoint_every_batch_still_no_loss():
    """Worst case: EVERY sharded batch loses a shard — everything drains
    generically, nothing is lost, the scheduler never wedges."""
    m, banner = build()
    failpoints.arm("pipeline.encode_shard")  # unbounded
    lines, sink, sched = run_stream(m, encode_workers=3)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)
    assert len(banner.regex_ban_logs) == len(lines)


def test_sharded_encode_with_device_windows_accounts():
    """Sharded encode feeding the fused two-phase path under churny
    small batches: accounting holds and effects all fire."""
    m, banner = build(device_windows=True)
    failpoints.arm("pipeline.encode_shard", count=1)
    lines, sink, sched = run_stream(m, encode_workers=2)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)
    assert len(banner.regex_ban_logs) == len(lines)


def test_resolve_ahead_abort_frees_turns():
    """matcher.resolve armed mid-stream under the depth-2 drain: the
    aborted chunk's lines are marked error, but its order turns are
    swept (fused_windows dead-turn sweep) so every later chunk and batch
    keeps draining — a leaked turn would hang the flush."""
    m, banner = build(
        device_windows=True,
        matcher_batch_lines=64,
        drain_resolve_depth=2,
        matcher_prefilter_cand_frac=1.0,
    )
    failpoints.arm("matcher.resolve", count=3)
    lines, sink, sched = run_stream(m, n_chunks=10, chunk=80,
                                    encode_workers=0)
    assert_accounted(sched, sink, lines)
    # aborted chunks' lines are error-marked results, not silent losses
    assert sched.stats.processed_lines == len(lines)
    n_err = sum(1 for r in sink.results if r.error)
    assert n_err > 0, "the armed resolve fault never fired"
    # every non-errored attack line still banned
    assert len(banner.regex_ban_logs) == len(lines) - n_err
    # the fused pipeline is idle: no order turn leaked
    assert m._fw_pipeline.idle()


def test_resolve_ahead_abort_then_recovery_depth2():
    """After mid-pipeline resolve aborts, the SAME matcher keeps
    committing two-phase chunks at depth 2 (turn counters advanced past
    the dead seqs)."""
    m, _ = build(
        device_windows=True,
        matcher_batch_lines=64,
        drain_resolve_depth=2,
        matcher_prefilter_cand_frac=1.0,
    )
    failpoints.arm("matcher.resolve", count=2)
    run_stream(m, n_chunks=6, chunk=80, encode_workers=0)
    before = m.pipelined_fused_chunks
    lines, sink, sched = run_stream(m, n_chunks=6, chunk=80,
                                    encode_workers=0)
    assert_accounted(sched, sink, lines)
    assert all(not r.error for r in sink.results)
    assert m.pipelined_fused_chunks > before, (
        "two-phase path did not recover after the aborts"
    )
    assert m._fw_pipeline.idle()


def test_command_flood_bounded_by_command_take_max():
    """A Kafka-style command flood takes batches of at most
    pipeline_command_take_max messages, so line batches interleave
    instead of starving behind one giant command dispatch."""
    m, _ = build()
    now = time.time()
    sink = _Sink()
    sched = PipelineScheduler(
        lambda: m, on_results=sink, now_fn=lambda: now,
        command_take_max=16,
    )
    seen_sizes = []
    handled = []
    lock = threading.Lock()

    def handler(raw):
        with lock:
            handled.append(raw)

    orig_put = sched._q_dev.put

    def spy_put(batch):
        if batch is not None and getattr(batch, "kind", None) == "cmd":
            seen_sizes.append(len(batch.lines))
        orig_put(batch)

    sched._q_dev.put = spy_put
    sched.start()
    sched.submit_commands([b"cmd%d" % i for i in range(400)], handler)
    lines = [
        f"{now:.6f} 1.1.1.{i} GET h.com GET /x HTTP/1.1 ua -"
        for i in range(50)
    ]
    sched.submit(lines)
    assert sched.flush(60)
    sched.stop()
    assert len(handled) == 400
    assert seen_sizes and max(seen_sizes) <= 16, seen_sizes
    s = sched.stats
    assert s.admitted_lines == 450
    assert s.processed_lines == 450
    assert s.command_items == 400
