"""Kafka loop resilience: reconnect-with-backoff (intervals counted via an
injected sleep), success reset, and the writer's no-report-lost contract
across a transport failure."""

import queue
import random
import threading
import time

from banjax_tpu.config.holder import ConfigHolder
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.ingest import reports
from banjax_tpu.ingest.kafka_io import (
    InMemoryTransport,
    KafkaReader,
    KafkaTransport,
    KafkaWriter,
)
from banjax_tpu.resilience.backoff import Backoff
from banjax_tpu.resilience.health import HealthRegistry, HealthStatus


class _StaticHolder:
    """ConfigHolder stand-in: a frozen config object."""

    def __init__(self, config):
        self._config = config

    def get(self):
        return self._config


def _config():
    from banjax_tpu.config.schema import config_from_yaml_text

    return config_from_yaml_text(
        "kafka_command_topic: cmd\nkafka_report_topic: rep\n"
        "expiring_decision_ttl_seconds: 10\n"
        "block_ip_ttl_seconds: 10\nblock_session_ttl_seconds: 10\n"
    )


class _ZeroRng(random.Random):
    def random(self):
        return 0.0


class FlakyReadTransport(KafkaTransport):
    """Raises on the first `fail_times` read attempts, then yields one
    command and blocks until closed."""

    def __init__(self, fail_times):
        self.fail_times = fail_times
        self.attempts = 0
        self.delivered = threading.Event()
        self._closed = threading.Event()

    def read_messages(self, config, topic, partition):
        self.attempts += 1
        if self.attempts <= self.fail_times:
            raise ConnectionError(f"broker down (attempt {self.attempts})")
        yield b'{"Name": "challenge_ip", "Value": "1.2.3.4", "host": "h"}'
        self.delivered.set()
        while not self._closed.wait(0.02):
            pass

    def close(self):
        self._closed.set()


def test_reader_reconnects_with_capped_exponential_backoff():
    sleeps = []

    def fake_sleep(delay):
        sleeps.append(delay)
        return False  # "stop not set"

    transport = FlakyReadTransport(fail_times=5)
    backoff = Backoff(base=1.0, cap=4.0, factor=2.0, jitter=0.5,
                      rng=_ZeroRng(), sleep=fake_sleep)
    registry = HealthRegistry()
    reader = KafkaReader(
        _StaticHolder(_config()), DynamicDecisionLists(start_sweeper=False),
        transport=transport, backoff=backoff,
        health=registry.register("kafka-reader"),
    )
    reader.start()
    assert transport.delivered.wait(5.0), "reader never recovered"
    # delivered fires AFTER the reader processed the message, so the
    # reset-on-success is observable before stop
    attempt_after_delivery = backoff.attempt
    status, _, _ = registry.get("kafka-reader").effective_status()
    reader.stop()

    # five failed connects → five sleeps, exponential then capped
    assert sleeps[:5] == [1.0, 2.0, 4.0, 4.0, 4.0]
    # delivery resets the backoff and reports healthy
    assert attempt_after_delivery == 0
    assert status == HealthStatus.HEALTHY


def test_reader_health_degraded_while_reconnecting():
    registry = HealthRegistry()
    backoff = Backoff(base=0.01, cap=0.01, jitter=0.0)
    reader = KafkaReader(
        _StaticHolder(_config()), DynamicDecisionLists(start_sweeper=False),
        transport=FlakyReadTransport(fail_times=10 ** 9),
        backoff=backoff, health=registry.register("kafka-reader"),
    )
    reader.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        status, detail, _ = registry.get("kafka-reader").effective_status()
        if status == HealthStatus.DEGRADED:
            break
        time.sleep(0.01)
    reader.stop()
    assert status == HealthStatus.DEGRADED
    assert "reconnecting" in detail


class FlakySendTransport(InMemoryTransport):
    """send raises `fail_times` times, then records like the in-memory
    transport."""

    def __init__(self, fail_times):
        super().__init__()
        self.fail_times = fail_times
        self.send_attempts = 0

    def send(self, config, topic, value):
        self.send_attempts += 1
        if self.send_attempts <= self.fail_times:
            raise ConnectionError("producer down")
        super().send(config, topic, value)


def test_writer_does_not_lose_inflight_report_across_send_failure():
    # drain anything earlier tests left in the module-level queue
    q = reports.get_message_queue()
    while True:
        try:
            q.get_nowait()
        except queue.Empty:
            break

    sleeps = []
    transport = FlakySendTransport(fail_times=3)
    backoff = Backoff(base=0.5, cap=2.0, jitter=0.0,
                      sleep=lambda d: (sleeps.append(d), False)[1])
    writer = KafkaWriter(_StaticHolder(_config()), transport=transport,
                         backoff=backoff)
    for i in range(3):
        q.put_nowait(f"report-{i}".encode())
    writer.start()
    deadline = time.time() + 5
    while len(transport.sent) < 3 and time.time() < deadline:
        time.sleep(0.01)
    writer.stop()

    # every report arrived exactly once, in order, despite three send
    # crashes — the dequeued message is held and retried, never dropped
    assert transport.sent == [b"report-0", b"report-1", b"report-2"]
    # the three failures each cost one reconnect sleep (0.5, 1.0, 2.0)
    assert sleeps[:3] == [0.5, 1.0, 2.0]
