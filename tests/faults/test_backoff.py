"""Backoff unit behavior: exponential growth, cap, jitter bounds, reset."""

import random
import threading

import pytest

from banjax_tpu.resilience.backoff import Backoff


class _ZeroRng(random.Random):
    """random() == 0.0 → jitter factor 1.0 (the deterministic upper edge)."""

    def random(self):
        return 0.0


def test_exponential_growth_and_cap():
    b = Backoff(base=1.0, cap=8.0, factor=2.0, jitter=0.5, rng=_ZeroRng())
    assert [b.next_delay() for _ in range(6)] == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]


def test_reset_returns_to_base():
    b = Backoff(base=1.0, cap=30.0, factor=2.0, jitter=0.5, rng=_ZeroRng())
    b.next_delay()
    b.next_delay()
    b.reset()
    assert b.next_delay() == 1.0


def test_jitter_stays_in_band():
    b = Backoff(base=2.0, cap=2.0, factor=2.0, jitter=0.5,
                rng=random.Random(42))
    for _ in range(200):
        d = b.next_delay()
        # jitter factor uniform in [1 - jitter, 1]
        assert 1.0 <= d <= 2.0


def test_injected_sleep_receives_delays_and_stop_flag():
    seen = []
    b = Backoff(base=1.0, cap=4.0, jitter=0.0,
                sleep=lambda d: (seen.append(d), False)[1])
    stop = threading.Event()
    assert b.wait(stop) is False
    assert b.wait(stop) is False
    assert seen == [1.0, 2.0]


def test_bad_parameters_rejected():
    for kwargs in (
        dict(base=0),
        dict(base=2.0, cap=1.0),
        dict(factor=0.5),
        dict(jitter=1.0),
    ):
        with pytest.raises(ValueError):
            Backoff(**kwargs)
