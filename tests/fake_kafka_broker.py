"""In-process fake Kafka broker speaking the server side of the wire
protocol subset banjax_tpu.ingest.kafka_wire implements.

Two advertised-version modes exercise both client ladders:
  * "legacy": Metadata ≤1, ListOffsets ≤1, Fetch ≤2, Produce ≤2
    (message-set v1 on the wire)
  * "modern": Metadata ≤7, ListOffsets ≤4, Fetch ≤10, Produce ≤7
    (record-batch v2 — the post-KIP-896 Kafka 4.x shape)

Single node, in-memory logs, optional TLS. Requests are answered on a
thread per connection; long-poll fetches honor max_wait_ms.
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from banjax_tpu.ingest.kafka_wire import (
    _Reader,
    _decode_record_batches,
    _encode_message_set_v1,
    _encode_record_batch_v2,
    _string,
)

_MODES = {
    "legacy": {0: (0, 2), 1: (0, 2), 2: (0, 1), 3: (0, 1), 18: (0, 0)},
    "modern": {0: (3, 7), 1: (4, 10), 2: (2, 4), 3: (4, 7), 18: (0, 0)},
}


class FakeKafkaBroker:
    def __init__(self, mode: str = "modern", n_partitions: int = 1,
                 ssl_context: Optional[ssl.SSLContext] = None):
        self.mode = mode
        self.versions = _MODES[mode]
        self.n_partitions = n_partitions
        self.logs: Dict[Tuple[str, int], List[bytes]] = {}
        self._lock = threading.Lock()
        self._data_event = threading.Condition(self._lock)
        self._ssl_context = ssl_context
        self._server = socket.create_server(("127.0.0.1", 0))
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.produce_count = 0

    # -- lifecycle

    def start(self) -> "FakeKafkaBroker":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass

    def append(self, topic: str, partition: int, value: bytes) -> None:
        """Seed a message directly (as if another producer wrote it)."""
        with self._data_event:
            self.logs.setdefault((topic, partition), []).append(value)
            self._data_event.notify_all()

    def log_end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            return len(self.logs.get((topic, partition), []))

    # -- server loop

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            if self._ssl_context is not None:
                try:
                    conn = self._ssl_context.wrap_socket(conn, server_side=True)
                except ssl.SSLError:
                    conn.close()
                    continue
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                head = self._read_exact(conn, 4)
                if head is None:
                    return
                (size,) = struct.unpack(">i", head)
                payload = self._read_exact(conn, size)
                if payload is None:
                    return
                r = _Reader(payload)
                api_key, version, corr = r.i16(), r.i16(), r.i32()
                r.string()  # client_id
                body = self._dispatch(api_key, version, r)
                conn.sendall(
                    struct.pack(">i", len(body) + 4)
                    + struct.pack(">i", corr) + body
                )
        except (OSError, ValueError, ssl.SSLError):
            pass
        finally:
            conn.close()

    @staticmethod
    def _read_exact(conn, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    # -- request handlers

    def _dispatch(self, api_key: int, version: int, r: _Reader) -> bytes:
        if api_key == 18:
            return self._api_versions()
        vmin, vmax = self.versions.get(api_key, (-1, -1))
        assert vmin <= version <= vmax, (
            f"client used api {api_key} v{version}, broker advertises "
            f"[{vmin},{vmax}]"
        )
        if api_key == 3:
            return self._metadata(version, r)
        if api_key == 2:
            return self._list_offsets(version, r)
        if api_key == 1:
            return self._fetch(version, r)
        if api_key == 0:
            return self._produce(version, r)
        raise ValueError(f"unsupported api {api_key}")

    def _api_versions(self) -> bytes:
        out = struct.pack(">h", 0) + struct.pack(">i", len(self.versions))
        for key, (vmin, vmax) in sorted(self.versions.items()):
            out += struct.pack(">hhh", key, vmin, vmax)
        return out

    def _metadata(self, v: int, r: _Reader) -> bytes:
        n_topics = r.i32()
        topics = [r.string() for _ in range(n_topics)]
        out = b""
        if v >= 3:
            out += struct.pack(">i", 0)  # throttle
        out += struct.pack(">i", 1)  # one broker
        out += struct.pack(">i", 0) + _string("127.0.0.1") + struct.pack(">i", self.port)
        if v >= 1:
            out += _string(None)  # rack
        if v >= 2:
            out += _string("fake-cluster")
        out += struct.pack(">i", 0)  # controller_id
        out += struct.pack(">i", len(topics))
        for t in topics:
            out += struct.pack(">h", 0) + _string(t)
            if v >= 1:
                out += struct.pack(">b", 0)  # is_internal
            out += struct.pack(">i", self.n_partitions)
            for pid in range(self.n_partitions):
                out += struct.pack(">hii", 0, pid, 0)  # err, partition, leader
                if v >= 7:
                    out += struct.pack(">i", 0)  # leader_epoch
                out += struct.pack(">ii", 1, 0)  # replicas [0]
                out += struct.pack(">ii", 1, 0)  # isr [0]
                if v >= 5:
                    out += struct.pack(">i", 0)  # offline_replicas
        return out

    def _list_offsets(self, v: int, r: _Reader) -> bytes:
        r.i32()  # replica_id
        if v >= 2:
            r.i8()  # isolation_level
        r.i32()  # n topics (assume 1)
        topic = r.string()
        r.i32()  # n partitions (assume 1)
        partition = r.i32()
        if v >= 4:
            r.i32()  # leader_epoch
        r.i64()  # timestamp
        offset = self.log_end_offset(topic, partition)
        out = b""
        if v >= 2:
            out += struct.pack(">i", 0)  # throttle
        out += struct.pack(">i", 1) + _string(topic) + struct.pack(">i", 1)
        out += struct.pack(">ih", partition, 0)
        out += struct.pack(">qq", -1, offset)  # timestamp, offset
        if v >= 4:
            out += struct.pack(">i", 0)  # leader_epoch
        return out

    def _fetch(self, v: int, r: _Reader) -> bytes:
        r.i32()  # replica_id
        max_wait = r.i32()
        r.i32()  # min_bytes
        if v >= 3:
            r.i32()  # max_bytes
        if v >= 4:
            r.i8()
        if v >= 7:
            r.i32()
            r.i32()
        r.i32()  # n topics (assume 1)
        topic = r.string()
        r.i32()
        partition = r.i32()
        if v >= 9:
            r.i32()
        offset = r.i64()
        if v >= 5:
            r.i64()
        r.i32()  # partition max bytes

        deadline = time.time() + max_wait / 1000.0
        with self._data_event:
            while (
                len(self.logs.get((topic, partition), [])) <= offset
                and time.time() < deadline
                and not self._stop.is_set()
            ):
                self._data_event.wait(timeout=max(0.01, deadline - time.time()))
            msgs = list(self.logs.get((topic, partition), []))[offset:]

        if v >= 3:  # modern ladder stores record batches
            record_data = b"".join(
                _encode_record_batch_v2(m, 0, offset + i)
                for i, m in enumerate(msgs)
            )
        else:
            record_data = b"".join(
                _encode_message_set_v1(m, 0, offset + i)
                for i, m in enumerate(msgs)
            )
        out = struct.pack(">i", 0)  # throttle
        if v >= 7:
            out += struct.pack(">hi", 0, 0)  # error, session_id
        out += struct.pack(">i", 1) + _string(topic) + struct.pack(">i", 1)
        hw = self.log_end_offset(topic, partition)
        out += struct.pack(">ihq", partition, 0, hw)
        if v >= 4:
            out += struct.pack(">q", hw)  # last_stable_offset
            if v >= 5:
                out += struct.pack(">q", 0)  # log_start_offset
            out += struct.pack(">i", 0)  # aborted txns
        out += struct.pack(">i", len(record_data)) + record_data
        return out

    def _produce(self, v: int, r: _Reader) -> bytes:
        if v >= 3:
            r.string()  # transactional_id
        r.i16()  # acks
        r.i32()  # timeout
        r.i32()  # n topics (assume 1)
        topic = r.string()
        r.i32()
        partition = r.i32()
        record_set = r.bytes_() or b""
        values = [val for _, val in _decode_record_batches(record_set)]
        base = self.log_end_offset(topic, partition)
        with self._data_event:
            log = self.logs.setdefault((topic, partition), [])
            log.extend(values)
            self.produce_count += len(values)
            self._data_event.notify_all()
        out = struct.pack(">i", 1) + _string(topic) + struct.pack(">i", 1)
        out += struct.pack(">ihq", partition, 0, base)
        if v >= 2:
            out += struct.pack(">q", -1)  # log_append_time
        if v >= 5:
            out += struct.pack(">q", 0)  # log_start_offset
        if v >= 1:
            out += struct.pack(">i", 0)  # throttle
        return out
