"""Component health registry.

Every long-lived loop (log tailer, Kafka reader/writer, matcher runner,
device mesh, worker supervisor) registers a component and either
heartbeats it (`beat`) or sets an explicit status (`set_status`).  The
registry's `snapshot()` is the single source for the /healthz route and
the additive health keys on the 29 s metrics line.

Staleness: a component registered with `stale_after > 0` that has not
beaten within that window is reported DEGRADED (FAILED after three
windows) regardless of its last explicit status — a wedged thread that
can't even complain still shows up.

The clock is injectable so fault tests can advance time deterministically.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Dict, Optional


class HealthStatus(enum.IntEnum):
    """Ordered worst-last so aggregate status is a max()."""

    HEALTHY = 0
    DEGRADED = 1
    FAILED = 2

    def __str__(self) -> str:
        return self.name.lower()


class ComponentHealth:
    """One registered component; all methods are thread-safe and cheap
    enough for per-message call sites (a lock around a few stores)."""

    def __init__(self, name: str, stale_after: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.stale_after = stale_after
        self._clock = clock
        self._lock = threading.Lock()
        self._status = HealthStatus.HEALTHY
        self._detail = ""
        self._last_beat = clock()

    def beat(self) -> None:
        """Heartbeat: refreshes liveness without changing the status."""
        with self._lock:
            self._last_beat = self._clock()

    def set_status(self, status: HealthStatus, detail: str = "") -> None:
        with self._lock:
            self._status = HealthStatus(status)
            self._detail = detail
            self._last_beat = self._clock()

    def ok(self, detail: str = "") -> None:
        self.set_status(HealthStatus.HEALTHY, detail)

    def degraded(self, detail: str = "") -> None:
        self.set_status(HealthStatus.DEGRADED, detail)

    def failed(self, detail: str = "") -> None:
        self.set_status(HealthStatus.FAILED, detail)

    def effective_status(self) -> "tuple[HealthStatus, str, float]":
        """(status, detail, seconds_since_beat) with staleness applied."""
        with self._lock:
            status, detail = self._status, self._detail
            age = max(0.0, self._clock() - self._last_beat)
        if self.stale_after > 0 and age > self.stale_after:
            stale = (HealthStatus.FAILED if age > 3 * self.stale_after
                     else HealthStatus.DEGRADED)
            if stale > status:
                status = stale
                detail = f"no heartbeat for {age:.0f}s"
        return status, detail, age


class HealthRegistry:
    """Process-wide component table; one per BanjaxApp (not a global, so
    in-process integration tests don't cross-contaminate)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._components: Dict[str, ComponentHealth] = {}

    def register(self, name: str, stale_after: float = 0.0) -> ComponentHealth:
        """Idempotent: re-registering returns the existing component (a
        hot-reloaded matcher keeps its history)."""
        with self._lock:
            comp = self._components.get(name)
            if comp is None:
                comp = ComponentHealth(name, stale_after, self._clock)
                self._components[name] = comp
            return comp

    def get(self, name: str) -> Optional[ComponentHealth]:
        with self._lock:
            return self._components.get(name)

    def snapshot(self) -> dict:
        """JSON-ready aggregate: overall status is the worst component."""
        with self._lock:
            comps = list(self._components.values())
        overall = HealthStatus.HEALTHY
        out: Dict[str, dict] = {}
        for comp in comps:
            status, detail, age = comp.effective_status()
            overall = max(overall, status)
            entry = {"status": str(status), "age_seconds": round(age, 1)}
            if detail:
                entry["detail"] = detail
            out[comp.name] = entry
        return {"status": str(overall), "components": out}
