"""Named failpoints: deterministic fault injection.

Instrumented sites call `check("site.name")`; when the failpoint is
disarmed (production) that is one module-flag test — effectively free on
the hot path.  Armed failpoints raise `FaultInjected` (an OSError
subclass, so sites that tolerate I/O errors — the tailer's retry loop,
the kafka reconnect loop — treat an injected fault exactly like a real
one) a bounded or unbounded number of times.

Arming:
  * programmatic (tests):  failpoints.arm("matcher.device", count=3)
  * env / config:          BANJAX_FAILPOINTS="matcher.device=error:3;kafka.read=error"
    (the config key `failpoints` uses the same spec syntax)

Instrumented sites in this tree:
  kafka.read       — KafkaReader, before the transport read loop
  kafka.send       — KafkaWriter, before each transport send
  tailer.open      — LogTailer, every file open (start and rotation)
  matcher.device   — TpuMatcher, every device dispatch boundary
  decision_chain   — decision_for_nginx entry (fail-open path)
  pipeline.encode  — pipeline scheduler, encode-stage boundary (a failing
                     batch drains generically; no loss)
  pipeline.submit  — pipeline scheduler, device submit boundary (breaker
                     failure + CPU-reference drain)
  pipeline.collect — pipeline scheduler, device collect boundary (same)
  pipeline.drain   — pipeline scheduler, drain-stage boundary (the batch's
                     lines are counted as shed, never silently lost)
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

log = logging.getLogger(__name__)


class FaultInjected(OSError):
    """Raised by an armed failpoint (OSError: see module docstring)."""


class _Failpoint:
    __slots__ = ("name", "mode", "remaining", "message", "fired", "delay_s")

    def __init__(self, name: str, mode: str = "error",
                 count: Optional[int] = None, message: str = "",
                 delay_s: float = 0.0):
        self.name = name
        self.mode = mode          # "error" | "sleep"
        self.remaining = count    # None = unlimited
        self.message = message or f"failpoint {name} armed"
        self.delay_s = delay_s
        self.fired = 0


_lock = threading.Lock()
_active: Dict[str, _Failpoint] = {}
_armed = False  # the fast gate read without the lock


def check(name: str) -> None:
    """The instrumented-site call: no-op unless `name` is armed."""
    if not _armed:
        return
    with _lock:
        fp = _active.get(name)
        if fp is None:
            return
        if fp.remaining is not None:
            if fp.remaining <= 0:
                return
            fp.remaining -= 1
        fp.fired += 1
        mode, message, delay = fp.mode, fp.message, fp.delay_s
    if mode == "sleep":
        time.sleep(delay)
        return
    raise FaultInjected(message)


def arm(name: str, mode: str = "error", count: Optional[int] = None,
        message: str = "", delay_s: float = 0.0) -> None:
    global _armed
    with _lock:
        _active[name] = _Failpoint(name, mode, count, message, delay_s)
        _armed = True
    log.warning("FAILPOINT armed: %s mode=%s count=%s", name, mode, count)


def disarm(name: Optional[str] = None) -> None:
    """Disarm one failpoint, or all of them (name=None)."""
    global _armed
    with _lock:
        if name is None:
            _active.clear()
        else:
            _active.pop(name, None)
        _armed = bool(_active)


def fired_count(name: str) -> int:
    with _lock:
        fp = _active.get(name)
        return fp.fired if fp is not None else 0


def is_armed(name: str) -> bool:
    with _lock:
        fp = _active.get(name)
        return fp is not None and (fp.remaining is None or fp.remaining > 0)


def arm_from_spec(spec: str) -> None:
    """Parse "name=mode[:count][;name2=..]" (the BANJAX_FAILPOINTS / config
    syntax).  A bare "name" arms an unlimited error failpoint.  Bad entries
    are logged and skipped — a typo in a fault spec must not stop a
    production start."""
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition("=")
        name = name.strip()
        mode, count = "error", None
        if rest:
            mode, _, count_s = rest.partition(":")
            mode = mode.strip() or "error"
            if count_s:
                try:
                    count = int(count_s)
                except ValueError:
                    log.warning("FAILPOINT: bad count in spec entry %r", entry)
                    continue
        if mode not in ("error", "sleep"):
            log.warning("FAILPOINT: unknown mode in spec entry %r", entry)
            continue
        arm(name, mode=mode, count=count)


def _load_env() -> None:
    spec = os.environ.get("BANJAX_FAILPOINTS", "")
    if spec:
        arm_from_spec(spec)


_load_env()
