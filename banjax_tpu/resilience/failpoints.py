"""Named failpoints: deterministic fault injection.

Instrumented sites call `check("site.name")`; when the failpoint is
disarmed (production) that is one module-flag test — effectively free on
the hot path.  Armed failpoints raise `FaultInjected` (an OSError
subclass, so sites that tolerate I/O errors — the tailer's retry loop,
the kafka reconnect loop — treat an injected fault exactly like a real
one) a bounded or unbounded number of times.

Arming:
  * programmatic (tests):  failpoints.arm("matcher.device", count=3)
  * env / config:          BANJAX_FAILPOINTS="matcher.device=error:3;kafka.read=error"
    (the config key `failpoints` uses the same spec syntax; an optional
    "@p" suffix on an entry — "matcher.device=error:3@0.5" — fires it
    with probability p per check, from a seeded per-failpoint RNG so a
    given arming is reproducible)
  * admin surface:         GET/POST /debug/failpoints (httpapi/server.py)
    lists armed points and arms/disarms them at runtime — the chaos-soak
    and operator path that needs no env restart

Instrumented sites in this tree (KNOWN_SITES):
  kafka.read       — KafkaReader, before the transport read loop
  kafka.send       — KafkaWriter, before each transport send
  tailer.open      — LogTailer, every file open (start and rotation)
  matcher.device   — TpuMatcher, every device dispatch boundary
  matcher.resolve  — fused two-phase resolve (turn-release abort path)
  decision_chain   — decision_for_nginx entry (fail-open path)
  pipeline.encode  — pipeline scheduler, encode-stage boundary (a failing
                     batch drains generically; no loss)
  pipeline.encode_shard — one shard of the sharded encode fan-out
  pipeline.submit  — pipeline scheduler, device submit boundary (breaker
                     failure + CPU-reference drain)
  pipeline.collect — pipeline scheduler, device collect boundary (same)
  pipeline.drain   — pipeline scheduler, drain-stage boundary (the batch's
                     lines are counted as shed, never silently lost)
  fabric.send      — fabric PeerClient, before every peer send attempt
                     (retried on the shared reconnect backoff; exhausting
                     the budget raises PeerUnavailable -> takeover)
  fabric.recv      — fabric node frame-read path (an injected fault drops
                     the connection like a torn network)
  fabric.takeover  — fabric router takeover entry (the takeover completes
                     anyway; the episode is visible in snapshot())
  fabric.gossip.ping — membership probe send path (an injected fault makes
                     every outgoing probe fail: the node goes deaf and its
                     peers' indirect probes decide the outcome)
  fabric.gossip.ack — membership probe answer path; arm with mode=sleep to
                     fake a slow-but-alive node and drive the
                     suspect -> refute cycle
  fabric.membership.update — before merging a received membership digest
                     (an injected fault drops that one update; gossip
                     re-delivers on a later frame)
  challenge.issue  — stateless issuer entry, before every cookie mint (a
                     fault propagates to the recovery middleware's
                     fail-open path — challenge issuance must never
                     wedge the worker)
  challenge.verify — sha-inv verification entry in the decision chain
                     (same fail-open contract as challenge.issue)
  challenge.device_verify — inside the device micro-batch dispatch: an
                     injected fault is swallowed by the verifier, counts
                     toward its breaker, and the caller re-verifies on
                     the CPU oracle — accept/reject decisions are
                     byte-identical across the drill
  serve.fastpath.lookup — compiled /auth_request fast path, before the
                     decision-table probe (httpapi/fastpath.py): an
                     injected fault counts as a fast-path fault and the
                     request falls open to the full decision chain —
                     responses stay byte-identical under the drill
  ipset.netlink.send — netlink batch writer, before every coalesced
                     sendmsg (effectors/ipset_netlink.py): an injected
                     fault routes the whole batch to the per-entry
                     subprocess fallback — no ban is lost
  obs.fleet.pull   — federated metrics fan-out, before each per-peer
                     T_STATS pull (obs/fleet.py FleetScraper): an
                     injected fault degrades that peer to its cached
                     snapshot (flagged stale) or drops it (flagged
                     unreachable) — /metrics?fleet=1 stays a 200
  obs.fleet.capture — cluster incident fan-out, before each per-peer
                     T_FLIGHTREC exchange (obs/fleet.py capture_fleet):
                     an injected fault turns that peer's bundle tree
                     into an error.txt — the local capture still lands
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

# the instrumented sites (module docstring) — served by /debug/failpoints
# so operators and the scenario harness discover what they can arm
KNOWN_SITES = (
    "kafka.read",
    "kafka.send",
    "tailer.open",
    "matcher.device",
    "matcher.resolve",
    "decision_chain",
    "pipeline.encode",
    "pipeline.encode_shard",
    "pipeline.submit",
    "pipeline.collect",
    "pipeline.drain",
    "fabric.send",
    "fabric.recv",
    "fabric.takeover",
    "fabric.frame.corrupt",
    "fabric.ring.stall",
    "fabric.gossip.ping",
    "fabric.gossip.ack",
    "fabric.membership.update",
    "challenge.issue",
    "challenge.verify",
    "challenge.device_verify",
    "serve.fastpath.lookup",
    "ipset.netlink.send",
    "obs.fleet.pull",
    "obs.fleet.capture",
)

MODES = ("error", "sleep")


class FaultInjected(OSError):
    """Raised by an armed failpoint (OSError: see module docstring)."""


class _Failpoint:
    __slots__ = ("name", "mode", "remaining", "message", "fired", "delay_s",
                 "probability", "rng")

    def __init__(self, name: str, mode: str = "error",
                 count: Optional[int] = None, message: str = "",
                 delay_s: float = 0.0, probability: float = 1.0,
                 seed: Optional[int] = None):
        self.name = name
        self.mode = mode          # "error" | "sleep"
        self.remaining = count    # None = unlimited
        self.message = message or f"failpoint {name} armed"
        self.delay_s = delay_s
        # probabilistic arming (chaos soak): each check() fires with this
        # probability, drawn from a PER-FAILPOINT seeded RNG — the default
        # seed derives from the name, so a given arming replays the same
        # fire pattern run to run
        self.probability = min(1.0, max(0.0, float(probability)))
        self.rng = random.Random(
            zlib.crc32(name.encode()) if seed is None else seed
        )
        self.fired = 0


_lock = threading.Lock()
_active: Dict[str, _Failpoint] = {}
_armed = False  # the fast gate read without the lock


def check(name: str) -> None:
    """The instrumented-site call: no-op unless `name` is armed."""
    if not _armed:
        return
    with _lock:
        fp = _active.get(name)
        if fp is None:
            return
        if fp.remaining is not None and fp.remaining <= 0:
            return
        if fp.probability < 1.0 and fp.rng.random() >= fp.probability:
            return  # probabilistic miss: count NOT consumed
        if fp.remaining is not None:
            fp.remaining -= 1
        fp.fired += 1
        mode, message, delay = fp.mode, fp.message, fp.delay_s
    if mode == "sleep":
        time.sleep(delay)
        return
    raise FaultInjected(message)


def arm(name: str, mode: str = "error", count: Optional[int] = None,
        message: str = "", delay_s: float = 0.0, probability: float = 1.0,
        seed: Optional[int] = None) -> None:
    global _armed
    with _lock:
        _active[name] = _Failpoint(name, mode, count, message, delay_s,
                                   probability, seed)
        _armed = True
    log.warning("FAILPOINT armed: %s mode=%s count=%s p=%s",
                name, mode, count, probability)


def disarm(name: Optional[str] = None) -> None:
    """Disarm one failpoint, or all of them (name=None)."""
    global _armed
    with _lock:
        if name is None:
            _active.clear()
        else:
            _active.pop(name, None)
        _armed = bool(_active)


def fired_count(name: str) -> int:
    with _lock:
        fp = _active.get(name)
        return fp.fired if fp is not None else 0


def is_armed(name: str) -> bool:
    with _lock:
        fp = _active.get(name)
        return fp is not None and (fp.remaining is None or fp.remaining > 0)


def snapshot() -> List[dict]:
    """JSON-ready view of every armed failpoint — the GET
    /debug/failpoints payload and the chaos soak's episode evidence."""
    with _lock:
        return [
            {
                "name": fp.name,
                "mode": fp.mode,
                "count": fp.remaining,   # None = unlimited
                "fired": fp.fired,
                "probability": fp.probability,
                "delay_s": fp.delay_s,
            }
            for fp in _active.values()
        ]


def arm_from_spec(spec: str) -> None:
    """Parse "name=mode[:count][@p][;name2=..]" (the BANJAX_FAILPOINTS /
    config / POST /debug/failpoints spec syntax).  A bare "name" arms an
    unlimited error failpoint; "@p" fires with probability p per check.
    Bad entries are logged and skipped — a typo in a fault spec must not
    stop a production start."""
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition("=")
        name = name.strip()
        mode, count, probability = "error", None, 1.0
        if rest:
            rest, _, prob_s = rest.partition("@")
            if prob_s:
                try:
                    probability = float(prob_s)
                except ValueError:
                    log.warning(
                        "FAILPOINT: bad probability in spec entry %r", entry
                    )
                    continue
            mode, _, count_s = rest.partition(":")
            mode = mode.strip() or "error"
            if count_s:
                try:
                    count = int(count_s)
                except ValueError:
                    log.warning("FAILPOINT: bad count in spec entry %r", entry)
                    continue
        if mode not in MODES:
            log.warning("FAILPOINT: unknown mode in spec entry %r", entry)
            continue
        arm(name, mode=mode, count=count, probability=probability)


def _load_env() -> None:
    spec = os.environ.get("BANJAX_FAILPOINTS", "")
    if spec:
        arm_from_spec(spec)


_load_env()
