"""Failure-handling layer: health registry, backoff, circuit breaker,
deterministic fault injection.

The reference is one Go process whose goroutines are restarted by a
supervisor; wedged components surface as crashed goroutines.  This port
runs long-lived Python threads and device dispatches instead, so failure
handling is explicit:

  * `health`     — every long-lived loop registers a component and
                   heartbeats it; /healthz and the 29 s metrics line
                   surface the aggregate;
  * `backoff`    — capped exponential backoff with jitter for every
                   reconnect loop (replaces the fixed 5 s sleeps);
  * `breaker`    — a circuit breaker around the TPU matcher batch path
                   (device failures route batches to the CPU reference
                   matcher until a half-open probe succeeds);
  * `failpoints` — named, deterministic fault injection (no-op unless
                   armed via config/env), exercised by tests/faults/.
"""

from banjax_tpu.resilience.backoff import Backoff
from banjax_tpu.resilience.breaker import CircuitBreaker
from banjax_tpu.resilience.health import (
    ComponentHealth,
    HealthRegistry,
    HealthStatus,
)

__all__ = [
    "Backoff",
    "CircuitBreaker",
    "ComponentHealth",
    "HealthRegistry",
    "HealthStatus",
]
