"""Capped exponential backoff with jitter.

Replaces the fixed `RETRY_SECONDS`/`RECONNECT_SECONDS` sleeps of the
ingest loops (kafka.go:169 / regex_rate_limiter.go:47 retried on a flat
5 s clock): a dead broker shared by a fleet of banjax edges would get a
synchronized reconnect stampede every 5 s, and a transient blip would
still wait the full period.  Delays grow `base * factor**attempt` up to
`cap`, each multiplied by a jitter factor drawn uniformly from
`[1 - jitter, 1]`, and `reset()` returns to `base` after sustained
success.

Both the RNG and the sleep are injectable so fault tests can count exact
intervals without real sleeping.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Optional


def reconnect_backoff(
    cap: float = 30.0,
    base: float = 0.5,
    rng: Optional[random.Random] = None,
    sleep: Optional[Callable[[float], bool]] = None,
) -> "Backoff":
    """The ONE reconnect policy every retry loop shares — kafka reader/
    writer, log tailer, fabric peer sockets.  Half-jittered exponential
    from `base` to `cap`; callers tune only the cap (how stale a dead
    endpoint may go) so a fleet never synchronizes its reconnects the
    way the reference's flat 5 s clocks (kafka.go:169,
    regex_rate_limiter.go:47) would."""
    return Backoff(base=base, cap=cap, factor=2.0, jitter=0.5,
                   rng=rng, sleep=sleep)


class Backoff:
    """Per-loop backoff state (not thread-safe across loops: each
    reconnect loop owns its own instance)."""

    def __init__(
        self,
        base: float = 0.5,
        cap: float = 30.0,
        factor: float = 2.0,
        jitter: float = 0.5,
        rng: Optional[random.Random] = None,
        sleep: Optional[Callable[[float], bool]] = None,
    ):
        if base <= 0 or cap < base or factor < 1 or not 0 <= jitter < 1:
            raise ValueError(
                f"bad backoff parameters base={base} cap={cap} "
                f"factor={factor} jitter={jitter}"
            )
        self.base = base
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._sleep = sleep  # tests: records the delay, returns stop flag
        self.attempt = 0

    def next_delay(self) -> float:
        """The next jittered delay; advances the attempt counter."""
        raw = min(self.cap, self.base * (self.factor ** self.attempt))
        self.attempt += 1
        if self.jitter:
            raw *= 1.0 - self.jitter * self._rng.random()
        return raw

    def reset(self) -> None:
        self.attempt = 0

    def wait(self, stop: threading.Event) -> bool:
        """Sleep the next delay; True means `stop` fired (caller exits).
        An injected `sleep` callable replaces the event wait (but an
        already-set stop still short-circuits, so shutdown never burns an
        attempt or a fake sleep)."""
        if stop.is_set():
            return True
        delay = self.next_delay()
        if self._sleep is not None:
            return bool(self._sleep(delay)) or stop.is_set()
        return stop.wait(delay)
