"""Circuit breaker for the TPU matcher batch path.

States: CLOSED (device path runs), OPEN (every batch routes straight to
the CPU reference matcher), HALF_OPEN (one probe batch is allowed through
the device path; success closes the breaker, failure re-opens it).

Trips after `failure_threshold` consecutive failures — a device dispatch
raising, or a batch breaching the latency budget — so a wedged TPU
degrades throughput instead of dropping log lines.  The clock is
injectable for deterministic recovery tests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Thread-safe; `allow()` + `record_success()`/`record_failure()`
    bracket each protected call."""

    def __init__(
        self,
        failure_threshold: int = 3,
        recovery_seconds: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
        on_trip: Optional[Callable[[str], None]] = None,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        self.failure_threshold = failure_threshold
        self.recovery_seconds = recovery_seconds
        self.name = name
        self._clock = clock
        self._on_trip = on_trip
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.trip_count = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """True: caller may take the protected (device) path. False:
        caller must use the fallback."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.recovery_seconds:
                    self._state = HALF_OPEN
                    self._probe_in_flight = True
                    return True
                return False
            # HALF_OPEN: exactly one probe at a time
            if not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            self._state = CLOSED

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                self._state = OPEN
                self._opened_at = self._clock()
                self.trip_count += 1
                tripped = True
            else:
                self._failures += 1
                if self._state == CLOSED and self._failures >= self.failure_threshold:
                    self._state = OPEN
                    self._opened_at = self._clock()
                    self.trip_count += 1
                    tripped = True
        if tripped and self._on_trip is not None:
            self._on_trip(self.name)
