"""Circuit breaker for the TPU matcher batch path.

States: CLOSED (device path runs), OPEN (every batch routes straight to
the CPU reference matcher), HALF_OPEN (one probe batch is allowed through
the device path; success closes the breaker, failure re-opens it).

Trips after `failure_threshold` consecutive failures — a device dispatch
raising, or a batch breaching the latency budget — so a wedged TPU
degrades throughput instead of dropping log lines.  The clock is
injectable for deterministic recovery tests.

Optionally (`window_size > 0`) a rolling failure-rate window runs
alongside the consecutive counter: the breaker also trips when
`failure_threshold` failures land within the last `window_size`
recorded outcomes, even when successes are interleaved — the flapping-
device mode a consecutive counter never catches (ROADMAP breaker-tuning
item).  The window clears on every trip so a recovered breaker starts
from a clean history.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Thread-safe; `allow()` + `record_success()`/`record_failure()`
    bracket each protected call."""

    def __init__(
        self,
        failure_threshold: int = 3,
        recovery_seconds: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
        on_trip: Optional[Callable[[str], None]] = None,
        window_size: int = 0,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        if window_size < 0:
            raise ValueError(f"window_size must be >= 0, got {window_size}")
        self.failure_threshold = failure_threshold
        self.recovery_seconds = recovery_seconds
        self.window_size = window_size
        self.name = name
        self._clock = clock
        self._on_trip = on_trip
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        # rolling outcome window (True = failure); None when disabled
        self._window: Optional[deque] = (
            deque(maxlen=window_size) if window_size > 0 else None
        )
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.trip_count = 0
        # cumulative seconds spent OPEN (closed intervals only; the
        # current open stretch is added at read time) — the SLO engine's
        # breaker-open burn-rate source (obs/slo.py)
        self._open_seconds_accum = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def open_seconds_total(self) -> float:
        """Cumulative wall seconds the breaker has been OPEN, including
        the in-progress stretch — monotone, safe for windowed deltas."""
        with self._lock:
            total = self._open_seconds_accum
            if self._state == OPEN:
                total += max(0.0, self._clock() - self._opened_at)
            return total

    def allow(self) -> bool:
        """True: caller may take the protected (device) path. False:
        caller must use the fallback."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.recovery_seconds:
                    self._open_seconds_accum += max(
                        0.0, self._clock() - self._opened_at
                    )
                    self._state = HALF_OPEN
                    self._probe_in_flight = True
                    return True
                return False
            # HALF_OPEN: exactly one probe at a time
            if not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == OPEN:
                # a straggler success landing while OPEN closes the
                # breaker; bank the open stretch before leaving the state
                self._open_seconds_accum += max(
                    0.0, self._clock() - self._opened_at
                )
            self._failures = 0
            self._probe_in_flight = False
            self._state = CLOSED
            if self._window is not None:
                self._window.append(False)

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                self._state = OPEN
                self._opened_at = self._clock()
                self.trip_count += 1
                tripped = True
            else:
                self._failures += 1
                if self._window is not None:
                    self._window.append(True)
                window_failures = (
                    sum(self._window) if self._window is not None else 0
                )
                if self._state == CLOSED and (
                    self._failures >= self.failure_threshold
                    or window_failures >= self.failure_threshold
                ):
                    self._state = OPEN
                    self._opened_at = self._clock()
                    self.trip_count += 1
                    tripped = True
            if tripped and self._window is not None:
                self._window.clear()
        if tripped and self._on_trip is not None:
            self._on_trip(self.name)
