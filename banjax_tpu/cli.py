"""Entry point / process supervisor.

Reference behavior: /root/reference/banjax.go:66-275 — parse the three CLI
flags, build all shared state, wire and launch the long-lived workers (HTTP
server, log tailer, Kafka reader/writer, metrics reporter, Kafka status
heartbeat), install the SIGHUP hot-reload handler, and wait for
SIGINT/SIGTERM.

The supervisor is an object (BanjaxApp) so integration tests can run the real
process in-process, the way the reference's standalone-testing tests run the
real main() in a goroutine (banjax_base_test.go:32-81).

Run:  python -m banjax_tpu.cli -config-file <path> [-standalone-testing] [-debug]
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys
import threading
import time
from typing import Optional

from banjax_tpu.config.holder import ConfigHolder
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.effectors.ipset import init_ipset
from banjax_tpu.httpapi.server import ServerDeps, run_http_server
from banjax_tpu.ingest.kafka_io import KafkaReader, KafkaWriter
from banjax_tpu.ingest.reports import report_status_message
from banjax_tpu.ingest.tailer import LogTailer
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.obs import fleet as fleet_mod
from banjax_tpu.obs import flightrec as flightrec_mod
from banjax_tpu.obs import provenance, trace
from banjax_tpu.obs.metrics import MetricsReporter
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.health import HealthRegistry

log = logging.getLogger(__name__)

KAFKA_STATUS_INTERVAL_SECONDS = 19  # banjax.go:204


def build_matcher(config, banner, static_lists, regex_states, health=None):
    """The Matcher seam flag (BASELINE.json): cpu (default) or tpu."""
    if config.matcher == "tpu":
        from banjax_tpu.matcher.runner import TpuMatcher

        return TpuMatcher(config, banner, static_lists, regex_states,
                          health=health)
    if health is not None:
        # the CPU matcher has no device to fail; register it so /healthz
        # still lists the component
        health.register("matcher")
    return CpuMatcher(config, banner, static_lists, regex_states)


class RegexStatesView:
    """Introspection facade: when the TPU matcher runs device-resident
    windows (matcher/windows.py), /rate_limit_states and the metrics
    reporter must read those counters, not the bypassed host dict."""

    def __init__(self, app: "BanjaxApp"):
        self._app = app

    def _target(self):
        dw = getattr(self._app._matcher, "device_windows", None)
        return dw if dw is not None else self._app.regex_states

    def format_states(self) -> str:
        return self._target().format_states()

    def get(self, ip):
        return self._target().get(ip)

    def __len__(self) -> int:
        return len(self._target())


class BanjaxApp:
    """Builds all state and owns the worker lifecycle (banjax.go main)."""

    def __init__(self, config_file: str, standalone_testing: bool = False,
                 debug: bool = False):
        log.info("INIT: config file: %s", config_file)
        self.config_holder = ConfigHolder(config_file, standalone_testing, debug)
        config = self.config_holder.get()

        # component health registry (resilience/health.py): every long-
        # lived loop below registers itself; /healthz and the metrics line
        # read the aggregate.  Per-app (not global) so in-process tests
        # don't cross-contaminate.
        self.health = HealthRegistry()
        self._failpoints_spec = getattr(config, "failpoints", "")
        if self._failpoints_spec:
            failpoints.arm_from_spec(self._failpoints_spec)

        # pipeline span tracing (obs/trace.py): off by default — the
        # disabled tracer's no-op fast path keeps the hot path at ≤1%
        # overhead (bench.py --trace-overhead); /debug/trace dumps the
        # ring as Perfetto-loadable Chrome trace JSON when enabled
        trace.configure(
            enabled=getattr(config, "trace_enabled", False),
            ring_size=getattr(config, "trace_ring_size", 4096),
            jax_annotations=getattr(config, "trace_jax_annotations", False),
        )

        # decision provenance ledger (obs/provenance.py): on by default —
        # records fire per decision event, not per log line, and
        # /decisions/explain answers "why is this IP banned?"
        provenance.configure(
            enabled=getattr(config, "provenance_enabled", True),
            ring_size=getattr(config, "provenance_ring_size", 2048),
        )

        self.regex_states = RegexRateLimitStates()
        self._supervisor = None  # multi-worker serving (httpapi/workers.py)
        n_http_workers = config.http_workers
        if n_http_workers == -1:  # auto: one worker per extra core
            n_http_workers = max(0, (os.cpu_count() or 1) - 1)
        elif n_http_workers < -1:
            log.warning(
                "http_workers=%d is out of range (only -1 means auto); "
                "serving single-process", n_http_workers,
            )
            n_http_workers = 0
        if n_http_workers > 0:
            from banjax_tpu.native import shm as native_shm

            if native_shm.available():
                self.failed_challenge_states = native_shm.ShmFailedChallengeStates()
            else:
                log.warning(
                    "http_workers=%d but native shmstate is unavailable "
                    "(no C compiler?); serving single-process", n_http_workers
                )
                n_http_workers = 0
        self._n_http_workers = n_http_workers
        if n_http_workers == 0:
            # bounded when challenge_failure_state_max is set (the shm
            # variant above carries its own fixed-slot bound + dropped
            # counter, so the python LRU/spill tiering is single-process)
            from banjax_tpu.challenge.failures import (
                make_failed_challenge_states,
            )

            self.failed_challenge_states = make_failed_challenge_states(
                config
            )
        # device-batched PoW verification (challenge/verifier.py):
        # None = pure-CPU reference path, decisions identical either way
        from banjax_tpu.challenge import verifier as challenge_verifier_mod

        self.challenge_verifier = challenge_verifier_mod.from_config(config)
        self.protected_paths = PasswordProtectedPaths(config)
        self.static_lists = StaticDecisionLists(config)
        if n_http_workers > 0:
            from banjax_tpu.httpapi.workers import ReplicatedDynamicLists

            self.dynamic_lists = ReplicatedDynamicLists()
        else:
            self.dynamic_lists = DynamicDecisionLists()

        # compiled serving fast path (httpapi/fastpath.py): the dynamic
        # lists mirror every insert/expiry into this table; fastserve
        # consults it before the chain.  Worker mode needs the shm-backed
        # native table (workers attach by name); without the native
        # toolchain workers just serve via the chain — never a Py table
        # only the primary could see.
        self.decision_table = None
        if getattr(config, "serve_fastpath_enabled", True):
            from banjax_tpu.native import decisiontable

            cap = getattr(config, "serve_decision_table_capacity", 65536)
            try:
                if n_http_workers > 0:
                    if decisiontable.available():
                        self.decision_table = decisiontable.ShmDecisionTable(
                            capacity=cap
                        )
                else:
                    self.decision_table = decisiontable.create_decision_table(
                        capacity=cap
                    )
            except Exception:  # noqa: BLE001 — fast path off, chain serves
                log.exception("decision table unavailable; serving via chain")
                self.decision_table = None
            if self.decision_table is not None:
                self.dynamic_lists.set_mirror(self.decision_table)

        # ban log files (banjax.go:124-138)
        self._banning_log_file = open(config.banning_log_file, "a", encoding="utf-8")
        temp_path = config.banning_log_file_temp or f"{config.banning_log_file}.tmp"
        self._banning_log_file_temp = open(temp_path, "a", encoding="utf-8")

        ipset_instance = init_ipset(
            config.iptables_ban_seconds, config.standalone_testing
        )
        # netlink-batched kernel edge (effectors/ipset_netlink.py): bans
        # coalesce into batched AF_NETLINK sends; the subprocess shim
        # stays as the in-writer fallback and the admin read path
        self.ipset_writer = None
        if ipset_instance is not None and getattr(
            config, "ipset_netlink_enabled", True
        ):
            from banjax_tpu.effectors.ipset_netlink import IpsetBatchWriter

            self.ipset_writer = IpsetBatchWriter(ipset_instance)
        self.banner = Banner(
            decision_lists=self.dynamic_lists,
            ban_log_file=self._banning_log_file,
            ban_log_file_temp=self._banning_log_file_temp,
            ipset_instance=ipset_instance,
            netlink_writer=self.ipset_writer,
        )

        self._matcher = None
        self._matcher_generation = -1
        # streaming pipeline scheduler (banjax_tpu/pipeline/): sits between
        # the tailer and the matcher when enabled — overlapped stages,
        # adaptive batch sizing, bounded backpressure, drain-time staleness.
        # Disabled: _consume_lines keeps the reference-shaped synchronous
        # per-batch path.
        self.pipeline = None
        if getattr(config, "pipeline_enabled", False):
            from banjax_tpu.pipeline import PipelineScheduler

            self.pipeline = PipelineScheduler.from_config(
                matcher_getter=lambda: self._current_matcher()[1],
                config=config,
                health=self.health.register("pipeline"),
            )
        self.tailer = LogTailer(
            config.server_log_file, self._consume_lines,
            health=self.health.register("tailer", stale_after=60.0),
        )

        # multi-host decision fabric (banjax_tpu/fabric/): shard the IP
        # keyspace across N banjax processes — this process keeps only
        # its hash range, forwards the rest over peer sockets, and
        # replicates every decision through the Kafka command path.
        # The banner wrap must happen BEFORE the first matcher build so
        # device decisions fan out from day one.
        self.fabric = None
        if getattr(config, "fabric_enabled", False):
            from banjax_tpu.fabric.service import FabricService
            from banjax_tpu.ingest.kafka_io import handle_command

            self.fabric = FabricService(
                config,
                local_submit=self._fabric_local_submit,
                apply_command=lambda cmd: handle_command(
                    self.config_holder.get(), cmd, self.dynamic_lists
                ),
                health=self.health,
                # fleet observability seams (obs/fleet.py): peers pull
                # this node's metrics over T_STATS, ask it to explain
                # over T_EXPLAIN, and capture it over T_FLIGHTREC
                metrics_text_fn=self._render_metrics_text,
                explain_fn=self._explain_local,
                health_bits_fn=lambda: fleet_mod.compute_health_bits(
                    slo=getattr(self, "slo", None),
                    matcher=getattr(self, "_matcher", None),
                ),
            )
            self.banner = self.fabric.wrap_banner(self.banner)
            # forwarded-line bans resolve (origin_node, origin_trace_id)
            # at record time: the origin index is fed by the owner-side
            # drain of every forwarded chunk
            provenance.set_origin_resolver(
                fleet_mod.get_origin_index().resolve
            )

        # federated /metrics?fleet=1 (obs/fleet.py FleetScraper): one
        # merged exposition across every ALIVE member, instance-labeled —
        # needs the fabric (its peer wire carries the T_STATS pulls)
        self.fleet_scraper = None
        if self.fabric is not None and getattr(
            config, "fleet_metrics_enabled", False
        ):
            from banjax_tpu.obs.fleet import FleetScraper

            self.fleet_scraper = FleetScraper(
                self.fabric.node_id,
                local_text_fn=self._render_metrics_text,
                peers_fn=self.fabric.fleet_pull_peers,
                timeout_s=getattr(
                    config, "fleet_scrape_timeout_ms", 750.0
                ) / 1000.0,
            )

        # incident flight recorder (obs/flightrec.py): armed only with a
        # flightrec_dir; installed as the module-level trigger target so
        # the breaker/scheduler/SLO hooks stay one None-check when off
        self.flightrec = None
        if getattr(config, "flightrec_dir", ""):
            from banjax_tpu.obs.flightrec import FlightRecorder

            self.flightrec = FlightRecorder(
                config.flightrec_dir,
                min_interval_s=getattr(
                    config, "flightrec_min_interval_s", 60.0
                ),
                keep=getattr(config, "flightrec_keep", 16),
                provenance_tail=getattr(
                    config, "flightrec_provenance_records", 256
                ),
                metrics_text_fn=self._render_metrics_text,
                config_hash_fn=self._config_hash,
                health=self.health,
                slo_getter=lambda: self.slo,
                traffic_fn=self._traffic_snapshot,
                fabric_fn=(
                    self._fabric_snapshot if self.fabric is not None
                    else None
                ),
                # cluster incident capture: fan T_FLIGHTREC to every
                # ALIVE peer; each contributes a peers/<node_id>/ tree
                fleet_capture_fn=(
                    (lambda incident: fleet_mod.capture_fleet(
                        incident, self.fabric.fleet_capture_peers
                    ))
                    if self.fabric is not None and getattr(
                        config, "flightrec_fleet_capture", False
                    )
                    else None
                ),
            )
            flightrec_mod.install(self.flightrec)

        # SLO burn-rate engine (obs/slo.py): evaluates 5 m / 1 h burn
        # from non-destructive peeks; a breach transition captures an
        # incident bundle (when the recorder is armed)
        self.slo = None
        if getattr(config, "slo_enabled", True):
            from banjax_tpu.obs.slo import SloEngine

            self.slo = SloEngine.from_config(
                config,
                matcher_getter=lambda: self._matcher,
                pipeline_getter=lambda: self.pipeline,
                on_breach=lambda name, burn: flightrec_mod.notify(
                    f"slo-{name}", f"burn rates {burn}"
                ),
            )

        # fleet-mode SLO: a second engine burning the CLUSTER-wide
        # admitted/shed/stale streams summed across the last federated
        # scrape (obs/fleet.py fleet_collect) — same window mechanics,
        # merged denominators
        self.fleet_slo = None
        if self.fleet_scraper is not None and getattr(
            config, "slo_enabled", True
        ):
            from banjax_tpu.obs.slo import SloEngine

            self.fleet_slo = SloEngine(
                collect_fn=self.fleet_scraper.fleet_collect,
                on_breach=lambda name, burn: flightrec_mod.notify(
                    f"fleet-slo-{name}", f"fleet burn rates {burn}"
                ),
            )

        self.kafka_reader: Optional[KafkaReader] = None
        self.kafka_writer: Optional[KafkaWriter] = None

        metrics_path = (
            "list-metrics.log" if config.standalone_testing else config.metrics_log_file
        )
        self.metrics = MetricsReporter(
            metrics_path, self.dynamic_lists, RegexStatesView(self),
            self.failed_challenge_states,
            matcher_getter=lambda: self._matcher,
            supervisor_getter=lambda: self._supervisor,
            health=self.health,
            pipeline_getter=lambda: self.pipeline,
            fabric_getter=lambda: (
                self.fabric.stats if self.fabric is not None else None
            ),
        )

        gin_log_name = "gin.log" if config.standalone_testing else config.gin_log_file
        self._gin_log_file = None
        if gin_log_name and gin_log_name != "-":
            # truncate on start (the reference's os.Create), then APPEND:
            # in multi-worker mode the workers append to the same file, and
            # a mode-"w" primary would overwrite their lines at its private
            # offset
            open(gin_log_name, "w", encoding="utf-8").close()
            self._gin_log_file = open(gin_log_name, "a", encoding="utf-8")

        self._server_log_file = None
        if config.standalone_testing:
            self._server_log_file = open(config.server_log_file, "a", encoding="utf-8")

        self._stop_event = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._async_stop: Optional[asyncio.Event] = None
        self._server_thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    # --- the SIGHUP body (banjax.go:101-117) ---
    def reload(self) -> None:
        log.info("HOT-RELOAD: reloading config")
        try:
            self.config_holder.reload()
        except Exception as e:  # noqa: BLE001 — keep serving on a bad reload
            log.error("failed to reload config: %s", e)
            return
        new_config = self.config_holder.get()
        self.static_lists.update_from_config(new_config)
        self.dynamic_lists.clear()
        self.protected_paths.update_from_config(new_config)
        # re-apply the fault-injection spec only when it CHANGED: a
        # reload for unrelated keys must not clobber points armed at
        # runtime via /debug/failpoints
        new_spec = getattr(new_config, "failpoints", "")
        if new_spec != self._failpoints_spec:
            failpoints.disarm()
            if new_spec:
                failpoints.arm_from_spec(new_spec)
            self._failpoints_spec = new_spec
        if self._supervisor is not None:
            self._supervisor.broadcast_reload()

    def _render_metrics_text(self) -> str:
        """Full /metrics text for incident bundles — the same render the
        route serves, from the same non-destructive views."""
        from banjax_tpu.obs.exposition import render_prometheus

        return render_prometheus(
            self.dynamic_lists, RegexStatesView(self),
            self.failed_challenge_states, matcher=self._matcher,
            pipeline=self.pipeline, health=self.health,
            supervisor=self._supervisor, slo=self.slo,
            flightrec=self.flightrec,
            fabric=self.fabric.stats if self.fabric is not None else None,
        )

    def _fabric_snapshot(self):
        """fabric.json for incident bundles: peer table, hash-range
        ownership, last takeover — a shard-failure capture is
        self-describing without asking the survivors."""
        if self.fabric is None:
            return {"enabled": False}
        return self.fabric.describe()

    def _explain_local(self, ip: str) -> dict:
        """This node's /decisions/explain payload — served locally AND
        over the peer wire (T_EXPLAIN) when another shard proxies an
        explain for an IP this shard owns."""
        ledger = provenance.get_ledger()
        active = None
        peek = getattr(self.dynamic_lists, "peek", None)
        if peek is not None:
            ed = peek(ip)
            if ed is not None:
                active = {
                    "decision": str(ed.decision),
                    "expires": ed.expires,
                    "domain": ed.domain,
                    "from_baskerville": ed.from_baskerville,
                }
        return {
            "ip": ip,
            "ledger_enabled": ledger.enabled,
            "records": ledger.explain(ip),
            "active_decision": active,
        }

    def _fabric_local_submit(self, lines, t_read=None, hop="local") -> int:
        """The single-process consume path — what the fabric router
        calls for lines THIS shard owns (and what every line takes when
        the fabric is off).  `t_read`/`hop` thread the tailer-read stamp
        through to the e2e latency histogram (local vs fabric hop)."""
        if self.pipeline is not None:
            # asynchronous: results surface through the pipeline's drain
            # stage; submit() applies bounded backpressure to the tailer
            self.pipeline.submit(lines, t_read=t_read, hop=hop)
            return len(lines)
        cfg, matcher = self._current_matcher()
        results = matcher.consume_lines(lines)
        if cfg.debug:
            for result in results:
                log.debug("consumeLine: %s", result)
        return len(lines)

    def _traffic_snapshot(self):
        """traffic.json for incident bundles (obs/sketch.py): a forced
        sketch pull so the bundle shows the flood as of the incident."""
        sketch = getattr(self._matcher, "traffic_sketch", None)
        if sketch is None:
            return {"enabled": False}
        return sketch.incident_snapshot()

    def _config_hash(self) -> str:
        """sha256 of the on-disk config file — ties an incident bundle
        to the exact rules/limits that were live."""
        import hashlib

        try:
            with open(self.config_holder.path, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return ""

    def _current_matcher(self):
        # rebuilt on config change so rules hot-reload (regex_rate_limiter.go:59)
        cfg = self.config_holder.get()
        if self._matcher_generation != self.config_holder.generation:
            if self._matcher is not None:
                self._matcher.close()
            self._matcher = build_matcher(
                cfg, self.banner, self.static_lists, self.regex_states,
                health=self.health,
            )
            self._matcher_generation = self.config_holder.generation
        return cfg, self._matcher

    def _consume_lines(self, lines):
        # tailer-read stamp: the e2e latency histogram measures from
        # HERE to effector commit, per hop (local vs fabric)
        t_read = time.monotonic()
        if self.fabric is not None:
            # keyspace-sharded: owned lines go down the local pipeline,
            # the rest ride peer sockets to their owning shard
            self.fabric.submit(lines, t_read=t_read)
            return None
        if self.pipeline is not None:
            # asynchronous: results surface through the pipeline's drain
            # stage; submit() applies bounded backpressure to the tailer
            self.pipeline.submit(lines, t_read=t_read)
            return None
        cfg, matcher = self._current_matcher()
        results = matcher.consume_lines(lines)
        if cfg.debug:
            for result in results:
                log.debug("consumeLine: %s", result)
        return results  # the tailer ignores this; fault tests assert on it

    def start_workers(self) -> None:
        """Launch tailer, Kafka, metrics, heartbeat (not the HTTP server)."""
        config = self.config_holder.get()
        if self.fabric is not None:
            # listen before the tailer feeds: peers may already be
            # forwarding this shard's range
            self.fabric.start()
        if self.pipeline is not None:
            self.pipeline.start()
        if self.slo is not None:
            self.slo.start(getattr(config, "slo_sample_seconds", 15.0))
        if self.fleet_slo is not None:
            self.fleet_slo.start(getattr(config, "slo_sample_seconds", 15.0))
        self.tailer.start()

        # kafka→pipeline routing: command messages share the pipeline's
        # admission buffer (bounded-block/oldest-first shed, drained in
        # admission order) when the scheduler runs — ROADMAP PR 2 item
        kafka_pipeline = (
            self.pipeline
            if getattr(config, "pipeline_kafka", True) else None
        )
        if config.disable_kafka:
            log.info("INIT: not running Kafka reader/writer due to disable_kafka")
        elif config.disable_kafka_writer:
            log.info("INIT: starting Kafka reader only due to disable_kafka_writer")
            self.kafka_reader = KafkaReader(
                self.config_holder, self.dynamic_lists,
                health=self.health.register("kafka-reader"),
                pipeline=kafka_pipeline,
            )
            self.kafka_reader.start()
        else:
            log.info("INIT: starting Kafka reader/writer")
            self.kafka_reader = KafkaReader(
                self.config_holder, self.dynamic_lists,
                health=self.health.register("kafka-reader"),
                pipeline=kafka_pipeline,
            )
            self.kafka_reader.start()
            self.kafka_writer = KafkaWriter(
                self.config_holder,
                health=self.health.register("kafka-writer"),
            )
            self.kafka_writer.start()

        if self.fabric is not None and self.kafka_reader is not None:
            # fabric dedup in front of command dispatch: own-origin
            # echoes and already-seen (origin, seq) pairs are suppressed
            self.kafka_reader.dispatch_raw = self.fabric.dispatch_raw

        self.metrics.start()

        if not config.disable_kafka:
            def heartbeat():
                while not self._stop_event.wait(KAFKA_STATUS_INTERVAL_SECONDS):
                    cfg = self.config_holder.get()
                    if not cfg.disable_kafka:
                        report_status_message(cfg)

            threading.Thread(target=heartbeat, name="kafka-status", daemon=True).start()

    def server_deps(self) -> ServerDeps:
        return ServerDeps(
            config_holder=self.config_holder,
            static_lists=self.static_lists,
            dynamic_lists=self.dynamic_lists,
            protected_paths=self.protected_paths,
            regex_states=RegexStatesView(self),
            failed_challenge_states=self.failed_challenge_states,
            banner=self.banner,
            gin_log_file=self._gin_log_file,
            server_log_file=self._server_log_file,
            health=self.health,
            # /metrics exposition sources (non-destructive peek() reads —
            # the 29 s line's interval windows are never stolen)
            matcher_getter=lambda: self._matcher,
            pipeline_getter=lambda: self.pipeline,
            supervisor_getter=lambda: self._supervisor,
            slo_getter=lambda: self.slo,
            flightrec_getter=lambda: self.flightrec,
            fabric_getter=lambda: (
                self.fabric.stats if self.fabric is not None else None
            ),
            fleet_getter=lambda: self.fleet_scraper,
            fabric_service_getter=lambda: self.fabric,
            challenge_verifier=self.challenge_verifier,
            decision_table=self.decision_table,
        )

    async def _serve(self, install_signal_handlers: bool) -> None:
        if self._n_http_workers > 0:
            import tempfile

            from banjax_tpu.httpapi.workers import PrimarySupervisor

            ctrl_dir = tempfile.mkdtemp(prefix="banjax-ctrl-")
            self._supervisor = PrimarySupervisor(
                self, ctrl_dir, self._n_http_workers,
                health=self.health.register("worker-supervisor"),
            )
            self.dynamic_lists.set_broadcast(self._supervisor.control.broadcast)
            runner = await run_http_server(
                self.server_deps(), reuse_port=True,
                unix_path=self._supervisor.primary_http_sock(),
            )
            self._supervisor.spawn_workers()
        else:
            runner = await run_http_server(self.server_deps())
        self._async_stop = asyncio.Event()
        if install_signal_handlers:
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, self._async_stop.set)
        self._started.set()
        await self._async_stop.wait()
        await runner.cleanup()

    def run_forever(self) -> None:
        """Blocking run for the CLI (main thread; installs signal handlers)."""
        signal.signal(signal.SIGHUP, lambda s, f: self.reload())
        self.start_workers()
        try:
            asyncio.run(self._serve(install_signal_handlers=True))
        finally:
            self.shutdown()

    def start_background(self, timeout: float = 10.0) -> None:
        """Non-blocking run for tests; waits until the server is listening."""
        self.start_workers()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(self._serve(install_signal_handlers=False))

        self._server_thread = threading.Thread(target=run, name="http-server", daemon=True)
        self._server_thread.start()
        if not self._started.wait(timeout):
            raise RuntimeError("http server did not start in time")

    def stop_background(self) -> None:
        if self._loop is not None and self._async_stop is not None:
            self._loop.call_soon_threadsafe(self._async_stop.set)
        if self._server_thread is not None:
            self._server_thread.join(timeout=5)
        self.shutdown()

    def shutdown(self) -> None:
        self._stop_event.set()
        if self._supervisor is not None:
            self._supervisor.stop()
            self._supervisor = None
        self.tailer.stop()
        if self.fabric is not None:
            # after the tailer (no new routes), before the pipeline
            # drain: peers get connection-refused and fail over
            self.fabric.stop()
        if self.pipeline is not None:
            # tailer first (no new admissions), then drain what's in flight
            self.pipeline.stop()
        if self.slo is not None:
            self.slo.stop()
        if self.fleet_slo is not None:
            self.fleet_slo.stop()
        # uninstall the module-level origin resolver so a later app in
        # the same process (in-process tests) starts clean
        if self.fabric is not None:
            provenance.set_origin_resolver(None)
        if self.flightrec is not None:
            # uninstall the module-level trigger target so a later app in
            # the same process (in-process tests) starts clean
            flightrec_mod.install(None)
        self.metrics.stop()
        # release the shm table only AFTER the metrics loop is stopped —
        # a late tick calling len(failed_challenge_states) on a released
        # mapping would segfault in fc_count
        fc = self.failed_challenge_states
        if hasattr(fc, "unlink"):
            fc.close()
            fc.unlink()
        # same ordering rule for the serving decision table: the metrics
        # loop and /metrics scrapes sample it (serve_stats), so it closes
        # only after metrics.stop(); close() NULL-guards later reads
        dt = self.decision_table
        if dt is not None:
            self.decision_table = None
            try:
                dt.close()
                if hasattr(dt, "unlink"):
                    dt.unlink()
            except Exception:  # noqa: BLE001
                pass
        if self.kafka_reader:
            self.kafka_reader.stop()
        if self.kafka_writer:
            self.kafka_writer.stop()
        if self._matcher is not None:
            self._matcher.close()
        if self.ipset_writer is not None:
            # final queue drain happens inside close(); errors there are
            # counted + logged, never raised
            self.ipset_writer.close()
        self.dynamic_lists.close()
        for f in (self._banning_log_file, self._banning_log_file_temp,
                  self._gin_log_file, self._server_log_file):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass


def main(argv: Optional[list] = None) -> int:
    # Go-style single-dash long flags (banjax.go:67-69)
    parser = argparse.ArgumentParser(prog="banjax-tpu", prefix_chars="-")
    parser.add_argument("-standalone-testing", dest="standalone_testing",
                        action="store_true", help="makes it easy to test standalone")
    parser.add_argument("-config-file", dest="config_file",
                        default="/etc/banjax/banjax-config.yaml", help="config file")
    parser.add_argument("-debug", dest="debug", action="store_true",
                        help="debug mode with verbose logging")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.debug else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    app = BanjaxApp(args.config_file, args.standalone_testing, args.debug)
    app.run_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
