"""Streaming pipeline scheduler (SURVEY §7.2 M5).

The subsystem between the ingest tailer and the matcher: turns the
per-batch synchronous submit→wait→collect path into a multi-stage
overlapped pipeline with adaptive batch sizing, bounded backpressure,
and drain-time staleness accounting.  See pipeline/scheduler.py for the
stage/ordering contract and pipeline/sizer.py for the batch sizing
policy.
"""

from banjax_tpu.pipeline.scheduler import PipelineScheduler
from banjax_tpu.pipeline.sizer import AdaptiveBatchSizer

__all__ = ["PipelineScheduler", "AdaptiveBatchSizer"]
