"""Streaming pipeline scheduler: overlapped tailer→device→effector batching.

PERF.md's transport finding: the fused matcher classifies 2.57M lines/s
device-resident but only ~135–206k end-to-end, because consume_lines is a
synchronous submit→wait→collect loop and the ~65 ms fixed device→host
latency is only hidden when overlapped with compute.  This module is the
continuous-batching scheduler that closes that gap — the inference-serving
pattern (SURVEY §7.2 M5) applied to log classification.

Stages, one thread each::

    tailer → submit() → [admission buffer]
        → encode  (batch formation at the adaptive target, host
                   parse/gate/encode — matcher.pipeline_begin)
        → device  (h2d + device dispatch — matcher.pipeline_submit — with
                   up to two batches in flight, so batch N's device→host
                   pull (pipeline_collect) hides behind batch N+1's
                   compute)
        → drain   (strictly FIFO: window updates, Banner effects,
                   staleness accounting — matcher.pipeline_finish)

so batch N+1 encodes and uploads while batch N computes and batch N−1
drains.

Ordering contract: the drain stage is a single thread consuming batches
in admission order, so per-(ip, rule) window updates and ban-log lines
stay in log order across batch boundaries — byte-identical to the
synchronous path (tests/differential/test_pipeline_differential.py).

Fused two-phase mode: with device windows on, the split protocol drives
the fused matcher+windows two-program path (matcher/fused_windows.py) —
pipeline_submit dispatches program A (stateless match) ahead freely,
and the window commit (program B) happens inside pipeline_finish on the
drain thread, strictly in admission order.  The dense bitmap never
crosses the host boundary (tests/differential/
test_fused_pipeline_differential.py proves byte-identity and the h2d
win).  Generic drains use consume_lines_serial so an inline fused burst
can't deadlock against in-flight two-phase order turns.

Single-kernel mode (`pallas_single_kernel`, the default where the Pallas
window-scan kernel lowers): match AND window commit are ONE device
program dispatched at the submit stage — the drain stage loses its
program-B dispatch turn entirely and just pulls each chunk's compact
event buffer (async since submit) in admission order.  Because the
commit happens at submit, the 10 s staleness cutoff is evaluated there
(the kernel's live-mask input), which is why the submit call below
receives the scheduler clock; a matcher advertises this with
`pipeline_submit_takes_now`.

Kafka commands: submit_commands() admits command messages into the SAME
buffer as tailer lines — shared bounded-block/oldest-first-shed
accounting (admitted == processed + shed spans both producers) — and
the drain thread dispatches each handler in admission order.

Batch sizing: pipeline/sizer.py grows/shrinks the encode target within
power-of-two buckets to hit `pipeline_latency_budget_ms` from observed
per-stage EWMA timings, replacing the fixed `matcher_batch_lines` guess.

Backpressure: a bounded ring of in-flight batches (`pipeline_ring_size`)
gates the encode stage; when the ring is full the admission buffer
absorbs up to `pipeline_buffer_lines`, beyond which submit() blocks the
tailer for at most `pipeline_max_block_ms` and then sheds OLDEST lines
first, counting every shed line (PipelineShedLines) — bounded memory,
never silent loss.

Staleness: the reference drops lines older than 10 s at consume time
(regex_rate_limiter.go:164-167).  Here age is measured at *effector
drain* time — a line that ages out while queued is dropped exactly as
the reference would have dropped it, marked old_line in its result, and
counted (PipelineStaleDroppedLines).

Resilience: matchers without the split protocol (CpuMatcher), batches
whose device stage failed, and batches admitted while the breaker is
OPEN all drain generically through matcher.consume_lines — which routes
to the CPU reference matcher under an open breaker — so the ring drains
through the CPU fallback and no admitted line is lost.  Failpoints
pipeline.encode / pipeline.submit / pipeline.collect / pipeline.drain
cover each stage boundary; the scheduler registers as a health
component; and an optional timer probe (`matcher_probe_seconds`) pushes
a synthetic batch through the idle device path so a wedged device trips
the breaker before the next traffic burst.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

from banjax_tpu.obs import flightrec, trace
from banjax_tpu.obs.stats import PipelineStats
from banjax_tpu.pipeline.sizer import AdaptiveBatchSizer
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import OPEN

log = logging.getLogger(__name__)

# a shard below this many rows costs more in fan-out/merge overhead than
# the parallel parse saves; batches smaller than 2x this stay single-thread
_MIN_SHARD_LINES = 2048


def resolve_encode_workers(v: int) -> int:
    """-1 = auto: min(4, cores), but 0 (single-thread, no pool) on a
    single-core host where a worker adds handoff latency for nothing."""
    if v >= 0:
        return v
    cores = os.cpu_count() or 1
    return min(4, cores) if cores > 1 else 0


class _Batch:
    __slots__ = ("lines", "matcher", "state", "t_encode_ms", "t_device_ms",
                 "t0_device", "kind", "trace_id", "root_span", "e2e")

    def __init__(self, lines: List[str], kind: str = "lines"):
        self.lines = lines      # log lines, or _Command items (kind="cmd")
        self.matcher = None
        self.state = None       # split-protocol state; None = generic drain
        self.t_encode_ms = 0.0
        self.t_device_ms = 0.0
        self.t0_device = 0.0
        self.kind = kind
        # span propagation (obs/trace.py): trace id allocated at the
        # encode stage's take; the root "admission" span opens there and
        # closes when the drain stage finishes this batch (0/NOOP when
        # tracing is off — every span call below no-ops on them)
        self.trace_id = 0
        self.root_span = trace.NOOP_SPAN
        # {hop: oldest tailer-read monotonic stamp} for the lines this
        # batch took — observed into banjax_e2e_latency_seconds at drain
        self.e2e: dict = {}


class _Command:
    """One Kafka command message riding the admission buffer: the raw
    payload plus the reader's dispatch callable.  Commands share the
    buffer bound, the bounded-block/oldest-first shed, and the
    admitted == processed + shed accounting with tailer lines; the drain
    stage executes them in admission order."""

    __slots__ = ("raw", "handler")

    def __init__(self, raw: bytes, handler: Callable[[bytes], None]):
        self.raw = raw
        self.handler = handler


class PipelineScheduler:
    def __init__(
        self,
        matcher_getter: Callable[[], object],
        ring_size: int = 4,
        latency_budget_ms: float = 250.0,
        buffer_lines: int = 131072,
        max_block_ms: float = 250.0,
        min_batch: int = 64,
        max_batch: int = 16384,
        probe_seconds: float = 0.0,
        encode_workers: int = 0,
        command_take_max: int = 1024,
        health=None,
        on_results: Optional[Callable[[List[str], Optional[list]], None]] = None,
        now_fn: Callable[[], float] = time.time,
    ):
        if ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {ring_size}")
        if buffer_lines < 1:
            raise ValueError(f"buffer_lines must be >= 1, got {buffer_lines}")
        self._matcher_getter = matcher_getter
        self.ring_size = ring_size
        self.buffer_lines = buffer_lines
        self.max_block_s = max(0.0, max_block_ms) / 1e3
        self.probe_seconds = probe_seconds
        # sharded encode-worker pool (0 = the single-thread encode path):
        # the encode stage splits each admission batch into row shards
        # fanned across this many threads — the native parse and the
        # columnar gate are GIL-free, so the host path scales with cores
        # instead of capping at one Python thread
        self.encode_workers = max(0, int(encode_workers))
        self._encode_pool = None  # created at start(), joined at stop()
        self._health = health
        self._on_results = on_results
        self._now_fn = now_fn
        self._sizer = AdaptiveBatchSizer(
            latency_budget_ms, min_batch=min_batch, max_batch=max_batch,
            command_max=command_take_max,
        )
        self.stats = PipelineStats()
        self._buf: deque = deque()
        # read-stamp runs parallel to the LINE items in _buf: [count,
        # t_read, hop] per admitted chunk, trimmed in lockstep by sheds
        # and encode takes (commands carry no stamp and no mark)
        self._marks: deque = deque()
        self._cond = threading.Condition()
        self._inflight = 0
        self._last_activity = time.monotonic()
        self._ring = threading.Semaphore(ring_size)
        self._q_dev: "queue.Queue" = queue.Queue()
        self._q_drain: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    @classmethod
    def from_config(cls, matcher_getter, config, health=None, on_results=None):
        return cls(
            matcher_getter,
            ring_size=getattr(config, "pipeline_ring_size", 4),
            latency_budget_ms=getattr(
                config, "pipeline_latency_budget_ms", 250.0
            ),
            buffer_lines=getattr(config, "pipeline_buffer_lines", 131072),
            max_block_ms=getattr(config, "pipeline_max_block_ms", 250.0),
            max_batch=max(64, getattr(config, "matcher_batch_lines", 16384)),
            probe_seconds=getattr(config, "matcher_probe_seconds", 0.0),
            encode_workers=resolve_encode_workers(
                getattr(config, "encode_workers", -1)
            ),
            command_take_max=getattr(
                config, "pipeline_command_take_max", 1024
            ),
            health=health,
            on_results=on_results,
        )

    # ---- lifecycle ----

    def start(self) -> None:
        if self.encode_workers > 0 and self._encode_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._encode_pool = ThreadPoolExecutor(
                max_workers=self.encode_workers,
                thread_name_prefix="pipeline-encode-worker",
            )
        loops = [
            ("pipeline-encode", self._encode_loop),
            ("pipeline-device", self._device_loop),
            ("pipeline-drain", self._drain_loop),
        ]
        if self.probe_seconds > 0:
            loops.append(("pipeline-probe", self._probe_loop))
        for name, fn in loops:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self._health is not None:
            self._health.ok()

    def stop(self, timeout: float = 10.0) -> None:
        """Drain everything already admitted, then stop the stage threads
        (bounded by ring_size + buffer_lines, both finite by contract)."""
        with self._cond:
            self._stop.set()
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(0.1, deadline - time.monotonic()))
        self._threads = []
        if self._encode_pool is not None:
            # after the stage threads joined no new shard work can arrive
            self._encode_pool.shutdown(wait=True)
            self._encode_pool = None

    def flush(self, timeout: float = 60.0) -> bool:
        """Block until every admitted line has drained (tests/bench)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._buf or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    # ---- admission (tailer thread) ----

    def submit(self, lines: Sequence[str], t_read: Optional[float] = None,
               hop: str = "local") -> None:
        """Admit a chunk of log lines.  Blocks for at most
        `pipeline_max_block_ms` when the buffer is full, then sheds
        oldest-first — the tailer is never blocked unboundedly and memory
        is never unbounded.  `t_read` is the tailer-read monotonic stamp
        and `hop` whether the chunk was tailed here ("local") or arrived
        over the fabric wire ("fabric") — together they feed the
        banjax_e2e_latency_seconds{hop} histogram at drain time."""
        self._admit(list(lines), t_read=t_read, hop=hop)

    def submit_commands(
        self, raws: Sequence[bytes], handler: Callable[[bytes], None]
    ) -> None:
        """Admit Kafka command messages into the same buffer as tailer
        lines: identical bounded-block/oldest-first-shed accounting
        (admitted == processed + shed holds across both producers), and
        the drain stage dispatches `handler(raw)` per message in admission
        order relative to everything else in the stream."""
        self._admit([_Command(r, handler) for r in raws], hop=None)

    def _mark_drop_locked(self) -> None:
        """One LINE item left the buffer head: trim the oldest mark."""
        if not self._marks:
            return
        m = self._marks[0]
        m[0] -= 1
        if m[0] <= 0:
            self._marks.popleft()

    def _take_marks_locked(self, n: int) -> dict:
        """Consume marks for `n` line items taken off the buffer head;
        returns {hop: oldest t_read} over the stamped ones."""
        out: dict = {}
        while n > 0 and self._marks:
            m = self._marks[0]
            took = min(n, m[0])
            if m[1] is not None:
                hop = m[2]
                if hop not in out or m[1] < out[hop]:
                    out[hop] = m[1]
            m[0] -= took
            n -= took
            if m[0] <= 0:
                self._marks.popleft()
        return out

    def _admit(self, lines: list, t_read: Optional[float] = None,
               hop: Optional[str] = "local") -> None:
        if not lines:
            return
        self.stats.note_admitted(len(lines))
        deadline: Optional[float] = None
        shed_burst = 0
        with self._cond:
            self._last_activity = time.monotonic()
            while (
                len(self._buf) + len(lines) > self.buffer_lines
                and not self._stop.is_set()
            ):
                if deadline is None:
                    deadline = time.monotonic() + self.max_block_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            overflow = len(self._buf) + len(lines) - self.buffer_lines
            if overflow > 0:
                # sustained overload: oldest-first shed, every line counted
                dropped = 0
                while overflow > 0 and self._buf:
                    item = self._buf.popleft()
                    if isinstance(item, str):
                        self._mark_drop_locked()
                    overflow -= 1
                    dropped += 1
                if overflow > 0:  # chunk alone exceeds the buffer bound
                    lines = lines[overflow:]
                    dropped += overflow
                self.stats.note_shed(dropped)
                # stream-level annotation: a shed belongs to no single
                # batch, so it rides the ring as an instant event
                trace.instant("shed", {"lines": dropped,
                                       "buffered": len(self._buf)})
                if self._health is not None:
                    self._health.degraded(f"overload: shed {dropped} lines")
                shed_burst = dropped
            was_empty = not self._buf
            self._buf.extend(lines)
            if hop is not None and lines:
                self._marks.append([len(lines), t_read, hop])
            if was_empty:
                # the encode thread only sleeps on an empty buffer; waking
                # it per chunk would burn the tailer thread on notify calls
                # at high submit rates (flush/backpressure waiters are woken
                # by the encode/drain stages, not here)
                self._cond.notify_all()
        if shed_burst:
            # incident capture OUTSIDE the condition lock: the recorder
            # writes files, and the stage threads must not wait on disk
            flightrec.notify("shed-burst", f"shed {shed_burst} lines")

    # ---- encode stage ----

    def _encode_loop(self) -> None:
        try:
            while True:
                with self._cond:
                    while not self._buf and not self._stop.is_set():
                        self._cond.wait(0.2)
                    if not self._buf and self._stop.is_set():
                        return
                # reserve a ring slot OUTSIDE the lock: while the ring is
                # full the admission buffer keeps absorbing (and shedding)
                # instead of the tailer blocking on a held condition
                if not self._ring.acquire(timeout=0.2):
                    continue
                with self._cond:
                    # take whatever is buffered up to the target, never
                    # wait for a fuller batch: holding the ring slot while
                    # the buffer fills starves the device stage (measured
                    # −40% on the 1-core box); partial batches are fine —
                    # the sizer's trickle rule ignores them.  A batch is
                    # homogeneous: a run of log lines OR a run of command
                    # messages, split at the kind boundary so admission
                    # order is preserved exactly.  Command batches have
                    # their OWN take bound (sizer.command_target): they
                    # carry no device timing for AIMD, and an unbounded
                    # take would let a Kafka command flood monopolize the
                    # drain thread in one giant dispatch loop, starving
                    # line batching.
                    is_cmd = bool(self._buf) and isinstance(
                        self._buf[0], _Command
                    )
                    take = min(
                        len(self._buf),
                        self._sizer.command_target() if is_cmd
                        else self._sizer.target(),
                    )
                    lines = []
                    while (
                        len(lines) < take and self._buf
                        and isinstance(self._buf[0], _Command) == is_cmd
                    ):
                        lines.append(self._buf.popleft())
                    e2e = (
                        self._take_marks_locked(len(lines))
                        if lines and not is_cmd else {}
                    )
                    if lines:
                        self._inflight += 1
                    self._cond.notify_all()
                if not lines:  # a shed emptied the buffer under us
                    self._ring.release()
                    continue
                # take-time is where a batch exists as a unit: allocate its
                # trace id here so admission-buffer wait is excluded but
                # every stage (incl. queueing between stages) is covered
                batch = _Batch(lines, kind="cmd" if is_cmd else "lines")
                batch.e2e = e2e
                if trace.enabled():
                    batch.trace_id = trace.new_trace()
                    batch.root_span = trace.begin(
                        "admission", batch.trace_id,
                        args={"items": len(lines), "kind": batch.kind},
                    )
                if not is_cmd:
                    self._encode_batch(batch)
                self._q_dev.put(batch)
        finally:
            self._q_dev.put(None)

    def _encode_batch(self, batch: _Batch) -> None:
        lines = batch.lines
        t0 = time.perf_counter()
        matcher = self._matcher_getter()
        batch.matcher = matcher
        breaker = getattr(matcher, "breaker", None)
        with trace.span("encode", batch.trace_id,
                        parent=batch.root_span.span_id) as sp:
            # breaker OPEN: skip the split encode entirely — the generic
            # drain re-parses inside consume_lines, which routes to the
            # CPU fallback
            if hasattr(matcher, "pipeline_begin") and not (
                breaker is not None and breaker.state == OPEN
            ):
                if hasattr(matcher, "set_latency_budget_source"):
                    # breaker-budget satellite: when
                    # matcher_latency_budget_ms is unset the breaker
                    # derives it from this pipeline's observed device p99
                    # (3x EWMA p99, floor 50 ms)
                    matcher.set_latency_budget_source(
                        self.stats.suggested_latency_budget_s
                    )
                try:
                    failpoints.check("pipeline.encode")
                    batch.state = self._begin_state(matcher, lines, sp)
                except Exception:  # noqa: BLE001 — encode failure → generic drain, no loss
                    log.exception(
                        "pipeline encode stage failed; batch drains "
                        "generically"
                    )
                    sp.note("failed", True)
                    batch.state = None
            elif breaker is not None and breaker.state == OPEN:
                sp.note("breaker", "open-skip")
        batch.t_encode_ms = (time.perf_counter() - t0) * 1e3

    def _begin_state(self, matcher, lines: List[str], encode_span):
        """pipeline_begin, sharded across the encode-worker pool when the
        batch is big enough to pay for the fan-out.  Shard boundaries are
        contiguous row ranges; the matcher's merge reassembles columnar
        arrays and unique-IP tables in strict line order, so downstream
        output is byte-identical to the single-thread path.  A failing
        shard (worker death, the pipeline.encode_shard failpoint) fails
        only THIS batch — the exception propagates to _encode_batch's
        generic-drain fallback and the pool itself survives.

        Each shard records an `encode-shard` child span of the encode
        span (explicit ids — the pool threads have no ambient parent);
        the single-thread path records one shard span covering the whole
        parse so the trace shape is uniform either way."""
        now = self._now_fn()
        pool = self._encode_pool
        n = len(lines)
        tid, parent = encode_span.trace_id, encode_span.span_id
        n_shards = 0
        if (
            pool is not None
            and hasattr(matcher, "encode_shard")
            and hasattr(matcher, "pipeline_begin_from_shards")
        ):
            n_shards = min(self.encode_workers, n // _MIN_SHARD_LINES)
        if n_shards < 2:
            with trace.span("encode-shard", tid, parent,
                            args={"shard": 0, "shards": 1, "rows": n}):
                return matcher.pipeline_begin(lines, now)
        bounds = [n * k // n_shards for k in range(n_shards + 1)]
        shard_ms = [0.0] * n_shards

        def run(k: int):
            t = time.perf_counter()
            with trace.span(
                "encode-shard", tid, parent,
                args={"shard": k, "shards": n_shards,
                      "rows": bounds[k + 1] - bounds[k]},
            ):
                failpoints.check("pipeline.encode_shard")
                out = matcher.encode_shard(
                    lines[bounds[k] : bounds[k + 1]], now
                )
            shard_ms[k] = (time.perf_counter() - t) * 1e3
            return out

        t_fan = time.perf_counter()
        futs = [pool.submit(run, k) for k in range(n_shards)]
        shards = []
        err = None
        for k, f in enumerate(futs):
            try:
                shards.append((bounds[k], f.result()))
            except Exception as e:  # noqa: BLE001 — await EVERY future before raising
                err = err or e
        if err is not None:
            raise err
        wall_ms = (time.perf_counter() - t_fan) * 1e3
        self.stats.note_encode_shards(shard_ms, wall_ms)
        return matcher.pipeline_begin_from_shards(lines, now, shards)

    # ---- device stage ----

    def _device_loop(self) -> None:
        pending: deque = deque()  # submitted, awaiting collect (≤ 2)
        try:
            while True:
                if pending:
                    # something is in flight: only take new work that is
                    # already queued; otherwise collect now — the overlap
                    # only pays when a successor batch exists to compute
                    # behind the pull
                    try:
                        batch = self._q_dev.get_nowait()
                    except queue.Empty:
                        self._collect(pending.popleft())
                        continue
                else:
                    batch = self._q_dev.get()
                if batch is None:
                    while pending:
                        self._collect(pending.popleft())
                    return
                if batch.kind == "cmd":
                    # no device work; FIFO still holds: everything
                    # submitted before the commands reaches drain first
                    while pending:
                        self._collect(pending.popleft())
                    self._q_drain.put(batch)
                    continue
                if batch.state is not None:
                    breaker = getattr(batch.matcher, "breaker", None)
                    if breaker is not None and not breaker.allow():
                        trace.instant(
                            "breaker-reroute", {"state": breaker.state},
                            trace_id=batch.trace_id,
                        )
                        batch.state = None  # generic drain → CPU fallback
                    else:
                        batch.t0_device = time.perf_counter()
                        try:
                            failpoints.check("pipeline.submit")
                            with trace.span(
                                "submit", batch.trace_id,
                                parent=batch.root_span.span_id,
                            ), trace.step_annotation(batch.trace_id):
                                # matchers that commit state at submit
                                # (the single-kernel fused path) take the
                                # scheduler clock so the staleness cut
                                # stays deterministic under an injected
                                # now_fn
                                if getattr(
                                    batch.matcher,
                                    "pipeline_submit_takes_now", False,
                                ):
                                    batch.matcher.pipeline_submit(
                                        batch.state, now=self._now_fn()
                                    )
                                else:
                                    batch.matcher.pipeline_submit(
                                        batch.state
                                    )
                            # submit half of the device time; collect adds
                            # its half (NOT wall-from-submit: with depth-2
                            # overlap that would double-count the gap where
                            # the successor batch submits)
                            batch.t_device_ms = (
                                time.perf_counter() - batch.t0_device
                            ) * 1e3
                        except Exception:  # noqa: BLE001 — device failure → fallback drain
                            log.exception(
                                "pipeline submit stage failed; batch drains "
                                "on the CPU reference path"
                            )
                            self._device_failure(batch, "submit")
                        else:
                            pending.append(batch)
                            # keep ≤ 2 in flight: collect the older batch
                            # while this one computes
                            while len(pending) >= 2:
                                self._collect(pending.popleft())
                            continue
                # generic/failed batches keep FIFO order: everything
                # submitted before them must reach the drain queue first
                while pending:
                    self._collect(pending.popleft())
                self._q_drain.put(batch)
        finally:
            self._q_drain.put(None)

    def _collect(self, batch: _Batch) -> None:
        t0 = time.perf_counter()
        try:
            failpoints.check("pipeline.collect")
            with trace.span("collect", batch.trace_id,
                            parent=batch.root_span.span_id):
                batch.matcher.pipeline_collect(batch.state)
        except Exception:  # noqa: BLE001 — device failure → fallback drain
            log.exception(
                "pipeline collect stage failed; batch drains on the CPU "
                "reference path"
            )
            self._device_failure(batch, "collect")
        else:
            batch.t_device_ms += (time.perf_counter() - t0) * 1e3
            self.stats.observe_device(batch.t_device_ms / 1e3)
            note = getattr(batch.matcher, "note_device_outcome", None)
            if note is not None:
                note(batch.t_device_ms / 1e3, ok=True)
        self._q_drain.put(batch)

    def _device_failure(self, batch: _Batch, stage: str = "device") -> None:
        trace.instant("device-failure", {"stage": stage},
                      trace_id=batch.trace_id)
        # settle any two-phase chunks the failed batch already dispatched
        # (order turns + slot pins) before the generic rerun — idempotent
        abort = getattr(batch.matcher, "pipeline_abort", None)
        if abort is not None and batch.state is not None:
            try:
                abort(batch.state)
            except Exception:  # noqa: BLE001
                log.exception("pipeline abort after device failure failed")
        batch.state = None
        batch.t_device_ms = max(
            batch.t_device_ms, (time.perf_counter() - batch.t0_device) * 1e3
        )
        note = getattr(batch.matcher, "note_device_outcome", None)
        if note is not None:
            note(batch.t_device_ms / 1e3, ok=False)

    # ---- drain stage (admission order — the ordering contract) ----

    def _drain_loop(self) -> None:
        while True:
            batch = self._q_drain.get()
            if batch is None:
                return
            t0 = time.perf_counter()
            n = len(batch.lines)
            results = None
            ok = True
            sp = trace.span("drain", batch.trace_id,
                            parent=batch.root_span.span_id)
            with sp:
                try:
                    failpoints.check("pipeline.drain")
                    now = self._now_fn()
                    if batch.kind == "cmd":
                        # command batch: dispatch each message in admission
                        # order; a bad command loses itself, not the batch
                        # (the handler owns parse errors, like the
                        # reference's reader loop)
                        for item in batch.lines:
                            try:
                                item.handler(item.raw)
                            except Exception:  # noqa: BLE001
                                log.exception(
                                    "pipeline command dispatch failed"
                                )
                        self.stats.note_commands(n)
                    elif batch.state is None:
                        # generic path: full consume_lines semantics,
                        # including the breaker's CPU-reference fallback —
                        # never a loss.  consume_lines_serial (when the
                        # matcher has it) keeps the fused single-dispatch
                        # burst out of the drain thread: its order turns
                        # belong to the two-phase pipeline and an inline
                        # burst here would deadlock behind in-flight later
                        # batches.
                        sp.note("fallback", "generic-drain")
                        consume = getattr(
                            batch.matcher, "consume_lines_serial", None
                        ) or batch.matcher.consume_lines
                        results = consume(batch.lines, now)
                        self.stats.note_batch(fallback=True)
                    else:
                        results, n_stale = batch.matcher.pipeline_finish(
                            batch.state, now
                        )
                        if n_stale:
                            sp.note("stale_dropped", n_stale)
                            self.stats.note_stale(n_stale)
                        self.stats.note_batch(fallback=False)
                except Exception:  # noqa: BLE001 — drain failure is counted, never silent
                    ok = False
                    log.exception(
                        "pipeline drain stage failed; %d lines counted as "
                        "shed", n
                    )
                    self.stats.note_drain_error(n)
                    if batch.state is not None:
                        # free any two-phase order turns/pins the unfinished
                        # batch still holds — a leaked turn would deadlock
                        # every later fused drain
                        abort = getattr(batch.matcher, "pipeline_abort", None)
                        if abort is not None:
                            try:
                                abort(batch.state)
                            except Exception:  # noqa: BLE001
                                log.exception("pipeline abort failed")
                    if self._health is not None:
                        self._health.degraded("drain failure; lines shed")
            if ok:
                self.stats.note_processed(n)
                if batch.e2e:
                    # effector commit time for every line in the batch:
                    # drain completion, measured against the oldest
                    # tailer-read stamp per hop
                    now_mono = time.monotonic()
                    for hop, t0 in batch.e2e.items():
                        self.stats.observe_e2e(hop, now_mono - t0)
                if self._health is not None:
                    self._health.ok()
            else:
                # lines lost to a drain failure are an incident like a
                # shed burst: capture evidence (debounced; outside every
                # scheduler lock — only this stage thread waits on disk)
                flightrec.notify("drain-error",
                                 f"{n} lines counted as shed")
            t_drain_ms = (time.perf_counter() - t0) * 1e3
            batch.root_span.note("ok", ok)
            trace.end(batch.root_span)
            if batch.kind != "cmd":
                stage_ms = {
                    "encode": batch.t_encode_ms,
                    "device": batch.t_device_ms,
                    "drain": t_drain_ms,
                }
                self._sizer.observe(n, stage_ms)
                # labeled per-stage duration histograms for /metrics —
                # recorded per batch regardless of tracing (the trace ring
                # is the sampled view, the histogram the complete one)
                self.stats.observe_stages(stage_ms)
            if self._on_results is not None and batch.kind != "cmd":
                try:
                    self._on_results(batch.lines, results)
                except Exception:  # noqa: BLE001 — an observer must not stall the drain
                    log.exception("pipeline on_results callback failed")
            self._ring.release()
            with self._cond:
                self._inflight -= 1
                self._last_activity = time.monotonic()
                self._cond.notify_all()

    # ---- idle probe (matcher staleness satellite) ----

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_seconds):
            with self._cond:
                idle = (
                    not self._buf
                    and self._inflight == 0
                    and time.monotonic() - self._last_activity
                    >= self.probe_seconds
                )
            if not idle:
                continue
            probe = getattr(self._matcher_getter(), "probe", None)
            if probe is None:
                continue
            try:
                probe_ok = bool(probe())
            except Exception:  # noqa: BLE001 — a probe bug must not kill the timer
                log.exception("pipeline device probe raised")
                probe_ok = False
            self.stats.note_probe(probe_ok)
            if self._health is not None:
                if probe_ok:
                    self._health.ok()
                else:
                    self._health.degraded("device probe failed")

    # ---- observability ----

    def snapshot(self) -> dict:
        """Additive 29 s metrics-line keys (obs/metrics.py).  Resets the
        interval windows — the line's single periodic consumer only."""
        out = self.stats.snapshot()
        out.update(self._sizer.snapshot())
        return self._live_gauges(out)

    def prom_snapshot(self) -> dict:
        """Non-destructive view for /metrics (obs/exposition.py): totals,
        EWMAs and live gauges; never steals the line's interval deltas."""
        out = self.stats.peek()
        out.update(self._sizer.snapshot())
        return self._live_gauges(out)

    def _live_gauges(self, out: dict) -> dict:
        with self._cond:
            out["PipelineBufferedLines"] = len(self._buf)
            out["PipelineInflightBatches"] = self._inflight
        out["PipelineRingSize"] = self.ring_size
        out["EncodeWorkers"] = self.encode_workers
        return out
