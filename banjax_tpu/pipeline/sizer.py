"""Adaptive batch sizing for the streaming pipeline.

The fixed `matcher_batch_lines` knob is the wrong control for a latency
budget: the right batch size depends on the attached backend, the
ruleset width, and the traffic mix, all of which the scheduler can only
observe at runtime.  AdaptiveBatchSizer picks the batch target from
observed per-stage timings instead:

  * batches are sized in power-of-two buckets (the same bucketing the
    matcher uses to bound jit recompiles — every bucket the sizer visits
    is a program the device has compiled before or will compile once);
  * per-stage (encode / device / drain) per-batch timings feed EWMAs;
    the per-batch TOTAL — the latency a line sees from admission to
    effector drain once queueing is subtracted — is compared against
    `pipeline_latency_budget_ms`;
  * AIMD within the buckets: comfortably under budget (below half) the
    bucket doubles, over budget it halves.  Extrapolating a target
    directly from per-line cost looks cleverer but deadlocks in the
    small-bucket regime, where fixed dispatch overhead dominates the
    per-line estimate and the model concludes big batches are expensive
    — exactly backwards.  AIMD probes upward and observes the truth.
  * an efficiency guard on top of AIMD: per-bucket EWMA of ms/line is
    remembered, growth into a bucket previously measured per-line WORSE
    is blocked, and a bucket that turns out less efficient than the one
    below shrinks back even when its latency fits the budget.  Latency
    headroom alone is not a reason to grow — on cache-bound backends the
    next power of two can be strictly slower per line (measured: the
    1-core CI box degrades past 2048).  Blocked growth is retried after
    `_RETRY_BLOCKED` decisions so a stale measurement (e.g. one polluted
    by a first-visit compile) cannot pin the size forever.
  * a bucket change resets the EWMA and requires `settle` fresh samples
    before the next move, so one noisy batch cannot oscillate the size.

Thread-safety: observe()/target() take a lock; both are called from
different pipeline stage threads.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_STAGES = ("encode", "device", "drain")
# a bucket must be at least this much per-line worse than its lower
# neighbor before the efficiency guard acts (EWMA noise tolerance)
_EFFICIENCY_SLACK = 1.05
# decisions after which a blocked grow forgets the upper bucket's stale
# per-line record and probes again
_RETRY_BLOCKED = 50


def _pow2_at_most(n: int) -> int:
    b = 1
    while b * 2 <= n:
        b <<= 1
    return b


class AdaptiveBatchSizer:
    def __init__(
        self,
        budget_ms: float,
        min_batch: int = 64,
        max_batch: int = 16384,
        start_batch: int = 1024,
        alpha: float = 0.3,
        settle: int = 2,
        command_max: int = 1024,
    ):
        if budget_ms <= 0:
            raise ValueError(f"budget_ms must be positive, got {budget_ms}")
        if not (0 < min_batch <= max_batch):
            raise ValueError(
                f"bad batch bounds [{min_batch}, {max_batch}]"
            )
        if command_max < 1:
            raise ValueError(
                f"command_max must be >= 1, got {command_max}"
            )
        self.budget_ms = budget_ms
        self.command_max = command_max
        self.min_batch = _pow2_at_most(min_batch)
        self.max_batch = _pow2_at_most(max_batch)
        self._alpha = alpha
        self._settle = settle
        self._lock = threading.Lock()
        self._bucket = min(
            max(_pow2_at_most(start_batch), self.min_batch), self.max_batch
        )
        self._total_ewma_ms: Optional[float] = None
        self._samples_at_bucket = 0
        # efficiency guard state: last EWMA ms/line seen at each bucket,
        # and how many grow decisions the upper bucket's record has blocked
        self._per_line_at: Dict[int, float] = {}
        self._blocked_grows = 0
        # the first full batch after a bucket change pays that bucket's
        # one-time jit compile; learning from it would poison both the
        # latency EWMA and the per-line efficiency record
        self._skip_first = True
        # per-stage EWMA ms at the current bucket — metrics surface only;
        # the grow/shrink decision uses the total
        self.stage_ewma_ms: Dict[str, Optional[float]] = {
            s: None for s in _STAGES
        }

    def target(self) -> int:
        """Current batch-size cap for the encode stage."""
        with self._lock:
            return self._bucket

    def command_target(self) -> int:
        """Take-size bound for COMMAND batches (ROADMAP PR 3 follow-up).
        Commands bypass the device, so they produce no stage timings for
        AIMD to learn from; instead of riding the adaptive line bucket
        (which a command flood would stretch to max_batch) they get a
        fixed cap, chopping a Kafka command flood into bounded batches
        that interleave with line batches at the admission-order kind
        boundary rather than starving line batching."""
        return self.command_max

    def observe(self, n_lines: int, stage_ms: Dict[str, float]) -> None:
        """One drained batch's per-stage wall times (ms).  Batches far
        below the current bucket (a trickle, not a full batch) update the
        stage EWMAs for metrics but don't drive sizing — their latency
        says nothing about the bucket's."""
        total = float(sum(stage_ms.values()))
        with self._lock:
            for s, ms in stage_ms.items():
                prev = self.stage_ewma_ms.get(s)
                self.stage_ewma_ms[s] = (
                    ms if prev is None
                    else prev + self._alpha * (ms - prev)
                )
            if n_lines * 2 < self._bucket and total <= self.budget_ms:
                return
            if self._skip_first:
                self._skip_first = False
                return
            self._total_ewma_ms = (
                total if self._total_ewma_ms is None
                else self._total_ewma_ms
                + self._alpha * (total - self._total_ewma_ms)
            )
            per_line = total / max(1, n_lines)
            prev_pl = self._per_line_at.get(self._bucket)
            cur_pl = self._per_line_at[self._bucket] = (
                per_line if prev_pl is None
                else prev_pl + self._alpha * (per_line - prev_pl)
            )
            self._samples_at_bucket += 1
            if self._samples_at_bucket < self._settle:
                return
            ewma = self._total_ewma_ms
            lower_pl = self._per_line_at.get(self._bucket >> 1)
            upper_pl = self._per_line_at.get(self._bucket << 1)
            if ewma > self.budget_ms and self._bucket > self.min_batch:
                self._bucket >>= 1
                self._reset_locked()
            elif (
                lower_pl is not None
                and cur_pl > lower_pl * _EFFICIENCY_SLACK
                and self._bucket > self.min_batch
            ):
                # latency fits, but this bucket is per-line WORSE than the
                # one below: larger batches are not paying here — go back
                self._bucket >>= 1
                self._reset_locked()
            elif ewma < self.budget_ms * 0.5 and self._bucket < self.max_batch:
                if (
                    upper_pl is not None
                    and upper_pl > cur_pl * _EFFICIENCY_SLACK
                ):
                    # the bucket above was measured per-line worse; retry
                    # eventually in case that record is stale
                    self._blocked_grows += 1
                    if self._blocked_grows >= _RETRY_BLOCKED:
                        self._per_line_at.pop(self._bucket << 1, None)
                        self._blocked_grows = 0
                    return
                self._bucket <<= 1
                self._reset_locked()

    def _reset_locked(self) -> None:
        self._total_ewma_ms = None
        self._samples_at_bucket = 0
        self._skip_first = True

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {
                "PipelineBatchTarget": self._bucket,
                "PipelineCommandBatchTarget": self.command_max,
            }
            for s in _STAGES:
                v = self.stage_ewma_ms.get(s)
                out[f"PipelineStage{s.capitalize()}EwmaMs"] = (
                    None if v is None else round(v, 3)
                )
            return out
