"""Sha-inv PoW verification: CPU wire checks, device-batched zero-bit
counting, byte-identical decisions on every path.

The wire-contract stages — base64 parse, length check, expiry compare,
HMAC — always run on the CPU (they're cheap, branchy, and every byte is
part of the reference contract).  Only the hot arithmetic, the
leading-zero count of ``sha256(hmac || solution)``, is routed to the
batched Pallas kernel (matcher/kernels/pow_verify.py).

The HTTP path never blocks on the device unboundedly and never changes
an accept/reject decision:

  * requests funnel into a leader/follower micro-batch: whichever
    worker thread reaches the queue first dispatches everything pending
    (up to ``challenge_verify_batch_max``) in ONE kernel call and wakes
    the followers with their per-lane counts;
  * a full queue, an open breaker, a failed startup selftest, a device
    fault (the ``challenge.device_verify`` failpoint drills this), or a
    wait timeout all degrade the *caller* to the inline CPU oracle —
    same digest, same count, same CookieError text;
  * repeated device faults trip a breaker that holds verification on
    the CPU until a cooldown expires, then probes half-open.

``verify_sha_inv`` is the one entry the decision chain calls; the
``challenge.verify`` failpoint at its top is the fail-open drill (a
fault there propagates to the recovery middleware's 502-with-
X-Accel-Redirect panic path, per the reference's nginx contract).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import List, Optional, Sequence

from banjax_tpu.challenge import stats as challenge_stats
from banjax_tpu.crypto.challenge import (
    CookieError,
    count_zero_bits_from_left,
    parse_cookie,
    validate_expiration_and_hmac,
)
from banjax_tpu.resilience import failpoints

logger = logging.getLogger(__name__)


class DeviceUnavailable(Exception):
    """Device path declined this verification — caller falls back to
    the CPU oracle inline.  Never surfaces to HTTP."""


class QueueFull(DeviceUnavailable):
    pass


def cpu_zero_bits(payload: bytes) -> int:
    """The pure-CPU oracle: reference digest + reference count."""
    return count_zero_bits_from_left(hashlib.sha256(payload).digest())


class _Slot:
    __slots__ = ("payload", "event", "bits", "error")

    def __init__(self, payload: bytes) -> None:
        self.payload = payload
        self.event = threading.Event()
        self.bits: Optional[int] = None
        self.error: Optional[BaseException] = None


class DeviceVerifier:
    """Micro-batching front end over the pow_verify kernel with a
    failure breaker.  Thread-safe; one per process."""

    def __init__(
        self,
        batch_max: int = 256,
        *,
        interpret: Optional[bool] = None,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 30.0,
        wait_timeout_s: float = 2.0,
    ) -> None:
        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        self.batch_max = int(batch_max)
        self._interpret = interpret
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        self._wait_timeout_s = float(wait_timeout_s)

        self._lock = threading.Lock()
        self._queue: List[_Slot] = []
        self._dispatching = False

        self._consecutive_failures = 0
        self._open_until = 0.0
        self._half_open_probe = False
        self._selftest_done = False
        self._disabled_reason: Optional[str] = None

        self.dispatches = 0
        self.lanes_verified = 0
        self.faults = 0
        self.queue_rejections = 0
        self.breaker_trips = 0

    # ---- health / breaker (lock held unless noted) ----

    def _ensure_selftest(self) -> None:
        """First-use differential proof vs hashlib; a mismatch disables
        the device path for the process (scan_selftest downgrade)."""
        if self._selftest_done:
            return
        self._selftest_done = True
        try:
            from banjax_tpu.matcher.kernels.pow_verify import (
                _default_interpret,
                pow_selftest,
            )

            if self._interpret is None:
                self._interpret = _default_interpret()
            pow_selftest(interpret=self._interpret)
        except Exception as exc:  # noqa: BLE001 — any failure disables
            self._disabled_reason = f"pow selftest failed: {exc}"
            logger.warning(
                "challenge device verifier disabled, CPU oracle only: %s",
                exc,
            )

    def available(self) -> bool:
        with self._lock:
            self._ensure_selftest()
            if self._disabled_reason is not None:
                return False
            if self._consecutive_failures < self._breaker_threshold:
                return True
            if time.monotonic() >= self._open_until and not self._half_open_probe:
                return True  # one caller probes half-open
            return False

    def _note_ok(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._half_open_probe = False

    def _note_failure(self) -> None:
        with self._lock:
            self.faults += 1
            self._consecutive_failures += 1
            self._half_open_probe = False
            if self._consecutive_failures == self._breaker_threshold:
                self.breaker_trips += 1
                logger.warning(
                    "challenge device breaker open after %d faults; "
                    "CPU oracle for %.0fs",
                    self._consecutive_failures,
                    self._breaker_cooldown_s,
                )
            if self._consecutive_failures >= self._breaker_threshold:
                self._open_until = time.monotonic() + self._breaker_cooldown_s

    # ---- dispatch ----

    def _device_bits(self, payloads: Sequence[bytes]) -> List[int]:
        failpoints.check("challenge.device_verify")
        from banjax_tpu.matcher.kernels.pow_verify import (
            leading_zero_bits_batch,
        )

        return leading_zero_bits_batch(
            payloads, interpret=bool(self._interpret)
        ).tolist()

    def _drain_as_leader(self) -> None:
        stats = challenge_stats.get_stats()
        try:
            while True:
                with self._lock:
                    batch = self._queue[: self.batch_max]
                    del self._queue[: len(batch)]
                    if not batch:
                        return
                try:
                    bits = self._device_bits([s.payload for s in batch])
                except BaseException as exc:  # noqa: BLE001 — wake followers
                    for slot in batch:
                        slot.error = exc
                        slot.event.set()
                    self._note_failure()
                    continue
                for slot, b in zip(batch, bits):
                    slot.bits = int(b)
                    slot.event.set()
                self._note_ok()
                self.dispatches += 1
                self.lanes_verified += len(batch)
                stats.note_device_batch(len(batch))
        finally:
            with self._lock:
                self._dispatching = False

    def submit(self, payload: bytes) -> int:
        """Zero-bit count for one hmac||solution payload via the device,
        micro-batched with concurrent callers.  Raises DeviceUnavailable
        (or subclass) when the caller should verify inline on CPU."""
        with self._lock:
            self._ensure_selftest()
            if self._disabled_reason is not None:
                raise DeviceUnavailable(self._disabled_reason)
            if self._consecutive_failures >= self._breaker_threshold:
                if time.monotonic() < self._open_until or self._half_open_probe:
                    raise DeviceUnavailable("breaker open")
                self._half_open_probe = True  # this caller is the probe
            if len(self._queue) >= self.batch_max:
                self.queue_rejections += 1
                raise QueueFull(
                    f"verification queue at bound {self.batch_max}"
                )
            slot = _Slot(payload)
            self._queue.append(slot)
            leader = not self._dispatching
            if leader:
                self._dispatching = True
        if leader:
            self._drain_as_leader()
        if not slot.event.wait(self._wait_timeout_s):
            raise DeviceUnavailable("device wait timeout")
        if slot.error is not None:
            raise DeviceUnavailable(str(slot.error))
        assert slot.bits is not None
        return slot.bits

    def verify_batch(
        self, payloads: Sequence[bytes]
    ) -> List[int]:
        """Bulk path for bench/scenario harnesses: dispatch in
        batch_max-sized kernel calls, CPU fallback per-chunk on fault."""
        stats = challenge_stats.get_stats()
        out: List[int] = []
        for i in range(0, len(payloads), self.batch_max):
            chunk = list(payloads[i : i + self.batch_max])
            if self.available():
                try:
                    bits = self._device_bits(chunk)
                    self._note_ok()
                    self.dispatches += 1
                    self.lanes_verified += len(chunk)
                    stats.note_device_batch(len(chunk))
                    out.extend(bits)
                    continue
                except BaseException:  # noqa: BLE001
                    self._note_failure()
            out.extend(cpu_zero_bits(p) for p in chunk)
        return out

    def counters(self) -> dict:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "lanes_verified": self.lanes_verified,
                "faults": self.faults,
                "queue_rejections": self.queue_rejections,
                "breaker_trips": self.breaker_trips,
                "disabled_reason": self._disabled_reason,
            }


def from_config(config) -> Optional[DeviceVerifier]:
    """The construction seam: a device verifier when
    challenge_device_verify is set, else None (pure-CPU reference
    path).  Both server layouts and the workers build through here."""
    if not getattr(config, "challenge_device_verify", False):
        return None
    return DeviceVerifier(
        int(getattr(config, "challenge_verify_batch_max", 256))
    )


def verify_sha_inv(
    secret_key: str,
    cookie_string: str,
    now_time_unix: float,
    client_binding: str,
    expected_zero_bits: int,
    device: Optional[DeviceVerifier] = None,
) -> None:
    """The decision chain's verification entry.  Raises CookieError on
    any invalid cookie with the reference's exact message text; the
    device only ever computes the zero-bit count, so decisions are
    byte-identical whichever path ran.

    The ``result``/``path`` labels on
    banjax_challenge_verifications_total record where the PoW stage
    actually executed (wire-stage rejects are CPU by construction)."""
    failpoints.check("challenge.verify")
    stats = challenge_stats.get_stats()
    try:
        hmac_from_client, solution_bytes, expiration_bytes = parse_cookie(
            cookie_string
        )
        validate_expiration_and_hmac(
            secret_key,
            expiration_bytes,
            now_time_unix,
            hmac_from_client,
            client_binding,
        )
    except CookieError:
        stats.note_verification("reject", "cpu")
        raise

    payload = hmac_from_client + solution_bytes
    path = "cpu"
    if device is not None and device.available():
        try:
            actual_zero_bits = device.submit(payload)
            path = "device"
        except DeviceUnavailable:
            actual_zero_bits = cpu_zero_bits(payload)
    else:
        actual_zero_bits = cpu_zero_bits(payload)

    if actual_zero_bits < expected_zero_bits:
        stats.note_verification("reject", path)
        raise CookieError(
            f"not enough zero bits in hash: expected {expected_zero_bits}, "
            f"found {actual_zero_bits}"
        )
    stats.note_verification("accept", path)
