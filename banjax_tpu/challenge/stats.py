"""Challenge-plane run counters for the /metrics surfaces.

A LEAF module in the scenarios/stats.py mold: obs/exposition.py and
obs/metrics.py import it lazily, so a process that never issues or
verifies a challenge pays one import and one lock per scrape — and the
banjax_challenge_* families declared in obs/registry.py keep the schema
CI-locked like every other surface.

The issuer, verifier and bounded failure state publish here; totals are
process-lifetime counters, the entries value is a point-in-time gauge.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from banjax_tpu.obs.registry import Histogram

# device dispatch sizes are small powers of two up to the queue bound
BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                      256.0, 512.0, 1024.0)


class ChallengeStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.issued_total = 0
        # (result, path) -> count; result in {"accept", "reject"},
        # path in {"cpu", "device"}
        self._verifications: Dict[Tuple[str, str], int] = {}
        self.verify_batch_size = Histogram(BATCH_SIZE_BUCKETS)
        self.failure_state_entries = 0
        self.failure_evictions_total = 0

    def note_issued(self, n: int = 1) -> None:
        with self._lock:
            self.issued_total += n

    def note_verification(self, result: str, path: str, n: int = 1) -> None:
        key = (result, path)
        with self._lock:
            self._verifications[key] = self._verifications.get(key, 0) + n

    def note_device_batch(self, size: int) -> None:
        self.verify_batch_size.observe(float(size))

    def note_failure_state(self, entries: int, evictions_total: int) -> None:
        with self._lock:
            self.failure_state_entries = int(entries)
            self.failure_evictions_total = int(evictions_total)

    def prom_snapshot(self) -> dict:
        with self._lock:
            verifications = dict(self._verifications)
            return {
                "issued_total": self.issued_total,
                "verifications": verifications,
                "verifications_total": sum(verifications.values()),
                "failure_state_entries": self.failure_state_entries,
                "failure_evictions_total": self.failure_evictions_total,
            }

    def active(self) -> bool:
        """True once anything challenge-shaped happened in this process —
        the render gate, so idle scrapes stay challenge-free."""
        with self._lock:
            return bool(
                self.issued_total or self._verifications
                or self.failure_state_entries or self.failure_evictions_total
            )

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self.issued_total = 0
            self._verifications.clear()
            self.verify_batch_size = Histogram(BATCH_SIZE_BUCKETS)
            self.failure_state_entries = 0
            self.failure_evictions_total = 0


_stats = ChallengeStats()


def get_stats() -> ChallengeStats:
    return _stats
