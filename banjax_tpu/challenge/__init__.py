"""Challenge plane: stateless issuance, device-batched PoW verification,
bounded failure state (ROADMAP item 3).

Three layers over the reference's challenge decision sources (the
SHA-inverting proof-of-work at 429, the password form at 401, and the
failed-challenge rate limiter — PAPER.md §0, sources 1/4):

  * issuer.py    — signed expiring challenge cookies in the reference's
                   exact wire format; issuance is a pure function of
                   (secret, binding, expiry) and holds ZERO per-IP state.
  * verifier.py  — sha-inv PoW verification with the leading-zero check
                   batched onto the device (matcher/kernels/pow_verify.py);
                   the pure-CPU reference verifier stays as differential
                   oracle and breaker fallback, so accept/reject decisions
                   are byte-identical on every path.
  * failures.py  — per-IP failed-challenge state with the reference's
                   fixed-window semantics, bounded by an LRU over exact
                   entries plus sketch-gated spill/refill so 1M+ concurrent
                   challengers cannot exhaust the host.
  * stats.py     — leaf-module counters behind the banjax_challenge_*
                   registry families.
"""
