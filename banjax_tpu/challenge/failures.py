"""Bounded failed-challenge state: LRU exact tier + sketch-gated spill.

The reference's FailedChallengeRateLimitStates (decisions/rate_limit.py)
is an unbounded per-IP dict — under a challenge storm every first-time
visitor to a BLOCK-mode challenge creates an entry, so 1M+ distinct
challengers exhaust the host.  This class keeps the reference's exact
fixed-window transition semantics (the strictly-greater window restart
and the exceed-resets-to-0 quirk, rate_limit.go:125-156) while bounding
memory with the mega-state tiering discipline (PR 10):

  * **exact tier** — an LRU-ordered dict of at most ``max_entries``
    per-IP (num_hits, interval_start) states; every apply() on a held
    entry is bit-identical to the reference.
  * **spill tier** — a fixed-size open-addressed fingerprint table
    (numpy, one slot per fingerprint): an evicted entry's exact
    (hits, start) pair parks here and refills losslessly on the IP's
    next failure.  A slot collision keeps the entry with more hits
    (ties: the fresher window) and counts the loser in ``spill_drops``
    — bounded memory, never silent.
  * **sketch gate** — the PR 8 count-min discipline (same hash family:
    obs/sketch.hash_ip + fmix32 rows), conservatively counting failure
    events per IP over a rotating two-epoch window: an evictee spills
    only when the sketch says it has shown repeat pressure (estimate
    >= 2) or its exact hits already prove it.  One-shot churners — the
    1M-flood's whole population — never touch the spill table, so the
    few repeat offenders' parked state survives the flood.

Divergence from the unbounded oracle is possible only for an IP whose
state was evicted AND spill-dropped AND who then returns in-window —
every step of which is counted.  Dropped state always *under*-counts
(the IP restarts fresh, exactly like a new oracle IP), so a drop can
delay a ban, never conjure one out of a benign client within the
oracle's window; BENCH_challenge.json banks the 1M-challenger storm row
at ban precision/recall 1.0 vs the unbounded oracle with entries <=
challenge_failure_state_max.

Evictions under storm pressure notify the flight recorder (debounced in
the recorder itself), so a forced storm leaves a loadable incident
bundle behind.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

import numpy as np

from banjax_tpu.challenge import stats as challenge_stats
from banjax_tpu.decisions.rate_limit import (
    NumHitsAndIntervalStart,
    RateLimitMatchType,
    RateLimitResult,
)
from banjax_tpu.obs import flightrec as flightrec_mod
from banjax_tpu.obs.sketch import _CM_SEEDS, _fmix32_np, hash_ip

_NS = 1_000_000_000


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class BoundedFailedChallengeStates:
    """Drop-in for FailedChallengeRateLimitStates (same apply/__len__/
    format_states surface) with bounded per-client memory."""

    def __init__(
        self,
        max_entries: int,
        *,
        spill_factor: int = 2,
        sketch_depth: int = 4,
        sketch_width: int = 0,
        now_ns_fn: Callable[[], int] = time.time_ns,
    ) -> None:
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self._max = int(max_entries)
        self._now_ns = now_ns_fn
        self._lock = threading.Lock()
        self._states: "OrderedDict[str, NumHitsAndIntervalStart]" = OrderedDict()

        # spill tier: fingerprint-keyed single-slot table of exact
        # (hits, interval_start) pairs; fp 0 = empty
        size = _pow2(max(1024, spill_factor * self._max))
        self._sp_mask = size - 1
        self._sp_fp = np.zeros(size, dtype=np.uint64)
        self._sp_hits = np.zeros(size, dtype=np.int32)
        self._sp_start = np.zeros(size, dtype=np.int64)

        # count-min over failure events, two rotating epochs so any
        # reference window (whose start is per-IP) is covered by
        # current + previous
        self._cm_depth = max(1, min(int(sketch_depth), len(_CM_SEEDS)))
        width = int(sketch_width) or _pow2(max(1024, 4 * self._max))
        self._cm_width = _pow2(width)
        self._cm_cur = np.zeros((self._cm_depth, self._cm_width), np.int32)
        self._cm_prev = np.zeros_like(self._cm_cur)
        self._cm_epoch_start_ns = 0

        self.evictions_total = 0
        self.spill_writes = 0
        self.spill_refills = 0
        self.spill_drops = 0       # collision losses — the only lossy step
        self.gate_skips = 0        # one-shot evictees the sketch kept out
        self.stale_drops = 0       # evictees whose window had already passed
        self._notified_epoch = -1

    # ---- hashing ----

    def _fingerprint(self, ip: str) -> int:
        h = np.uint32(hash_ip(ip))
        hi = int(_fmix32_np(np.asarray([h], np.uint32))[0])
        lo = int(_fmix32_np(np.asarray([h ^ np.uint32(_CM_SEEDS[1])],
                                       np.uint32))[0])
        return ((hi << 32) | lo) | 1  # never 0 (the empty-slot marker)

    def _cm_cols(self, ip: str) -> np.ndarray:
        base = np.full(self._cm_depth, hash_ip(ip), np.uint32)
        seeds = np.asarray(_CM_SEEDS[: self._cm_depth], np.uint32)
        return (_fmix32_np(base ^ seeds) & np.uint32(self._cm_width - 1)).astype(
            np.int64
        )

    # ---- sketch (caller holds the lock) ----

    def _cm_tick(self, now_ns: int, interval_ns: int) -> None:
        epoch_ns = max(1, interval_ns)
        if now_ns - self._cm_epoch_start_ns > epoch_ns:
            self._cm_prev, self._cm_cur = self._cm_cur, self._cm_prev
            self._cm_cur[:] = 0
            self._cm_epoch_start_ns = now_ns

    def _cm_add(self, ip: str) -> None:
        cols = self._cm_cols(ip)
        rows = np.arange(self._cm_depth)
        counts = self._cm_cur[rows, cols]
        # conservative update: only the min buckets advance, so the
        # estimate (min over rows, cur + prev) never undercounts and
        # rarely overcounts
        m = counts.min()
        self._cm_cur[rows[counts == m], cols[counts == m]] = m + 1

    def _cm_estimate(self, ip: str) -> int:
        cols = self._cm_cols(ip)
        rows = np.arange(self._cm_depth)
        return int(
            (self._cm_cur[rows, cols] + self._cm_prev[rows, cols]).min()
        )

    # ---- spill tier (caller holds the lock) ----

    def _spill_take(self, ip: str) -> Optional[NumHitsAndIntervalStart]:
        fp = self._fingerprint(ip)
        slot = (fp >> 17) & self._sp_mask
        if int(self._sp_fp[slot]) != fp:
            return None
        state = NumHitsAndIntervalStart(
            int(self._sp_hits[slot]), int(self._sp_start[slot])
        )
        self._sp_fp[slot] = 0
        self.spill_refills += 1
        return state

    def _spill_put(self, ip: str, state: NumHitsAndIntervalStart) -> None:
        fp = self._fingerprint(ip)
        slot = (fp >> 17) & self._sp_mask
        occupied = int(self._sp_fp[slot]) not in (0, fp)
        if occupied:
            # keep whichever entry carries more evidence: more hits,
            # ties broken toward the fresher window
            held = (int(self._sp_hits[slot]), int(self._sp_start[slot]))
            cand = (state.num_hits, state.interval_start_time_ns)
            if held >= cand:
                self.spill_drops += 1
                return
            self.spill_drops += 1  # the displaced entry is the loss
        self._sp_fp[slot] = np.uint64(fp)
        self._sp_hits[slot] = np.int32(state.num_hits)
        self._sp_start[slot] = np.int64(state.interval_start_time_ns)
        self.spill_writes += 1

    # ---- eviction (caller holds the lock) ----

    def _evict_one(self, now_ns: int, interval_ns: int) -> None:
        ip, state = self._states.popitem(last=False)
        self.evictions_total += 1
        if now_ns - state.interval_start_time_ns > interval_ns:
            self.stale_drops += 1  # window already over: nothing to keep
        elif state.num_hits >= 2 or self._cm_estimate(ip) >= 2:
            self._spill_put(ip, state)
        else:
            self.gate_skips += 1  # one-shot churner: sketch remembers it
        # one storm notification per sketch epoch: the recorder debounces
        # further, and a quiet process never pays the call
        epoch = self._cm_epoch_start_ns
        if self._notified_epoch != epoch:
            self._notified_epoch = epoch
            flightrec_mod.notify(
                "challenge-failure-storm",
                f"evictions={self.evictions_total} "
                f"entries={len(self._states)} max={self._max}",
            )

    # ---- the reference surface ----

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def apply(self, ip: str, config) -> RateLimitResult:
        """Reference transitions (rate_limit.go:125-156) over the exact
        tier, with spill refill on re-entry and LRU eviction past the
        bound."""
        result = RateLimitResult()
        timestamp_ns = self._now_ns()
        interval_ns = (
            config.too_many_failed_challenges_interval_seconds * _NS
        )
        with self._lock:
            self._cm_tick(timestamp_ns, interval_ns)
            self._cm_add(ip)
            state = self._states.get(ip)
            if state is not None:
                self._states.move_to_end(ip)
            else:
                state = self._spill_take(ip)
                if state is not None:
                    self._states[ip] = state
            if state is not None:
                if timestamp_ns - state.interval_start_time_ns > interval_ns:
                    result.match_type = RateLimitMatchType.OUTSIDE_INTERVAL
                    state.num_hits = 1
                    state.interval_start_time_ns = timestamp_ns
                else:
                    result.match_type = RateLimitMatchType.INSIDE_INTERVAL
                    state.num_hits += 1
            else:
                result.match_type = RateLimitMatchType.FIRST_TIME
                state = NumHitsAndIntervalStart(1, timestamp_ns)
                self._states[ip] = state

            if state.num_hits > config.too_many_failed_challenges_threshold:
                state.num_hits = 0  # same reference quirk: reset to 0
                result.exceeded = True
            else:
                result.exceeded = False

            while len(self._states) > self._max:
                self._evict_one(timestamp_ns, interval_ns)

            entries = len(self._states)
            evictions = self.evictions_total
        challenge_stats.get_stats().note_failure_state(entries, evictions)
        return result

    def format_states(self) -> str:
        with self._lock:
            return "".join(
                f"{ip},: interval_start: {s.interval_start_time_ns}, "
                f"num hits: {s.num_hits}\n"
                for ip, s in self._states.items()
            )

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._states),
                "evictions_total": self.evictions_total,
                "spill_writes": self.spill_writes,
                "spill_refills": self.spill_refills,
                "spill_drops": self.spill_drops,
                "gate_skips": self.gate_skips,
                "stale_drops": self.stale_drops,
            }


def make_failed_challenge_states(config):
    """The construction seam: bounded when challenge_failure_state_max
    is set, the reference's unbounded dict otherwise (cli.py and the
    scenario harness both build through here)."""
    from banjax_tpu.decisions.rate_limit import FailedChallengeRateLimitStates

    limit = int(getattr(config, "challenge_failure_state_max", 0) or 0)
    if limit > 0:
        return BoundedFailedChallengeStates(limit)
    return FailedChallengeRateLimitStates()
