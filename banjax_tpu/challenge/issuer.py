"""Stateless challenge issuance.

A challenge cookie is a PURE function of (secret, binding, expiry) —
hmac[20] ‖ zeros[32] ‖ expiry_be8, base64'd, the reference's exact wire
layout (crypto/challenge.py; byte-compatible with the unchanged
client-side JS solvers).  Issuing one therefore holds zero per-IP state:
a flash crowd of a million first-time visitors costs a million HMACs and
nothing else.  State enters the picture only when a challenge is
*failed* (challenge/failures.py).

The decision chain's 429/401 paths route through `issue()` so every
issuance crosses the `challenge.issue` failpoint (fault drills prove an
issuance fault fails open through the recovery middleware, never
wedging the worker) and lands in the banjax_challenge_issued_total
counter.
"""

from __future__ import annotations

import time
from typing import Optional

from banjax_tpu.challenge import stats as challenge_stats
from banjax_tpu.crypto.challenge import new_challenge_cookie_at
from banjax_tpu.resilience import failpoints


def issue_at(secret_key: str, expire_time_unix: int, client_binding: str) -> str:
    """The deterministic issuance primitive — same inputs, same bytes."""
    return new_challenge_cookie_at(secret_key, expire_time_unix, client_binding)


def issue(
    secret_key: str,
    cookie_ttl_seconds: int,
    client_binding: str,
    now_unix: Optional[float] = None,
) -> str:
    """Issue one signed expiring challenge cookie (the decision chain's
    _challenge_cookie call site, both the sha-inv 429 and password 401
    flows)."""
    failpoints.check("challenge.issue")
    now = time.time() if now_unix is None else now_unix
    cookie = issue_at(secret_key, int(now) + cookie_ttl_seconds, client_binding)
    challenge_stats.get_stats().note_issued()
    return cookie
