"""Compiled-serving-path counters for the /metrics surfaces.

A LEAF module in the challenge/stats.py mold: obs/exposition.py and
obs/metrics.py import it lazily, so a process that never takes the
/auth_request fast path pays one import and one lock per scrape — and
the banjax_serve_fastpath_* families declared in obs/registry.py keep
the schema CI-locked like every other surface.

The fastserve fast path (httpapi/fastpath.py) and the dynamic-list
mirror (decisions/dynamic_lists.py) publish here; totals are
process-lifetime counters, the table figures are point-in-time gauges
sampled from the attached decision table at scrape time.
"""

from __future__ import annotations

import threading
from typing import Dict

# every terminal state of one fast-path consultation (the tier label)
HIT_TIERS = ("allow", "block", "challenge")
# why the consultation declined and the chain served instead
MISS_REASONS = ("disabled", "table", "expired", "ineligible", "password",
                "global_list", "session_guard", "baskerville")


class ServeFastpathStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._misses: Dict[str, int] = {}
        self.faults_total = 0          # failpoint / unexpected lookup error
        self.mirror_errors_total = 0   # dynamic-list mirror write failures
        self._table = None             # sampled for the gauges at scrape

    def set_table(self, table) -> None:
        with self._lock:
            self._table = table

    def note_hit(self, tier: str, n: int = 1) -> None:
        with self._lock:
            self._hits[tier] = self._hits.get(tier, 0) + n

    def note_miss(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self._misses[reason] = self._misses.get(reason, 0) + n

    def note_fault(self, n: int = 1) -> None:
        with self._lock:
            self.faults_total += n

    def note_mirror_error(self, n: int = 1) -> None:
        with self._lock:
            self.mirror_errors_total += n

    def prom_snapshot(self) -> dict:
        with self._lock:
            table = self._table
            hits = dict(self._hits)
            misses = dict(self._misses)
            faults = self.faults_total
            mirror_errors = self.mirror_errors_total
        entries = dropped = sessions = 0
        if table is not None:
            try:
                entries = len(table)
                dropped = int(table.dropped)
                sessions = int(table.session_count())
            except Exception:  # noqa: BLE001 — a closed table reads as 0
                pass
        return {
            "hits": hits,
            "hits_total": sum(hits.values()),
            "misses": misses,
            "misses_total": sum(misses.values()),
            "faults_total": faults,
            "mirror_errors_total": mirror_errors,
            "table_entries": entries,
            "table_dropped_total": dropped,
            "table_session_entries": sessions,
        }

    def active(self) -> bool:
        """True once the fast path was consulted (or a table attached) in
        this process — the render gate, so idle scrapes stay clean."""
        with self._lock:
            return bool(
                self._hits or self._misses or self.faults_total
                or self.mirror_errors_total or self._table is not None
            )

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self._hits.clear()
            self._misses.clear()
            self.faults_total = 0
            self.mirror_errors_total = 0
            self._table = None


_stats = ServeFastpathStats()


def get_stats() -> ServeFastpathStats:
    return _stats
