"""Server-side rewriting of the challenge HTML pages before serving.

Reference behavior: /root/reference/internal/http_server.go:438-491 — the
pages ship with hardcoded JS that the server patches by literal string
replacement (first occurrence only): the cookie-set expression gains a
max-age (and, for roaming password sites, a domain scope), and
`new_solver(10)` is rewritten to the configured difficulty. The replacement
targets are part of the page contract (see the page headers in
banjax_tpu/httpapi/pages/).
"""

from __future__ import annotations

from banjax_tpu.config.schema import Config

PASSWORD_COOKIE_NAME = "deflect_password3"
CHALLENGE_COOKIE_NAME = "deflect_challenge3"


def modify_html_content(page_bytes: bytes, target: str, replacement: str) -> bytes:
    """bytes.Replace(..., 1) equivalent (http_server.go:438-440)."""
    return page_bytes.replace(target.encode(), replacement.encode(), 1)


def apply_cookie_max_age(page_bytes: bytes, cookie_name: str, ttl_seconds: int) -> bytes:
    """http_server.go:442-452."""
    return modify_html_content(
        page_bytes,
        f'"{cookie_name}=" + base64_cookie',
        f'"{cookie_name}=" + base64_cookie + ";max-age={ttl_seconds}"',
    )


def apply_cookie_domain(page_bytes: bytes, cookie_name: str) -> bytes:
    """http_server.go:454-464."""
    return modify_html_content(
        page_bytes,
        f'"{cookie_name}=" + base64_cookie',
        f'"{cookie_name}=" + base64_cookie + ";domain=" + window.location.hostname',
    )


def apply_args_to_password_page(page_bytes: bytes, roaming: bool, cookie_ttl: int) -> bytes:
    """http_server.go:466-477."""
    modified = apply_cookie_max_age(page_bytes, PASSWORD_COOKIE_NAME, cookie_ttl)
    if not roaming:
        return modified
    return apply_cookie_domain(modified, PASSWORD_COOKIE_NAME)


def apply_args_to_sha_inv_page(config: Config) -> bytes:
    """http_server.go:479-491."""
    modified = apply_cookie_max_age(
        config.challenger_bytes, CHALLENGE_COOKIE_NAME, config.sha_inv_cookie_ttl_seconds
    )
    return modify_html_content(
        modified, "new_solver(10)", f"new_solver({config.sha_inv_expected_zero_bits})"
    )
