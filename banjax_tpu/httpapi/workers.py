"""Multi-process SO_REUSEPORT serving for the HTTP request API.

The reference is a compiled Go server: one process, goroutine-per-request,
shared-memory state behind mutexes (/root/reference/internal/http_server.go:32,
rate_limit.go:105-156).  A single CPython event loop tops out near 1k
requests/sec on the same hardware, so the framework scales the request path
across N worker processes instead, preserving the reference's decision
semantics:

  * every process binds 127.0.0.1:8081 with SO_REUSEPORT — the kernel
    load-balances connections; nginx needs no config change;
  * the **failed-challenge rate limiter** — the one piece of state the hot
    path *writes* — lives in a native shared-memory table
    (native/shmstate.c), so an IP spreading failed challenges across
    workers is counted exactly once, like the reference's mutex-guarded
    map;
  * each worker keeps a **replica of the dynamic decision lists**, kept
    convergent by a primary→worker broadcast (the lists' monotonic-
    severity `update` makes replays/echoes idempotent);
  * every side effect with a single-writer invariant — ipset calls, kafka
    reports, ban-log lines, dynamic-list inserts — is forwarded
    worker→primary over a unix datagram control socket with the same
    drop-don't-block discipline as the reference's kafka channel
    (kafka.go:334-346);
  * the 7 cold routes (/decision_lists, /rate_limit_states, /is_banned,
    /ipset/list, /banned, /unban) are reverse-proxied to the primary over
    a unix HTTP socket, because only the primary owns the regex-rate-limit
    states, the ipset, and the authoritative lists.

`http_workers: 0` (the default) keeps the exact single-process behavior.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.effectors.banner import BannerInterface

log = logging.getLogger(__name__)

CONTROL_SOCK = "control.sock"
PRIMARY_HTTP_SOCK = "primary-http.sock"

# routes served by the primary only (worker reverse-proxies them)
COLD_ROUTES = (
    "/decision_lists",
    "/rate_limit_states",
    "/is_banned",
    "/ipset/list",
    "/banned",
    "/unban",
    "/healthz",
    # observability surface: the metrics registries, the trace ring, the
    # provenance ledger and the flight recorder live in the primary (the
    # pipeline/matcher run there)
    "/metrics",
    "/debug/trace",
    # fault-injection admin: failpoints are process-global module state
    # in the primary (the pipeline/matcher run there)
    "/debug/failpoints",
    "/decisions/explain",
    "/debug/incidents",
    # traffic introspection (obs/sketch.py): the sketch lives with the
    # matcher in the primary
    "/traffic/top",
)


def worker_sock_path(ctrl_dir: str, index: int) -> str:
    return os.path.join(ctrl_dir, f"worker-{index}.sock")


def _send_json(sock: socket.socket, path: str, msg: dict) -> None:
    """Fire-and-forget datagram; drops (never blocks) when the peer is gone
    or its buffer is full — the control plane inherits the kafka channel's
    drop-don't-block discipline."""
    try:
        sock.sendto(json.dumps(msg).encode(), path)
    except OSError as e:
        log.debug("control send to %s dropped: %s", path, e)


class ControlPlane:
    """Primary side: receives worker commands, broadcasts list deltas."""

    def __init__(self, ctrl_dir: str, app) -> None:
        self.ctrl_dir = ctrl_dir
        self._app = app  # BanjaxApp — executes forwarded side effects
        self._recv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._recv.bind(os.path.join(ctrl_dir, CONTROL_SOCK))
        self._recv.settimeout(0.5)
        self._send = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._send.setblocking(False)
        self._worker_paths: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_worker(self, index: int) -> str:
        path = worker_sock_path(self.ctrl_dir, index)
        self._worker_paths.append(path)
        return path

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._recv_loop, name="control-plane", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._recv.close()
        self._send.close()

    def broadcast(self, msg: dict) -> None:
        for path in self._worker_paths:
            _send_json(self._send, path, msg)

    # --- worker→primary command execution ---

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data = self._recv.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._handle(json.loads(data))
            except Exception as e:  # noqa: BLE001 — a bad datagram must not
                # kill the control plane
                log.warning("control plane: bad command: %s", e)

    def _handle(self, msg: dict) -> None:
        op = msg.get("op")
        app = self._app
        if op == "ban_or_challenge":
            app.banner.ban_or_challenge_ip(
                app.config_holder.get(), msg["ip"],
                Decision(int(msg["decision"])), msg["domain"],
            )
            # the worker recorded the chain-side provenance in ITS
            # process; the primary (which owns /decisions/explain)
            # ledgers the authoritative insert it just applied
            from banjax_tpu.obs import provenance

            provenance.record(
                provenance.SOURCE_CHALLENGE, msg["ip"],
                Decision(int(msg["decision"])), rule="worker-forwarded",
            )
        elif op == "fc_log":
            app.banner.log_failed_challenge_ban(
                app.config_holder.get(), msg["ip"], msg["challenge_type"],
                msg["host"], msg["path"], int(msg["threshold"]), msg["ua"],
                Decision(int(msg["decision"])), msg["method"],
            )
        elif op == "kafka":
            from banjax_tpu.ingest import reports

            # re-inject the worker's report into the primary's queue with
            # the same drop-don't-block put
            try:
                reports.get_message_queue().put_nowait(
                    msg["data"].encode("utf-8")
                )
            except Exception:  # noqa: BLE001 — queue.Full: drop
                log.debug("KAFKA: dropped forwarded worker report")
        else:
            log.warning("control plane: unknown op %r", op)


class ReplicatedDynamicLists(DynamicDecisionLists):
    """Primary's dynamic lists: every mutation also broadcasts a delta so
    worker replicas converge.  Monotonic-severity `update` makes the
    originator-applies-locally + broadcast-echo pattern idempotent."""

    def __init__(self, start_sweeper: bool = True):
        super().__init__(start_sweeper=start_sweeper)
        self._broadcast: Optional[Callable[[dict], None]] = None

    def set_broadcast(self, fn: Callable[[dict], None]) -> None:
        self._broadcast = fn

    def _emit(self, msg: dict) -> None:
        if self._broadcast is not None:
            self._broadcast(msg)

    def update(self, ip, expires, new_decision, from_baskerville, domain):
        super().update(ip, expires, new_decision, from_baskerville, domain)
        self._emit({
            "op": "dyn_update", "ip": ip, "expires": expires,
            "decision": int(new_decision),
            "from_baskerville": from_baskerville, "domain": domain,
        })

    def update_by_session_id(self, ip, session_id, expires, new_decision,
                             from_baskerville, domain):
        super().update_by_session_id(
            ip, session_id, expires, new_decision, from_baskerville, domain
        )
        self._emit({
            "op": "dyn_update_session", "ip": ip, "session_id": session_id,
            "expires": expires, "decision": int(new_decision),
            "from_baskerville": from_baskerville, "domain": domain,
        })

    def remove_by_ip(self, ip):
        super().remove_by_ip(ip)
        self._emit({"op": "dyn_remove", "ip": ip})

    def clear(self):
        super().clear()
        self._emit({"op": "dyn_clear"})


class WorkerControl:
    """Worker side: forwards side effects to the primary; applies
    primary broadcasts to the local replica."""

    def __init__(self, ctrl_dir: str, index: int,
                 replica: DynamicDecisionLists,
                 on_reload: Callable[[], None]) -> None:
        self._primary_path = os.path.join(ctrl_dir, CONTROL_SOCK)
        self._send = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._send.setblocking(False)
        self._recv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        path = worker_sock_path(ctrl_dir, index)
        if os.path.exists(path):
            os.unlink(path)
        self._recv.bind(path)
        self._recv.settimeout(0.5)
        self._replica = replica
        self._on_reload = on_reload
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._recv_loop, name="worker-control", daemon=True
        )
        self._thread.start()

    def send(self, msg: dict) -> None:
        _send_json(self._send, self._primary_path, msg)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._recv.close()
        self._send.close()

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data = self._recv.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._apply(json.loads(data))
            except Exception as e:  # noqa: BLE001
                log.warning("worker control: bad broadcast: %s", e)

    def _apply(self, msg: dict) -> None:
        op = msg.get("op")
        if op == "dyn_update":
            self._replica.update(
                msg["ip"], float(msg["expires"]), Decision(int(msg["decision"])),
                bool(msg["from_baskerville"]), msg["domain"],
            )
        elif op == "dyn_update_session":
            self._replica.update_by_session_id(
                msg["ip"], msg["session_id"], float(msg["expires"]),
                Decision(int(msg["decision"])),
                bool(msg["from_baskerville"]), msg["domain"],
            )
        elif op == "dyn_remove":
            self._replica.remove_by_ip(msg["ip"])
        elif op == "dyn_clear":
            self._replica.clear()
        elif op == "reload":
            self._on_reload()
        else:
            log.warning("worker control: unknown op %r", op)


class RemoteBanner(BannerInterface):
    """Worker-side banner: applies the list effect locally for immediate
    visibility on THIS worker, forwards the authoritative side effects
    (ipset, ban log, kafka ip_banned report, broadcast) to the primary."""

    def __init__(self, control: WorkerControl,
                 replica: DynamicDecisionLists) -> None:
        self._control = control
        self._replica = replica

    def ban_or_challenge_ip(self, config, ip, decision, domain):
        expires = time.time() + config.expiring_decision_ttl_seconds
        self._replica.update(ip, expires, decision, False, domain)
        self._control.send({
            "op": "ban_or_challenge", "ip": ip, "decision": int(decision),
            "domain": domain,
        })

    def log_regex_ban(self, config, log_time_unix, ip, rule_name,
                      log_line_rest, decision):
        # regex bans originate in the primary's matcher pipeline; a worker
        # never takes this path, but forward defensively rather than drop
        log.warning("RemoteBanner.log_regex_ban called in a worker (unexpected)")

    def log_failed_challenge_ban(self, config, ip, challenge_type, host, path,
                                 too_many_failed_challenges_threshold,
                                 user_agent, decision, method):
        self._control.send({
            "op": "fc_log", "ip": ip, "challenge_type": challenge_type,
            "host": host, "path": path,
            "threshold": too_many_failed_challenges_threshold,
            "ua": user_agent, "decision": int(decision), "method": method,
        })

    # ipset is primary-owned; the routes that need it are proxied there.
    def ipset_add(self, config, ip):
        log.warning("RemoteBanner.ipset_add called in a worker (unexpected)")

    def ipset_test(self, config, ip):
        return False

    def ipset_list(self):
        return []

    def ipset_del(self, ip):
        log.warning("RemoteBanner.ipset_del called in a worker (unexpected)")


class PrimarySupervisor:
    """Owns worker subprocesses + the control plane, from the primary.

    A monitor thread respawns any worker that dies (crash, OOM-kill) with
    exponential backoff per worker slot — the serving capacity heals
    instead of silently degrading.  A respawned worker rebuilds its
    decision-list replica from the primary's broadcasts going forward;
    stale entries it missed while down converge via the next reload or
    expire on their TTLs (monotonic-severity updates make the partial
    window safe: it can only under-block briefly, exactly like the
    reference restarting)."""

    RESPAWN_BACKOFF_S = (1.0, 2.0, 4.0, 8.0, 16.0)
    MONITOR_INTERVAL_S = 1.0

    def __init__(self, app, ctrl_dir: str, n_workers: int,
                 health=None) -> None:
        self.ctrl_dir = ctrl_dir
        self.n_workers = n_workers
        self.control = ControlPlane(ctrl_dir, app)
        self._app = app
        self._procs: List[subprocess.Popen] = []
        self._respawns = [0] * n_workers
        self._next_spawn_ok = [0.0] * n_workers
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.health = health  # resilience.health.ComponentHealth

    def primary_http_sock(self) -> str:
        return os.path.join(self.ctrl_dir, PRIMARY_HTTP_SOCK)

    def _spawn_one(self, index: int) -> subprocess.Popen:
        config = self._app.config_holder.get()
        cmd = [
            sys.executable, "-m", "banjax_tpu.httpapi.worker_serve",
            "-config-file", self._app.config_holder.path,
            "-ctrl-dir", self.ctrl_dir,
            "-index", str(index),
            "-shm-name", self._app.failed_challenge_states.name,
        ]
        dt = getattr(self._app, "decision_table", None)
        if dt is not None and getattr(dt, "name", None):
            # workers attach the serving decision table read-only by name
            cmd += ["-dt-shm-name", dt.name]
        if config.standalone_testing:
            cmd.append("-standalone-testing")
        if config.debug:
            cmd.append("-debug")
        env = dict(os.environ)
        # workers never touch jax; keep their footprint host-only
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the package may be run from a source tree (not installed):
        # make sure the worker can import banjax_tpu
        import banjax_tpu

        pkg_root = os.path.dirname(os.path.dirname(banjax_tpu.__file__))
        parts = [pkg_root] + (
            env.get("PYTHONPATH", "").split(os.pathsep)
            if env.get("PYTHONPATH") else []
        )
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        return subprocess.Popen(cmd, env=env)

    def spawn_workers(self) -> None:
        for i in range(self.n_workers):
            self.control.add_worker(i)
            self._procs.append(self._spawn_one(i))
        self.control.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="worker-monitor", daemon=True
        )
        self._monitor.start()
        log.info("spawned %d http workers (ctrl %s)", self.n_workers, self.ctrl_dir)

    def kill_worker(self, index: int, sig: int = 9) -> None:
        """Fault-injection hook (tests/faults/): deliver `sig` (default
        SIGKILL — the un-maskable OOM-kill shape) to one worker and let the
        monitor heal it."""
        proc = self._procs[index]
        if proc.poll() is None:
            os.kill(proc.pid, sig)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.MONITOR_INTERVAL_S):
            down = sum(1 for p in self._procs if p.poll() is not None)
            if self.health is not None:
                if down:
                    self.health.degraded(
                        f"{down}/{self.n_workers} http workers down "
                        "(respawning)"
                    )
                else:
                    self.health.ok()
            for i, proc in enumerate(self._procs):
                try:
                    if proc.poll() is None:
                        continue
                    now = time.monotonic()
                    if now < self._next_spawn_ok[i]:
                        continue
                    n = self._respawns[i]
                    backoff = self.RESPAWN_BACKOFF_S[
                        min(n, len(self.RESPAWN_BACKOFF_S) - 1)
                    ]
                    self._next_spawn_ok[i] = now + backoff
                    self._respawns[i] = n + 1
                    log.warning(
                        "http worker %d exited rc=%s — respawning (attempt "
                        "%d, next backoff %.0fs)",
                        i, proc.returncode, n + 1, backoff,
                    )
                    self._procs[i] = self._spawn_one(i)
                except Exception as e:  # noqa: BLE001 — a failed spawn
                    # (fork EAGAIN under memory pressure) must not kill the
                    # monitor; the slot retries after its backoff
                    log.error("worker %d respawn failed: %s", i, e)

    @property
    def respawn_count(self) -> int:
        return sum(self._respawns)

    def broadcast_reload(self) -> None:
        self.control.broadcast({"op": "reload"})

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=3)
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self.control.stop()
        import shutil

        shutil.rmtree(self.ctrl_dir, ignore_errors=True)


def install_proxy_routes(app, primary_sock: str) -> None:
    """Register reverse-proxy handlers for the primary-owned cold routes
    on a worker's aiohttp application."""
    import aiohttp
    from aiohttp import web

    state: dict = {"session": None}

    async def _open_session(app_):
        # created on startup (inside the running loop) — a lazy
        # check-then-set in the handler could race two first requests and
        # leak a session
        state["session"] = aiohttp.ClientSession(
            connector=aiohttp.UnixConnector(path=primary_sock)
        )

    app.on_startup.append(_open_session)

    async def proxy(request: web.Request) -> web.Response:
        sess = state["session"]
        body = await request.read()
        try:
            async with sess.request(
                request.method, f"http://primary{request.rel_url}",
                headers=request.headers, data=body,
                timeout=aiohttp.ClientTimeout(total=10),
            ) as r:
                payload = await r.read()
                resp = web.Response(status=r.status, body=payload)
                ct = r.headers.get("Content-Type")
                if ct:
                    resp.headers["Content-Type"] = ct
                return resp
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return web.Response(status=502, text=f"primary unavailable: {e}\n")

    for route in COLD_ROUTES:
        for method in ("GET", "POST"):
            try:
                app.router.add_route(method, route, proxy)
            except RuntimeError:
                pass  # duplicate method registration

    async def _close_session(app_):
        if state["session"] is not None:
            await state["session"].close()

    app.on_cleanup.append(_close_session)
