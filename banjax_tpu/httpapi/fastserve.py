"""Native asyncio-protocol server for the /auth_request hot path.

The reference hot path is a compiled Go/gin handler
(/root/reference/internal/http_server.go:171-214); an aiohttp handler
spends ~60% of its per-request time in framework internals (routing,
Request/Response objects, header classes — PERF.md r5 addendum).  This
module serves the hot routes straight from an `asyncio.Protocol`: a
hand-rolled HTTP/1.1 request parser over bytes, the same decision chain,
and direct response serialization — ~2-3x the requests/sec of the aiohttp
path on one core, with the identical wire contract (differential-tested
against the aiohttp app in
tests/integration/test_fastserve_differential.py).

Routes served natively: /auth_request (the nginx subrequest), /info, and
/favicon.ico (standalone).  Every other route — the introspection/admin
set and the debug endpoints — is RAW-PROXIED over a unix socket to the
full aiohttp application (the primary's, in multi-worker mode; a local
unix listener otherwise), so the complete API surface stays reachable on
127.0.0.1:8081 regardless of mode.  `http_fast_path: false` restores the
pure-aiohttp layout.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional
from urllib.parse import parse_qs

from banjax_tpu.httpapi.decision_chain import (
    ChainState,
    DecisionListResult,
    RequestInfo,
    Response,
    decision_for_nginx,
)
from banjax_tpu.utils import go_query_escape, go_query_unescape

log = logging.getLogger(__name__)

_REASONS = {
    200: "OK", 201: "Created", 204: "No Content", 301: "Moved Permanently",
    302: "Found", 400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed", 429: "Too Many Requests",
    413: "Request Entity Too Large",
    500: "Internal Server Error", 502: "Bad Gateway", 501: "Not Implemented",
}

MAX_HEADER_BYTES = 65536
MAX_BODY_BYTES = 10 * 1024 * 1024


def _reason(status: int) -> str:
    return _REASONS.get(status, "Unknown")


def _clean_header(value) -> str:
    """CR/LF-sanitize a header name or value (aiohttp rejects them; the
    hand-rolled serializer must too).  The fail-open 500 path puts raw
    exception text — which can embed client-controlled bytes — into
    X-Banjax-Error, so unsanitized \\r\\n here is a response-splitting
    vector."""
    s = str(value)
    if "\r" in s or "\n" in s:
        s = s.replace("\r", " ").replace("\n", " ")
    return s


def serialize_response(resp: Response, keep_alive: bool,
                       head_only: bool = False) -> bytes:
    """Response dataclass → HTTP/1.1 bytes (matches what the aiohttp app
    emits for the same Response: status, bare content_type, custom
    headers, gin-escaped cookies).  head_only keeps Content-Length but
    suppresses the body bytes (RFC 7230 HEAD semantics)."""
    body = resp.body if isinstance(resp.body, bytes) else str(resp.body).encode()
    # no charset suffix: the aiohttp app emits the bare content_type for
    # byte bodies (differential-tested)
    lines = [
        f"HTTP/1.1 {resp.status} {_reason(resp.status)}",
        f"Content-Type: {resp.content_type}",
        f"Content-Length: {len(body)}",
    ]
    for k, v in resp.headers.items():
        lines.append(f"{_clean_header(k)}: {_clean_header(v)}")
    for c in resp.cookies:
        attrs = [f"{c.name}={go_query_escape(c.value)}"]
        # `is not None`: Max-Age=0 (immediate expiry) must reach the wire —
        # the aiohttp layout emits it, and a bare `if c.max_age:` turned it
        # into a session cookie on this layout (ADVICE r5)
        if c.max_age is not None:
            attrs.append(f"Max-Age={c.max_age}")
        if c.domain:
            attrs.append(f"Domain={c.domain}")
        attrs.append(f"Path={c.path}")
        if c.secure:
            attrs.append("Secure")
        if c.http_only:
            attrs.append("HttpOnly")
        lines.append("Set-Cookie: " + _clean_header("; ".join(attrs)))
    lines.append("Connection: keep-alive" if keep_alive else "Connection: close")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode()
    return head if head_only else head + body


class _ParsedRequest:
    __slots__ = ("method", "path", "query", "headers", "body",
                 "keep_alive", "raw_head")

    def __init__(self, method, path, query, headers, body,
                 keep_alive, raw_head):
        self.method = method
        self.path = path              # str, decoded-less path component
        self.query = query            # raw query string (str)
        self.headers = headers        # dict[str(lower), str]
        self.body = body              # bytes
        self.keep_alive = keep_alive
        self.raw_head = raw_head      # bytes, original head incl. final CRLFCRLF

    def header(self, name: str) -> str:
        return self.headers.get(name, "")

    def query_param(self, name: str) -> str:
        if not self.query:
            return ""
        vals = parse_qs(self.query, keep_blank_values=True).get(name)
        return vals[0] if vals else ""


class FastHttpProtocol(asyncio.Protocol):
    """One instance per connection.

    Hot requests are parsed and answered INLINE in data_received — the
    decision chain is synchronous, so the common case costs zero task
    switches.  The first cold (proxied) request flips the connection into
    task mode: an event-driven loop that preserves request ordering and
    awaits the upstream."""

    def __init__(self, server: "FastPathServer"):
        self.server = server
        self.transport: Optional[asyncio.Transport] = None
        self.buf = bytearray()
        self.peer = ""
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._closed = False
        self._task_mode = False

    # --- asyncio.Protocol ---

    def connection_made(self, transport) -> None:
        self.transport = transport
        sock = transport.get_extra_info("socket")
        if sock is not None:
            import socket as _socket

            try:
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            except OSError:
                pass
        peername = transport.get_extra_info("peername")
        self.peer = peername[0] if peername else "127.0.0.1"

    def data_received(self, data: bytes) -> None:
        self.buf.extend(data)
        if self._task_mode:
            self._wake.set()
            return
        self._drain_inline()

    def connection_lost(self, exc) -> None:
        self._closed = True
        if self._wake is not None:
            self._wake.set()
        if self._task is not None:
            self._task.cancel()

    def eof_received(self):
        self._closed = True
        if self._wake is not None:
            self._wake.set()
        return False

    # --- inline fast path ---

    def _drain_inline(self) -> None:
        while True:
            req = self._try_parse()
            if req is None:
                # cap an endless header stream (task mode has the same
                # check in _next_request)
                if (b"\r\n\r\n" not in self.buf
                        and len(self.buf) > MAX_HEADER_BYTES):
                    self.write(serialize_response(
                        Response(status=400, body=b"header block too large"),
                        False))
                    self.transport.close()
                return
            if self.server.is_hot(req):
                self._handle_sync(req)
                if not req.keep_alive:
                    self.transport.close()
                    return
            else:
                self._enter_task_mode(req)
                return

    def _handle_sync(self, req: "_ParsedRequest") -> None:
        try:
            self.server.handle_hot(self, req)
        except Exception as e:  # noqa: BLE001 — the fail-open recovery
            # contract (http_server.go:110-135)
            import traceback

            tb = traceback.extract_tb(e.__traceback__)
            loc = f"{tb[-1].filename}:{tb[-1].lineno}" if tb else "?"
            log.error("fastserve handler panic: %s (%s)", e, loc)
            resp = Response(status=500, headers={
                "X-Banjax-Error": f"{e} ({loc})",
                "X-Accel-Redirect": "@fail_open",
            })
            self.write(serialize_response(resp, req.keep_alive))

    def _enter_task_mode(self, first_req: "_ParsedRequest") -> None:
        self._task_mode = True
        self._wake = asyncio.Event()
        self._task = asyncio.ensure_future(self._run(first_req))

    # --- task mode (proxied requests / slow bodies) ---

    async def _run(self, pending: Optional["_ParsedRequest"]) -> None:
        try:
            while not self._closed:
                req = pending
                pending = None
                if req is None:
                    req = await self._next_request()
                if req is None:
                    break
                if self.server.is_hot(req):
                    self._handle_sync(req)
                else:
                    try:
                        await self.server.proxy(self, req)
                    except Exception as e:  # noqa: BLE001 — fail open
                        log.error("fastserve proxy panic: %s", e)
                        self.write(serialize_response(
                            Response(status=502,
                                     body=f"proxy error: {e}\n".encode()),
                            False,
                        ))
                        break
                if not req.keep_alive:
                    break
        except asyncio.CancelledError:
            pass
        finally:
            if self.transport is not None and not self.transport.is_closing():
                self.transport.close()

    async def _next_request(self) -> Optional[_ParsedRequest]:
        while True:
            req = self._try_parse()
            if req is not None:
                return req
            if self._closed:
                return None
            if len(self.buf) > MAX_HEADER_BYTES:
                self.write(serialize_response(
                    Response(status=400, body=b"header block too large"),
                    False))
                return None
            self._wake.clear()
            await self._wake.wait()

    # --- shared parser: consumes from buf ONLY when a complete request
    # (head + body) is buffered; returns None otherwise ---

    def _try_parse(self) -> Optional[_ParsedRequest]:
        end = self.buf.find(b"\r\n\r\n")
        if end < 0:
            return None
        head_len = end + 4
        try:
            head = bytes(self.buf[:end]).decode("latin-1")
            req_line, *hdr_lines = head.split("\r\n")
            method, target, version = req_line.split(" ", 2)
            headers = {}
            for hl in hdr_lines:
                k, _, v = hl.partition(":")
                k = k.strip().lower()
                if k == "content-length" and k in headers \
                        and headers[k] != v.strip():
                    # conflicting Content-Length values: reject (RFC 7230)
                    # — last-wins would reopen the body-smuggling desync
                    # the Transfer-Encoding guard below closes
                    raise ValueError("conflicting content-length")
                headers[k] = v.strip()
        except ValueError:
            self.write(serialize_response(
                Response(status=400, body=b"bad request"), False))
            self.transport.close()
            return None
        if "transfer-encoding" in headers:
            # no chunked-request support: accepting the head with clen=0
            # would leave the chunked body in the buffer to be re-parsed
            # as a smuggled pipelined request
            self.write(serialize_response(
                Response(status=501, body=b"transfer-encoding unsupported"),
                False))
            self.transport.close()
            return None
        clen = 0
        if "content-length" in headers:
            try:
                clen = int(headers["content-length"])
            except ValueError:
                clen = -1
            if clen < 0 or clen > MAX_BODY_BYTES:
                # reject outright — clamping would leave body bytes in the
                # buffer to be re-parsed as a smuggled pipelined request
                status = 413 if clen > MAX_BODY_BYTES else 400
                self.write(serialize_response(
                    Response(status=status, body=b"bad content-length"),
                    False))
                self.transport.close()
                return None
        if len(self.buf) < head_len + clen:
            return None  # body not fully buffered yet
        raw_head = bytes(self.buf[:head_len])
        body = bytes(self.buf[head_len : head_len + clen])
        del self.buf[: head_len + clen]
        path, _, query = target.partition("?")
        conn = headers.get("connection", "").lower()
        keep_alive = (version == "HTTP/1.1" and conn != "close") or (
            conn == "keep-alive"
        )
        return _ParsedRequest(method, path, query, headers, body,
                              keep_alive, raw_head)

    def write(self, data: bytes) -> None:
        if self.transport is not None and not self.transport.is_closing():
            self.transport.write(data)


class FastPathServer:
    """Builds native handlers from ServerDeps; owns the upstream proxy."""

    def __init__(self, deps, proxy_sock: str,
                 coalesced_gin=None, coalesced_server=None,
                 listen_host: str = "127.0.0.1"):
        self.deps = deps
        self.proxy_sock = proxy_sock
        self.gin_log = coalesced_gin
        self.server_log = coalesced_server
        self.listen_host = listen_host  # admin-surface auth gate input
        config0 = deps.config_holder.get()
        self.standalone = config0.standalone_testing
        # compiled /auth_request fast path: decision-table hit → template
        # bytes, anything else (miss / fault / ineligible) → the chain
        from banjax_tpu.httpapi.fastpath import AuthFastPath

        self.fastpath = AuthFastPath(deps)

    # ------------------------------------------------------------- handle

    def is_hot(self, req: _ParsedRequest) -> bool:
        # exact route + method matching, mirroring the aiohttp router:
        # /auth_request is ANY-method; /info, /healthz and /favicon.ico are
        # GET-only (other methods proxy upstream and get aiohttp's 405/404)
        path = req.path
        if path == "/auth_request":
            return True
        if req.method != "GET":
            return False
        if path == "/healthz" and self.deps.health is not None:
            # served natively so health stays answerable even when the
            # aiohttp upstream is the thing that is wedged; a worker
            # (health is None there) proxies it to the primary instead
            return True
        return path == "/info" or (self.standalone and path == "/favicon.ico")

    def handle_hot(self, proto: FastHttpProtocol, req: _ParsedRequest) -> None:
        start = time.monotonic()
        path = req.path

        # --- standalone middleware (http_server.go:137-169) ---
        if self.standalone:
            client_ip = req.header("x-client-ip") or proto.peer or "127.0.0.1"
            query_path = req.query_param("path")  # parsed once per request
            # req.headers is built fresh per request in _try_parse — safe
            # to update in place (the reference mutates its shared header
            # map the same way)
            req.headers.update({
                "x-client-ip": client_ip,
                "x-requested-host": req.header("host"),
                "x-requested-path": query_path,
                "x-client-user-agent": req.header("x-client-user-agent")
                or "mozilla",
            })
            if self.server_log is not None:
                self.server_log.write(
                    "%f %s %s %s %s %s HTTP/1.1 %s\n"
                    % (
                        float(int(time.time())),
                        client_ip,
                        req.method,
                        req.header("host"),
                        req.method,
                        query_path,
                        req.header("user-agent"),
                    )
                )

        if path == "/info":
            body = json.dumps({
                "config_version": self.deps.config_holder.get().config_version
            }).encode()
            # aiohttp's json_response content type, charset included
            resp = Response(status=200, body=body,
                            content_type="application/json; charset=utf-8")
        elif path == "/healthz":
            # same admin gate as the aiohttp layout (server.admin_auth_ok):
            # bearer-token required when the listener binds non-loopback
            from banjax_tpu.httpapi.server import admin_auth_ok

            if not admin_auth_ok(
                self.deps.config_holder.get(), self.listen_host,
                req.header("authorization"),
            ):
                resp = Response(
                    status=401,
                    body=b'{"error": "unauthorized"}',
                    content_type="application/json; charset=utf-8",
                    headers={"WWW-Authenticate": "Bearer"},
                )
            else:
                snap = self.deps.health.snapshot()
                resp = Response(
                    status=503 if snap["status"] == "failed" else 200,
                    body=json.dumps(snap).encode(),
                    content_type="application/json; charset=utf-8",
                )
        elif path == "/favicon.ico":
            # the aiohttp route uses web.Response(text="") — charset added
            resp = Response(status=200, body=b"",
                            content_type="text/plain; charset=utf-8")
        else:
            fast = self.fastpath.try_serve(req)
            if fast is not None:
                raw, status = fast
                proto.write(raw)
                self._access_log(req, path, status, start)
                return
            resp = self._auth_request(req)
        proto.write(serialize_response(
            resp, req.keep_alive, head_only=req.method == "HEAD"
        ))
        self._access_log(req, path, resp.status, start)

    def _access_log(self, req: _ParsedRequest, path: str, status: int,
                    start: float) -> None:
        """Access log middleware (http_server.go:65-95) — shared by the
        template fast path and the full-chain path so both emit the same
        gin-shaped line."""
        if self.gin_log is not None:
            latency_us = int((time.monotonic() - start) * 1e6)
            line = {
                "Time": time.strftime("%a, %d %b %Y %H:%M:%S %Z"),
                "ClientIp": req.header("x-client-ip"),
                "ClientReqHost": req.header("x-requested-host"),
                "ClientReqPath": req.header("x-requested-path"),
                "Method": req.method,
                "Path": path,
                "Status": status,
                "Latency": latency_us,
            }
            self.gin_log.write(json.dumps(line) + "\n")

    def _auth_request(self, req: _ParsedRequest) -> Response:
        deps = self.deps
        config = deps.config_holder.get()
        cookies = {}
        raw = req.header("cookie")
        if raw:
            for part in raw.split(";"):
                name, eq, value = part.strip().partition("=")
                if not eq:
                    continue
                try:
                    # gin reads cookies through url.QueryUnescape; a value
                    # whose unescape fails is treated as absent
                    cookies[name] = go_query_unescape(value)
                except ValueError:
                    continue
        info = RequestInfo(
            client_ip=req.header("x-client-ip"),
            requested_host=req.header("x-requested-host"),
            requested_path=req.header("x-requested-path"),
            client_user_agent=req.header("x-client-user-agent"),
            method=req.method,
            cookies=cookies,
        )
        state = ChainState(
            config=config,
            static_lists=deps.static_lists,
            dynamic_lists=deps.dynamic_lists,
            protected_paths=deps.protected_paths,
            failed_challenge_states=deps.failed_challenge_states,
            banner=deps.banner,
            challenge_verifier=getattr(deps, "challenge_verifier", None),
        )
        resp, result = decision_for_nginx(state, info)
        if config.debug:
            log.info("decisionForNginx: %s", result.to_json())
        elif result.decision_list_result != DecisionListResult.NO_MENTION:
            log.info("decisionForNginx: %s", result.to_json())
        return resp

    # -------------------------------------------------------------- proxy

    async def proxy(self, proto: FastHttpProtocol, req: _ParsedRequest) -> None:
        """Forward the request verbatim to the aiohttp app on the unix
        socket and relay the response bytes back."""
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_unix_connection(self.proxy_sock), timeout=10
            )
        except (OSError, asyncio.TimeoutError) as e:
            proto.write(serialize_response(
                Response(status=502, body=f"upstream unavailable: {e}\n".encode()),
                req.keep_alive,
            ))
            return
        try:
            writer.write(req.raw_head + req.body)
            await writer.drain()
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout=60
            )
            proto.write(head)
            hdr_text = head[:-4].decode("latin-1").lower()
            clen = None
            chunked = "transfer-encoding: chunked" in hdr_text
            for line in hdr_text.split("\r\n")[1:]:
                if line.startswith("content-length:"):
                    clen = int(line.split(":", 1)[1])
            if req.method == "HEAD":
                pass  # header-only response; no body follows Content-Length
            elif chunked:
                while True:
                    size_line = await asyncio.wait_for(
                        reader.readline(), timeout=60
                    )
                    proto.write(size_line)
                    size = int(size_line.strip() or b"0", 16)
                    chunk = await asyncio.wait_for(
                        reader.readexactly(size + 2), timeout=60
                    )
                    proto.write(chunk)
                    if size == 0:
                        break
            elif clen:
                remaining = clen
                while remaining > 0:
                    chunk = await asyncio.wait_for(
                        reader.read(min(65536, remaining)), timeout=60
                    )
                    if not chunk:
                        break
                    proto.write(chunk)
                    remaining -= len(chunk)
        except (OSError, asyncio.IncompleteReadError, asyncio.TimeoutError,
                ValueError) as e:
            log.warning("fastserve proxy error: %s", e)
            proto.write(serialize_response(
                Response(status=502, body=f"upstream error: {e}\n".encode()),
                False,
            ))
            if proto.transport is not None:
                proto.transport.close()
        finally:
            writer.close()


async def start_fast_server(deps, proxy_sock: str, host: str, port: int,
                            reuse_port: bool = False,
                            coalesced_gin=None, coalesced_server=None):
    """Bind the fast-path protocol server; returns the asyncio Server."""
    fps = FastPathServer(deps, proxy_sock, coalesced_gin, coalesced_server,
                         listen_host=host)
    loop = asyncio.get_running_loop()
    server = await loop.create_server(
        lambda: FastHttpProtocol(fps), host, port,
        reuse_port=reuse_port or None,
    )
    return server
