"""Compiled /auth_request fast path: decision-table hit → byte template.

The serving twin of the reference escalating already-decided IPs out of
userspace: before fastserve runs the nine-step Python decision chain, it
consults the native shm decision table (native/decisiontable.py) that
the dynamic lists mirror into.  A hit on an eligible request serializes
the response straight from prebuilt byte templates — one lock-free C
probe, one session-cookie HMAC, a handful of joins — instead of the
full `decision_for_nginx` walk.

Byte-identity is the contract, not best-effort: a template response must
equal `serialize_response(decision_for_nginx(...))` bit for bit (status
line, header order, X-Accel-Redirect, cookies), and the differential
suite (tests/integration/test_fastpath_differential.py) plus the bench
witness (`bench.py --serve`) hold it there.  Anything the templates
cannot reproduce — password cookies, per-site static lists, sitewide
sha-inv path exceptions, session-id entries, baskerville-disabled hosts
— is an ELIGIBILITY miss, and the unchanged chain serves it.

Every exit is fail-open: a table fault, a torn read, an armed
`serve.fastpath.lookup` failpoint, or any unexpected error only ever
means "the chain serves this request".  Misses and hits are counted per
reason/tier in httpapi/serve_stats.py (banjax_serve_fastpath_*).
"""

from __future__ import annotations

import json
import logging
import struct
import time
from typing import Optional, Tuple

from banjax_tpu.crypto._b64 import decode_cookie_b64
from banjax_tpu.crypto.session import (
    SESSION_COOKIE_NAME,
    SessionCookieError,
    new_session_cookie,
    validate_session_cookie,
)
from banjax_tpu.decisions.model import Decision, FailAction
from banjax_tpu.httpapi.rewrite import PASSWORD_COOKIE_NAME
from banjax_tpu.httpapi.serve_stats import get_stats
from banjax_tpu.resilience import failpoints
from banjax_tpu.utils import go_query_escape, go_query_unescape

log = logging.getLogger(__name__)

_GRANTED_BODY = b"access granted\n"
_DENIED_BODY = b"access denied\n"
_UNSET = object()


class _Gen:
    """Everything derived from one config generation, precompiled once:
    the byte templates and the eligibility gates.  Rebuilt whenever the
    config object identity changes (hot reload swaps the object)."""

    __slots__ = (
        "config", "enabled", "secret", "ttl", "not_verify",
        "granted_head", "denied_head", "setcookie_prefix",
        "setcookie_mid", "conn_keep", "conn_close",
        "has_global_ip", "has_global_ua",
        "password_hosts", "list_hosts", "sha_exc_hosts", "bask_disabled",
        "debug", "session_cache", "global_ip_cache", "global_ua_cache",
        "unescape_cache",
    )

    # bound for the per-generation memo dicts below; hitting it clears
    # the dict (O(1), rare) rather than evicting
    CACHE_MAX = 8192

    def __init__(self, config):
        self.config = config
        self.enabled = bool(getattr(config, "serve_fastpath_enabled", True))
        self.secret = config.session_cookie_hmac_secret
        self.ttl = config.session_cookie_ttl_seconds
        self.not_verify = bool(config.session_cookie_not_verify)
        self.debug = bool(config.debug)
        # template heads run through the static half of the wire layout
        # (serialize_response order: status, CT, CL, headers, cookies,
        # Connection); the session headers are spliced per request
        self.granted_head = (
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: text/plain\r\n"
            f"Content-Length: {len(_GRANTED_BODY)}\r\n"
            "X-Banjax-Decision: ExpiringAccessGranted\r\n"
            "X-Accel-Redirect: @access_granted\r\n"
            "X-Deflect-Session: "
        ).encode()
        self.denied_head = (
            "HTTP/1.1 403 Forbidden\r\n"
            "Content-Type: text/plain\r\n"
            f"Content-Length: {len(_DENIED_BODY)}\r\n"
            "X-Banjax-Decision: ExpiringBlock\r\n"
            "Cache-Control: no-cache,no-store\r\n"
            "X-Accel-Redirect: @access_denied\r\n"
            "X-Deflect-Session: "
        ).encode()
        self.setcookie_prefix = f"Set-Cookie: {SESSION_COOKIE_NAME}=".encode()
        self.setcookie_mid = f"; Max-Age={self.ttl}; Path=/; HttpOnly\r\n".encode()
        self.conn_keep = b"Connection: keep-alive\r\n\r\n"
        self.conn_close = b"Connection: close\r\n\r\n"
        # eligibility gates: any host with per-site state goes to the
        # chain (steps 2-4 could fire); sha-inv path exceptions make a
        # CHALLENGE hit path-dependent (step 7's prefix check)
        self.has_global_ip = bool(config.global_decision_lists)
        self.has_global_ua = bool(config.global_user_agent_decision_lists)
        self.password_hosts = frozenset(config.password_protected_paths) | \
            frozenset(config.password_protected_path_exceptions)
        self.list_hosts = frozenset(config.per_site_decision_lists) | \
            frozenset(config.per_site_user_agent_decision_lists)
        self.sha_exc_hosts = frozenset(config.sha_inv_path_exceptions)
        self.bask_disabled = frozenset(config.sites_to_disable_baskerville)
        # per-generation memos (all invalidated with the generation):
        #   session_cache: (url-decoded cookie, ip) -> embedded expiry.
        #     A cookie that validated once stays valid until the expiry
        #     baked into its own bytes (there is no revocation), so the
        #     steady-state echo path pays a dict probe, not an HMAC.
        #   global_ip_cache / global_ua_cache: the step-5/6 static-list
        #     probes are pure functions of the config generation.
        #   unescape_cache: escaped value -> QueryUnescape(value), None on
        #     reject.  A pure function; session cookies always carry %3D
        #     padding so a repeat bearer pays a dict probe, not the
        #     per-char unescape walk.
        self.session_cache = {}
        self.global_ip_cache = {}
        self.global_ua_cache = {}
        self.unescape_cache = {}

    def unescape(self, value: str):
        """Memoized go_query_unescape; None where it raises ValueError."""
        cache = self.unescape_cache
        out = cache.get(value, _UNSET)
        if out is _UNSET:
            try:
                out = go_query_unescape(value)
            except ValueError:
                out = None
            if len(cache) >= self.CACHE_MAX:
                cache.clear()
            cache[value] = out
        return out


class AuthFastPath:
    """One per FastPathServer; `try_serve` returns the full wire bytes
    for a decision-table hit, or None ("the chain serves this")."""

    def __init__(self, deps):
        self.deps = deps
        self.stats = get_stats()
        self._gen: Optional[_Gen] = None
        table = getattr(deps, "decision_table", None)
        if table is not None:
            self.stats.set_table(table)

    def try_serve(self, req) -> Optional[Tuple[bytes, int]]:
        """(wire_bytes, status) on a fast-path hit, else None."""
        table = getattr(self.deps, "decision_table", None)
        if table is None:
            return None
        config = self.deps.config_holder.get()
        gen = self._gen
        if gen is None or gen.config is not config:
            gen = _Gen(config)
            self._gen = gen
        if not gen.enabled:
            return None
        stats = self.stats
        try:
            failpoints.check("serve.fastpath.lookup")
            return self._lookup(req, gen, table, stats)
        except failpoints.FaultInjected:
            stats.note_fault()
            return None
        except Exception:  # noqa: BLE001 — fail open, the chain serves it
            stats.note_fault()
            log.debug("fastpath lookup fault", exc_info=True)
            return None

    # ------------------------------------------------------------- lookup

    def _lookup(self, req, gen: _Gen, table, stats) -> Optional[Tuple[bytes, int]]:
        headers = req.headers
        host = headers.get("x-requested-host", "")
        ip = headers.get("x-client-ip", "")
        # hosts with per-site static/password state can decide before the
        # dynamic lists (chain steps 1-4) — chain territory
        if host in gen.password_hosts or host in gen.list_hosts:
            stats.note_miss("ineligible")
            return None

        cookies = {}
        raw = headers.get("cookie", "")
        if raw:
            for part in raw.split(";"):
                name, eq, value = part.strip().partition("=")
                if not eq:
                    continue
                if "%" in value or "+" in value:
                    value = gen.unescape(value)
                    if value is None:
                        continue
                    cookies[name] = value
                else:
                    # QueryUnescape is the identity on a value with no
                    # escapes — skip the per-char walk (gin's read does
                    # the same unescape, so identity here is exact)
                    cookies[name] = value
            if PASSWORD_COOKIE_NAME in cookies:
                # chain step 1 (priority pass) could fire — let it decide
                stats.note_miss("password")
                return None
            if SESSION_COOKIE_NAME in cookies and table.session_count() > 0:
                # a session-id entry would beat the IP entry in chain
                # step 7; the table only mirrors a count, so any session
                # bearer defers to the chain while such entries exist
                stats.note_miss("session_guard")
                return None

        # chain steps 5-6 (global static lists) outrank the dynamic
        # lists; when configured they must MISS for the fast path to own
        # the request (both checks are cheap dict/filter probes)
        static_lists = self.deps.static_lists
        if gen.has_global_ip:
            cache = gen.global_ip_cache
            found = cache.get(ip)
            if found is None:
                _, found = static_lists.check_global(ip)
                if len(cache) >= gen.CACHE_MAX:
                    cache.clear()
                cache[ip] = found
            if found:
                stats.note_miss("global_list")
                return None
        if gen.has_global_ua:
            ua = headers.get("x-client-user-agent", "")
            cache = gen.global_ua_cache
            found = cache.get(ua)
            if found is None:
                _, found = static_lists.check_global_user_agent(ua)
                if len(cache) >= gen.CACHE_MAX:
                    cache.clear()
                cache[ua] = found
            if found:
                stats.note_miss("global_list")
                return None

        entry = table.get(ip)
        if entry is None:
            stats.note_miss("table")
            return None
        decision, expires, from_baskerville = entry
        # the chain's lazy-expiry comparison to the bit (dynamic_lists
        # check: strictly `now - expires > 0`); an expired entry misses
        # so the chain performs the deletion + provenance record
        if time.time() - expires > 0:
            stats.note_miss("expired")
            return None

        if decision == Decision.ALLOW:
            raw_resp = self._render(
                gen, gen.granted_head, req, cookies, ip, host, 200
            )
            self._log_result(gen, req, ip, host, "ExpiringAccessGranted")
            stats.note_hit("allow")
            return raw_resp, 200

        if decision == Decision.CHALLENGE:
            if host in gen.sha_exc_hosts:
                # step 7's per-path sha-inv exception prefix check
                stats.note_miss("ineligible")
                return None
            if from_baskerville and host in gen.bask_disabled:
                # chain falls through to step 8 with a DIS-BASK log line
                stats.note_miss("baskerville")
                return None
            return self._challenge(req, cookies, ip, host, stats)

        if decision in (Decision.NGINX_BLOCK, Decision.IPTABLES_BLOCK):
            if from_baskerville and host in gen.bask_disabled:
                stats.note_miss("baskerville")
                return None
            raw_resp = self._render(
                gen, gen.denied_head, req, cookies, ip, host, 403
            )
            self._log_result(gen, req, ip, host, "ExpiringBlock")
            stats.note_hit("block")
            return raw_resp, 403

        stats.note_miss("table")  # unknown decision byte: fall open
        return None

    # ------------------------------------------------------------- render

    def _render(self, gen: _Gen, head: bytes, req, cookies, ip: str,
                host: str, status: int) -> bytes:
        """Template render = the static head + the per-request session
        splice, reproducing `_session_cookie_endpoint` +
        `serialize_response` byte for byte."""
        dsc = cookies.get(SESSION_COOKIE_NAME)
        if dsc is not None:
            # the chain QueryUnescapes a second time on top of the cookie
            # read, falling back to the original on error (identity when
            # the value carries no escapes)
            if "%" in dsc or "+" in dsc:
                url_decoded = gen.unescape(dsc)
                if url_decoded is None:
                    url_decoded = dsc
            else:
                url_decoded = dsc
            now = time.time()
            cache = gen.session_cache
            exp = cache.get((url_decoded, ip))
            if exp is not None and exp >= now:
                # validated before and not yet past its embedded expiry —
                # exactly the window validate_session_cookie accepts
                out, new = url_decoded, False
            else:
                try:
                    validate_session_cookie(url_decoded, gen.secret, now, ip)
                    valid = True
                except SessionCookieError:
                    valid = False
                if valid:
                    try:
                        raw = decode_cookie_b64(
                            url_decoded, SessionCookieError, "bad b64"
                        )
                        if len(cache) >= gen.CACHE_MAX:
                            cache.clear()
                        cache[(url_decoded, ip)] = float(
                            struct.unpack(">Q", raw[8:16])[0]
                        )
                    except Exception:  # noqa: BLE001 — memo only
                        pass
                if valid or gen.not_verify:
                    out, new = url_decoded, False
                else:
                    out, new = new_session_cookie(gen.secret, gen.ttl, ip), True
        else:
            out, new = new_session_cookie(gen.secret, gen.ttl, ip), True
        # header values pass the serializer's CR/LF sanitizer (a client-
        # controlled echoed session value is a splitting vector)
        if "\r" in out or "\n" in out:
            out_hdr = out.replace("\r", " ").replace("\n", " ")
        else:
            out_hdr = out
        parts = [head, out_hdr.encode()]
        if new:
            parts.append(b"\r\nX-Deflect-Session-New: true\r\n")
            parts.append(gen.setcookie_prefix)
            parts.append(go_query_escape(out).encode())
            parts.append(gen.setcookie_mid)
        else:
            parts.append(b"\r\nX-Deflect-Session-New: false\r\n")
        parts.append(gen.conn_keep if req.keep_alive else gen.conn_close)
        if req.method != "HEAD":
            parts.append(_GRANTED_BODY if status == 200 else _DENIED_BODY)
        return b"".join(parts)

    def _challenge(self, req, cookies, ip: str, host: str,
                   stats) -> Tuple[bytes, int]:
        """A CHALLENGE hit skips chain steps 1-6 (all proven misses by
        the gates above) and enters the REAL challenge stage directly —
        issuance, verification, failure counting and ban side effects
        are the chain's own code, so the response and every side effect
        stay byte-identical."""
        from banjax_tpu.httpapi.decision_chain import (
            ChainState,
            DecisionForNginxResult,
            DecisionListResult,
            RequestInfo,
            send_or_validate_sha_challenge,
        )
        from banjax_tpu.httpapi.fastserve import serialize_response

        deps = self.deps
        info = RequestInfo(
            client_ip=ip,
            requested_host=host,
            requested_path=req.headers.get("x-requested-path", ""),
            client_user_agent=req.headers.get("x-client-user-agent", ""),
            method=req.method,
            cookies=cookies,
        )
        state = ChainState(
            config=deps.config_holder.get(),
            static_lists=deps.static_lists,
            dynamic_lists=deps.dynamic_lists,
            protected_paths=deps.protected_paths,
            failed_challenge_states=deps.failed_challenge_states,
            banner=deps.banner,
            challenge_verifier=getattr(deps, "challenge_verifier", None),
        )
        resp, sha_result, rate_result = send_or_validate_sha_challenge(
            state, info, FailAction.BLOCK
        )
        result = DecisionForNginxResult(
            client_ip=ip,
            requested_host=host,
            requested_path=info.requested_path,
            decision_list_result=DecisionListResult.EXPIRING_CHALLENGE,
            sha_challenge_result=sha_result,
            too_many_failed_challenges_result=rate_result,
            client_user_agent=info.client_user_agent,
        )
        log.info("decisionForNginx: %s", result.to_json())
        stats.note_hit("challenge")
        raw_resp = serialize_response(
            resp, req.keep_alive, head_only=req.method == "HEAD"
        )
        return raw_resp, resp.status

    @staticmethod
    def _log_result(gen: _Gen, req, ip: str, host: str, dlr: str) -> None:
        """The chain's per-request log line (fastserve logs every result
        that isn't NoMention; fast-path hits never are).  Serialized only
        when INFO is actually emitted — the line's content is unchanged."""
        if not log.isEnabledFor(logging.INFO):
            return
        log.info("decisionForNginx: %s", json.dumps({
            "ClientIp": ip,
            "RequestedHost": host,
            "RequestedPath": req.headers.get("x-requested-path", ""),
            "DecisionListResult": dlr,
            "PasswordChallengeResult": None,
            "ShaChallengeResult": None,
            "TooManyFailedChallengesResult": None,
            "ClientUserAgent": req.headers.get("x-client-user-agent", ""),
        }))
