"""HTTP worker process entrypoint (`python -m banjax_tpu.httpapi.worker_serve`).

One of N SO_REUSEPORT processes serving the /auth_request hot path (see
httpapi/workers.py for the architecture).  A worker builds ONLY the
host-side request state — config, static lists, a dynamic-lists replica,
the shared-memory failed-challenge table, a forwarding banner — and never
imports jax: the matcher pipeline, ingest, kafka, ipset, and metrics all
live in the primary.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys
from typing import Optional

from banjax_tpu.config.holder import ConfigHolder
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.httpapi.server import ServerDeps, run_http_server
from banjax_tpu.httpapi.workers import (
    PRIMARY_HTTP_SOCK,
    RemoteBanner,
    WorkerControl,
)
from banjax_tpu.ingest import reports
from banjax_tpu.native.shm import ShmFailedChallengeStates

log = logging.getLogger(__name__)


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(prog="banjax-tpu-worker", prefix_chars="-")
    parser.add_argument("-config-file", dest="config_file", required=True)
    parser.add_argument("-ctrl-dir", dest="ctrl_dir", required=True)
    parser.add_argument("-index", dest="index", type=int, required=True)
    parser.add_argument("-shm-name", dest="shm_name", required=True)
    parser.add_argument("-dt-shm-name", dest="dt_shm_name", default="")
    parser.add_argument("-standalone-testing", dest="standalone_testing",
                        action="store_true")
    parser.add_argument("-debug", dest="debug", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.debug else logging.INFO,
        format=f"%(asctime)s worker-{args.index} %(name)s %(levelname)s %(message)s",
    )

    config_holder = ConfigHolder(
        args.config_file, args.standalone_testing, args.debug
    )
    config = config_holder.get()

    # the worker's decision chain records list-hit/challenge provenance
    # into its process-local ledger; configure it to the same shape as
    # the primary's (the authoritative inserts are re-ledgered there via
    # the control plane — the primary serves /decisions/explain)
    from banjax_tpu.obs import provenance

    provenance.configure(
        enabled=getattr(config, "provenance_enabled", True),
        ring_size=getattr(config, "provenance_ring_size", 2048),
    )

    static_lists = StaticDecisionLists(config)
    protected_paths = PasswordProtectedPaths(config)
    replica = DynamicDecisionLists()
    failed_challenge_states = ShmFailedChallengeStates(name=args.shm_name)

    # the primary's serving decision table, attached read-only: the
    # replica never mirrors (the primary's broadcast already wrote every
    # insert into the shm table — mirroring here would double-apply);
    # a failed attach only costs this worker the fast path
    decision_table = None
    if args.dt_shm_name:
        try:
            from banjax_tpu.native.decisiontable import ShmDecisionTable

            decision_table = ShmDecisionTable(name=args.dt_shm_name)
        except Exception:  # noqa: BLE001
            log.exception(
                "worker %d: decision table attach failed; serving via chain",
                args.index,
            )
            decision_table = None

    def on_reload() -> None:
        log.info("worker %d: reloading config", args.index)
        try:
            config_holder.reload()
        except Exception as e:  # noqa: BLE001 — keep serving on a bad reload
            log.error("worker reload failed: %s", e)
            return
        new_config = config_holder.get()
        static_lists.update_from_config(new_config)
        protected_paths.update_from_config(new_config)
        # the replica is cleared by the primary's dyn_clear broadcast

    control = WorkerControl(args.ctrl_dir, args.index, replica, on_reload)
    banner = RemoteBanner(control, replica)

    # kafka reports from this worker's request path ride the control socket
    reports.set_forwarder(
        lambda data: control.send({"op": "kafka", "data": data.decode("utf-8")})
    )

    gin_log_file = None
    gin_log_name = "gin.log" if config.standalone_testing else config.gin_log_file
    if gin_log_name and gin_log_name != "-":
        # O_APPEND: every worker and the primary append whole lines to the
        # same access log
        gin_log_file = open(gin_log_name, "a", encoding="utf-8")

    server_log_file = None
    if config.standalone_testing:
        server_log_file = open(config.server_log_file, "a", encoding="utf-8")

    deps = ServerDeps(
        config_holder=config_holder,
        static_lists=static_lists,
        dynamic_lists=replica,
        protected_paths=protected_paths,
        regex_states=RegexRateLimitStates(),  # primary-owned; route proxied
        failed_challenge_states=failed_challenge_states,
        banner=banner,
        gin_log_file=gin_log_file,
        server_log_file=server_log_file,
        # workers never import jax (module docstring): PoW verification
        # stays on the CPU oracle here; the device-batched path runs in
        # single-process serving, where the primary owns the device
        challenge_verifier=None,
        decision_table=decision_table,
    )
    primary_sock = os.path.join(args.ctrl_dir, PRIMARY_HTTP_SOCK)

    async def serve() -> None:
        runner = await run_http_server(
            deps, reuse_port=True, worker_proxy_sock=primary_sock
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        log.info("worker %d serving", args.index)
        await stop.wait()
        await runner.cleanup()

    try:
        asyncio.run(serve())
    finally:
        control.stop()
        replica.close()
        failed_challenge_states.close()
        if decision_table is not None:
            try:
                decision_table.close()
            except Exception:  # noqa: BLE001
                pass
        for f in (gin_log_file, server_log_file):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
