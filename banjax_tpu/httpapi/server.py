"""The HTTP request API.

Reference behavior: /root/reference/internal/http_server.go:32-332 — a server
on hard-coded 127.0.0.1:8081 with: a JSON access-log middleware, a recovery
middleware that FAILS OPEN (X-Accel-Redirect: @fail_open + 500 with an
X-Banjax-Error header) on any handler crash, a standalone-testing middleware
that fakes the Nginx X-* headers and writes the Nginx-format access log line
itself, and these routes:

  ANY  /auth_request        — the decision chain
  GET  /info                — config version
  GET  /decision_lists      — formatted static+dynamic lists
  GET  /rate_limit_states   — formatted rate-limit states
  GET  /is_banned?ip=       — expiring-list + ipset lookup
  GET  /ipset/list          — raw ipset entries
  GET  /banned?domain=      — expiring entries for a domain
  POST /unban               — remove an IP from expiring list + ipset
"""

from __future__ import annotations

import asyncio
import dataclasses
import hmac
import json
import logging
import os
import time
import traceback
from typing import Callable, Optional, TextIO

from aiohttp import web

from banjax_tpu.config.holder import ConfigHolder
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import BannerInterface
from banjax_tpu.httpapi.decision_chain import (
    ChainState,
    DecisionListResult,
    RequestInfo,
    Response,
    decision_for_nginx,
)
from banjax_tpu.httpapi.fastserve import _clean_header
from banjax_tpu.utils import go_query_escape, go_query_unescape

log = logging.getLogger(__name__)

LISTEN_HOST = "127.0.0.1"
LISTEN_PORT = 8081  # http_server.go:42 (XXX config — kept identical)

_LOOPBACK_HOSTS = {"", "127.0.0.1", "::1", "localhost"}


def is_loopback_host(host: str) -> bool:
    return (host or "").strip("[]") in _LOOPBACK_HOSTS or (
        host or ""
    ).startswith("127.")


def admin_auth_ok(config, listen_host: str, authorization: str) -> bool:
    """Gate for the admin surface (/healthz, /metrics, /debug/trace,
    /decisions/explain, /debug/incidents, /traffic/top).

    Open on a loopback listener (the reference's 127.0.0.1:8081 posture —
    local operators and sidecar scrapers need no secret) or when no
    `admin_token` is configured (run_http_server logged the warning at
    bind time).  Otherwise the request must carry `Authorization:
    Bearer <token>`; comparison is constant-time so the token can't be
    recovered byte-by-byte from response timing."""
    token = getattr(config, "admin_token", "") or ""
    if not token or is_loopback_host(listen_host):
        return True
    provided = authorization or ""
    if provided.startswith("Bearer "):
        provided = provided[len("Bearer "):]
    return hmac.compare_digest(token.encode(), provided.encode())


@dataclasses.dataclass
class ServerDeps:
    config_holder: ConfigHolder
    static_lists: StaticDecisionLists
    dynamic_lists: DynamicDecisionLists
    protected_paths: PasswordProtectedPaths
    regex_states: RegexRateLimitStates
    failed_challenge_states: FailedChallengeRateLimitStates
    banner: BannerInterface
    gin_log_file: Optional[TextIO] = None  # the JSON access log
    server_log_file: Optional[TextIO] = None  # standalone: fake nginx log
    health: Optional[object] = None  # resilience.health.HealthRegistry
    # /metrics exposition sources (getters, not objects: SIGHUP reload
    # swaps the matcher, and the supervisor appears after spawn)
    matcher_getter: Optional[Callable[[], object]] = None
    pipeline_getter: Optional[Callable[[], object]] = None
    supervisor_getter: Optional[Callable[[], object]] = None
    # SLO burn-rate engine (obs/slo.py) and incident flight recorder
    # (obs/flightrec.py) — both optional, both primary-owned
    slo_getter: Optional[Callable[[], object]] = None
    flightrec_getter: Optional[Callable[[], object]] = None
    # decision-fabric counters (fabric/stats.py FabricStats) — None when
    # the fabric is off
    fabric_getter: Optional[Callable[[], object]] = None
    # fleet observability (obs/fleet.py FleetScraper) — None unless
    # fleet_metrics_enabled AND the fabric is on; /metrics?fleet=1
    fleet_getter: Optional[Callable[[], object]] = None
    # the FabricService itself (fabric/service.py) — the cross-shard
    # /decisions/explain proxy needs owner_of + explain_remote, which
    # live on the service, not on its stats object
    fabric_service_getter: Optional[Callable[[], object]] = None
    # device-batched PoW verifier (challenge/verifier.py DeviceVerifier)
    # — None = pure-CPU reference verification, decisions identical
    challenge_verifier: Optional[object] = None
    # compiled serving fast path (native/decisiontable.py): the table the
    # dynamic lists mirror into — None = every request takes the chain
    decision_table: Optional[object] = None


_STANDALONE_KEY = "banjax_standalone_hdrs"


def _hdr(request: web.Request, name: str) -> str:
    """Read an X-* header, honoring the standalone middleware's injected
    values (kept in the request's state dict — cheaper than cloning the
    request per hit, which the reference does by mutating the shared
    header map in place, http_server.go:137-169)."""
    ov = request.get(_STANDALONE_KEY)
    if ov is not None and name in ov:
        return ov[name]
    return request.headers.get(name, "")


def _request_info(request: web.Request) -> RequestInfo:
    # gin reads cookies through url.QueryUnescape (c.Cookie); a value whose
    # unescape fails is treated as an absent cookie
    cookies = {}
    for name, value in request.cookies.items():
        try:
            cookies[name] = go_query_unescape(value)
        except ValueError:
            continue
    return RequestInfo(
        client_ip=_hdr(request, "X-Client-IP"),
        requested_host=_hdr(request, "X-Requested-Host"),
        requested_path=_hdr(request, "X-Requested-Path"),
        client_user_agent=_hdr(request, "X-Client-User-Agent"),
        method=request.method,
        cookies=cookies,
    )


class CoalescedLog:
    """Per-request log lines without a per-request flush.

    Lines accumulate in a Python list; a single delayed callback (50 ms)
    writes the batch with ONE os.write on the underlying fd, so a 1k-rps
    burst pays ~20 syscalls/sec instead of 1k.  Bypassing the TextIO
    buffer matters in multi-worker mode: several processes append to the
    same file, and a block-buffer flush could split a line mid-byte —
    os.write(O_APPEND) emits whole lines atomically.  Consumers (the
    standalone tailer, integration tests) all poll with retry budgets far
    above 50 ms; shutdown replays any tail through flush()."""

    __slots__ = ("_f", "_lines", "_pending", "delay")

    def __init__(self, f: TextIO, delay: float = 0.05):
        self._f = f
        self._lines: list = []
        self._pending = False
        self.delay = delay

    def write(self, s: str) -> None:
        self._lines.append(s)
        if not self._pending:
            self._pending = True
            asyncio.get_running_loop().call_later(self.delay, self._flush)

    def _flush(self) -> None:
        self._pending = False
        if not self._lines:
            return
        data = "".join(self._lines).encode("utf-8", "surrogatepass")
        self._lines.clear()
        try:
            os.write(self._f.fileno(), data)
        except (OSError, ValueError):
            pass  # closed during shutdown


def _to_web_response(resp: Response) -> web.Response:
    out = web.Response(
        status=resp.status, body=resp.body, content_type=resp.content_type
    )
    for k, v in resp.headers.items():
        out.headers[k] = v
    for c in resp.cookies:
        # gin SetCookie url.QueryEscape's the value; the page JS
        # decodeURIComponent's it back — keep the same wire encoding
        out.set_cookie(
            c.name, go_query_escape(c.value), max_age=c.max_age, path=c.path,
            domain=c.domain or None, secure=c.secure, httponly=c.http_only,
        )
    return out


def build_app(deps: ServerDeps,
              worker_proxy_sock: Optional[str] = None,
              listen_host: str = LISTEN_HOST) -> web.Application:
    """Build the application.  With `worker_proxy_sock` set (multi-worker
    mode, httpapi/workers.py) the primary-owned cold routes are registered
    as reverse proxies to the primary's unix HTTP socket instead of local
    handlers — a worker's replicas are authoritative only for the
    /auth_request hot path."""
    middlewares = []

    config0 = deps.config_holder.get()

    coalesced_logs: list = []

    # --- access log middleware (http_server.go:65-95) ---
    if deps.gin_log_file is not None:
        gin_log = CoalescedLog(deps.gin_log_file)
        coalesced_logs.append(gin_log)

        @web.middleware
        async def access_log_middleware(request: web.Request, handler):
            start = time.monotonic()
            response = await handler(request)
            latency_us = int((time.monotonic() - start) * 1e6)
            line = {
                "Time": time.strftime("%a, %d %b %Y %H:%M:%S %Z"),
                "ClientIp": _hdr(request, "X-Client-IP"),
                "ClientReqHost": _hdr(request, "X-Requested-Host"),
                "ClientReqPath": _hdr(request, "X-Requested-Path"),
                "Method": request.method,
                "Path": request.path,
                "Status": response.status,
                "Latency": latency_us,
            }
            gin_log.write(json.dumps(line) + "\n")
            return response

        middlewares.append(access_log_middleware)

    # --- fail-open recovery middleware (http_server.go:110-135) ---
    @web.middleware
    async def recovery_middleware(request: web.Request, handler):
        try:
            return await handler(request)
        except web.HTTPException:
            raise  # normal HTTP responses (404 etc.), not crashes
        except Exception as e:  # noqa: BLE001 — this IS the crash handler
            tb = traceback.extract_tb(e.__traceback__)
            location = f"{tb[-1].filename}:{tb[-1].lineno}" if tb else "unknown"
            log.error("handler panic: %s (%s)", e, location)
            # CR/LF-sanitized: exception text can embed client-controlled
            # bytes, and an unsanitizable header value would make aiohttp
            # raise INSIDE the crash handler — dropping the fail-open
            # contract exactly when it matters
            headers = {
                "X-Banjax-Error": _clean_header(f"{e} ({location})"),
                "X-Accel-Redirect": "@fail_open",
            }
            return web.Response(status=500, headers=headers)

    middlewares.append(recovery_middleware)

    # --- standalone-testing middleware (http_server.go:137-169) ---
    if config0.standalone_testing:
        log.info("!!! standalone-testing mode enabled. adding some X- headers here")

        server_log = (
            CoalescedLog(deps.server_log_file)
            if deps.server_log_file is not None else None
        )
        if server_log is not None:
            coalesced_logs.append(server_log)

        @web.middleware
        async def standalone_middleware(request: web.Request, handler):
            # injected values ride the request's state dict (read back via
            # _hdr) — same effect as the reference's in-place header-map
            # mutation, without a per-request clone of the request object
            hdrs = request.headers
            client_ip = hdrs.get("X-Client-IP") or request.remote or "127.0.0.1"
            request[_STANDALONE_KEY] = {
                "X-Client-IP": client_ip,
                "X-Requested-Host": request.host,
                "X-Requested-Path": request.query.get("path", ""),
                "X-Client-User-Agent": hdrs.get("X-Client-User-Agent")
                or "mozilla",
            }

            # write the fake nginx banjax_format line so the log tailer has
            # input: '$msec $remote_addr $request_method $host $request $ua'
            if server_log is not None:
                server_log.write(
                    "%f %s %s %s %s %s HTTP/1.1 %s\n"
                    % (
                        float(int(time.time())),
                        client_ip,
                        request.method,
                        request.host,
                        request.method,
                        request.query.get("path", ""),
                        hdrs.get("User-Agent", ""),
                    )
                )
            return await handler(request)

        # outermost, so the injected X-* headers are visible to the access
        # log (the reference mutates the shared header map in place)
        middlewares.insert(0, standalone_middleware)

    app = web.Application(middlewares=middlewares)

    if coalesced_logs:
        # drain any coalesced log tail when the server shuts down
        async def _drain_logs(app_):
            for lg in coalesced_logs:
                lg._flush()

        app.on_cleanup.append(_drain_logs)

    # ---------------- routes ----------------

    async def auth_request(request: web.Request) -> web.Response:
        config = deps.config_holder.get()
        state = ChainState(
            config=config,
            static_lists=deps.static_lists,
            dynamic_lists=deps.dynamic_lists,
            protected_paths=deps.protected_paths,
            failed_challenge_states=deps.failed_challenge_states,
            banner=deps.banner,
            challenge_verifier=deps.challenge_verifier,
        )
        resp, result = decision_for_nginx(state, _request_info(request))
        if config.debug:
            log.info("decisionForNginx: %s", result.to_json())
        elif result.decision_list_result != DecisionListResult.NO_MENTION:
            log.info("decisionForNginx: %s", result.to_json())
        return _to_web_response(resp)

    async def info(request: web.Request) -> web.Response:
        return web.json_response(
            {"config_version": deps.config_holder.get().config_version}
        )

    async def decision_lists_route(request: web.Request) -> web.Response:
        per_site, global_ = deps.static_lists.format_lists()
        expiring = deps.dynamic_lists.format_ip_entries()

        def fmt_ip_map(m):
            return "".join(f"{ip}:\n\t{d}\n" for ip, d in m.items())

        per_site_str = "".join(
            f"{site}:\n" + "".join(f"\t{ip}:\n\t\t{d}\n" for ip, d in ips.items())
            for site, ips in per_site.items()
        )
        expiring_str = "".join(
            f"{ip}:\n\t{ed.domain} {ed.decision} until "
            f"{time.strftime('%H:%M:%S', time.localtime(ed.expires))} "
            f"(baskerville: {str(ed.from_baskerville).lower()})\n"
            for ip, ed in expiring.items()
        )
        body = (
            f"per_site:\n{per_site_str}\n\nglobal:\n{fmt_ip_map(global_)}\n\n"
            f"expiring:\n{expiring_str}"
        )
        return web.Response(text=body)

    async def rate_limit_states_route(request: web.Request) -> web.Response:
        body = (
            f"regexes:\n{deps.regex_states.format_states()}\n"
            f"failed challenges:\n{deps.failed_challenge_states.format_states()}\n"
        )
        return web.Response(text=body)

    async def is_banned(request: web.Request) -> web.Response:
        ip = request.query.get("ip", "")
        if not ip:
            return web.json_response({"error": "ip query param is required"}, status=400)
        try:
            banned = deps.banner.ipset_list()
        except Exception:  # noqa: BLE001 — reference ignores the error (banned, _ :=)
            banned = None
        expiring, ok = deps.dynamic_lists.check("", ip)
        if not ok:
            return web.json_response(
                {"ip": ip, "banned": banned, "expiringDecision": None}
            )
        return web.json_response(
            {
                "ip": ip,
                "banned": banned,
                "expiringDecision": {
                    "Decision": str(expiring.decision),
                    "Expires": expiring.expires,
                    "IpAddress": expiring.ip_address,
                },
            }
        )

    async def ipset_list_route(request: web.Request) -> web.Response:
        try:
            entries = deps.banner.ipset_list()
        except Exception as e:  # noqa: BLE001 — surface as 500 JSON like the reference
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"entries": entries})

    async def banned_route(request: web.Request) -> web.Response:
        domain = request.query.get("domain", "")
        if not domain:
            return web.json_response({"error": "domain query param is required"}, status=400)
        entries = deps.dynamic_lists.check_by_domain(domain)
        return web.json_response(
            {
                "domain": domain,
                "entries": [
                    {
                        "ip": e.ip_or_session_id,
                        "decision": e.decision,
                        "expires": time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(e.expires)
                        ),
                        "from_baskerville": e.from_baskerville,
                    }
                    for e in entries
                ],
            }
        )

    async def unban(request: web.Request) -> web.Response:
        config = deps.config_holder.get()
        form = await request.post()
        ip = str(form.get("ip", "")).strip()
        if not ip:
            return web.json_response({"error": "ip in post form is required"}, status=400)
        expiring, ok = deps.dynamic_lists.check("", ip)
        decision_str = str(expiring.decision) if ok and expiring else ""
        if not ok or (expiring and expiring.decision == Decision.IPTABLES_BLOCK):
            if not deps.banner.ipset_test(config, ip):
                return web.json_response(
                    {
                        "ip": ip,
                        "found_in_decision_list": ok,
                        "decision": decision_str,
                        "unban": False,
                        "error": "ip is not banned",
                    },
                    status=400,
                )
            try:
                deps.banner.ipset_del(ip)
            except Exception as e:  # noqa: BLE001 — reference returns the error as 500 JSON
                return web.json_response(
                    {
                        "ip": ip,
                        "found_in_decision_list": ok,
                        "decision": decision_str,
                        "unban": False,
                        "error": str(e),
                    },
                    status=500,
                )
        if ok:
            deps.dynamic_lists.remove_by_ip(ip)
        return web.json_response(
            {
                "ip": ip,
                "found_in_decision_list": ok,
                "decision": decision_str,
                "unban": True,
            }
        )

    def _admin_denied(request: web.Request) -> Optional[web.Response]:
        """None when the admin request may proceed; a 401 otherwise.
        Evaluated per request (not at build time) so a SIGHUP'd token
        takes effect without a listener restart."""
        if admin_auth_ok(deps.config_holder.get(), listen_host,
                         request.headers.get("Authorization", "")):
            return None
        return web.json_response(
            {"error": "unauthorized"}, status=401,
            headers={"WWW-Authenticate": "Bearer"},
        )

    async def healthz(request: web.Request) -> web.Response:
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        # the component health aggregate (resilience/health.py): 200 while
        # serving is possible (HEALTHY or DEGRADED — degraded modes still
        # answer traffic), 503 only when a component has FAILED
        if deps.health is None:
            return web.json_response({"status": "unknown", "components": {}})
        snap = deps.health.snapshot()
        status = 503 if snap["status"] == "failed" else 200
        return web.json_response(snap, status=status)

    async def metrics_route(request: web.Request) -> web.Response:
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        if request.query.get("fleet") in ("1", "true"):
            scraper = deps.fleet_getter() if deps.fleet_getter else None
            if scraper is None:
                return web.json_response(
                    {"error": "fleet metrics disabled "
                              "(fleet_metrics_enabled + fabric required)"},
                    status=404,
                )
            # scrape() does blocking peer socket I/O — keep it off the
            # event loop; peer failures degrade inside scrape() (cached/
            # unreachable gauges), so this is partial-but-200, never a 500
            text = await asyncio.get_running_loop().run_in_executor(
                None, scraper.scrape
            )
            return web.Response(
                text=text,
                content_type="text/plain",
                charset="utf-8",
                headers={"X-Prometheus-Exposition-Version": "0.0.4"},
            )
        from banjax_tpu.obs.exposition import render_prometheus

        text = render_prometheus(
            deps.dynamic_lists,
            deps.regex_states,
            deps.failed_challenge_states,
            matcher=deps.matcher_getter() if deps.matcher_getter else None,
            pipeline=deps.pipeline_getter() if deps.pipeline_getter else None,
            health=deps.health,
            supervisor=(
                deps.supervisor_getter() if deps.supervisor_getter else None
            ),
            slo=deps.slo_getter() if deps.slo_getter else None,
            flightrec=(
                deps.flightrec_getter() if deps.flightrec_getter else None
            ),
            fabric=deps.fabric_getter() if deps.fabric_getter else None,
        )
        return web.Response(
            text=text,
            content_type="text/plain",
            charset="utf-8",
            headers={"X-Prometheus-Exposition-Version": "0.0.4"},
        )

    async def debug_trace_route(request: web.Request) -> web.Response:
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        from banjax_tpu.obs import trace as trace_mod

        tracer = trace_mod.get_tracer()
        # snapshot+clear is ONE lock section inside the tracer: a span
        # recorded while this dump renders is either in the dump or kept
        # for the next one — never silently dropped by the clear
        payload = tracer.export_chrome(
            clear=request.query.get("clear") in ("1", "true")
        )
        payload["otherData"]["enabled"] = tracer.enabled
        return web.json_response(payload)

    async def decisions_explain_route(request: web.Request) -> web.Response:
        """Decision provenance for one IP: every ledger record across
        the six sources, plus the live dynamic-list entry (read without
        the lazy-expiry side effect — an admin read must not mutate)."""
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        ip = request.query.get("ip", "")
        if not ip:
            return web.json_response(
                {"error": "ip query param is required"}, status=400
            )
        from banjax_tpu.obs import provenance as provenance_mod

        # cross-shard proxy: when the fabric is on and this IP hashes to
        # another owner, the authoritative ledger lives THERE — forward
        # the question over the peer wire (T_EXPLAIN) and tag the answer
        # with the owning node.  Unreachable owner -> fall back to the
        # local (partial) view, flagged, never a 500.
        owner_unreachable = None
        svc = (
            deps.fabric_service_getter()
            if deps.fabric_service_getter else None
        )
        if svc is not None:
            try:
                owner = svc.router.owner_of(ip)
            except Exception:
                owner = None
            if owner is not None and owner != svc.node_id:
                try:
                    payload = await asyncio.get_running_loop().run_in_executor(
                        None, svc.explain_remote, owner, ip
                    )
                    payload["owning_node"] = owner
                    payload["proxied"] = True
                    return web.json_response(payload)
                except Exception:
                    owner_unreachable = owner

        ledger = provenance_mod.get_ledger()
        records = ledger.explain(ip)
        active = None
        peek = getattr(deps.dynamic_lists, "peek", None)
        if peek is not None:
            ed = peek(ip)
            if ed is not None:
                active = {
                    "decision": str(ed.decision),
                    "expires": ed.expires,
                    "domain": ed.domain,
                    "from_baskerville": ed.from_baskerville,
                }
        out = {
            "ip": ip,
            "ledger_enabled": ledger.enabled,
            "records": records,
            "active_decision": active,
        }
        if svc is not None:
            out["node_id"] = svc.node_id
        if owner_unreachable is not None:
            out["owner_unreachable"] = owner_unreachable
        return web.json_response(out)

    async def traffic_top_route(request: web.Request) -> web.Response:
        """Live traffic introspection (obs/sketch.py): top-K heavy
        hitters with conservative count-min estimates, the HLL
        distinct-IP estimate and per-rule match pressure, refreshed
        from the device sketch on its sampling interval (?refresh=1
        forces a pull for an operator staring at a live flood)."""
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        matcher = deps.matcher_getter() if deps.matcher_getter else None
        sketch = getattr(matcher, "traffic_sketch", None)
        if sketch is None:
            return web.json_response({
                "enabled": False,
                "top": [],
                "hint": "traffic_sketch_enabled + matcher_device_windows "
                        "required",
            })
        try:
            k = int(request.query.get("k", "0") or 0)
        except ValueError:
            return web.json_response(
                {"error": "k must be an integer"}, status=400
            )
        force = request.query.get("refresh") in ("1", "true")
        summary = sketch.pull(force=force)
        top = summary["top"]
        if k > 0:
            top = top[:k]
        age = sketch.pull_age_seconds()
        return web.json_response({
            "enabled": True,
            "k": k or summary["k_max"],
            "k_max": summary["k_max"],
            "top": top,
            "distinct_ips_estimate": summary["distinct_ips_estimate"],
            "heavy_hitter_share": summary["heavy_hitter_share"],
            "lines_total": summary["lines_total"],
            "rule_pressure": summary["rule_pressure"],
            "sketch": {
                **summary["sketch"],
                "pull_age_seconds": (
                    None if age is None else round(age, 3)
                ),
            },
        })

    async def debug_failpoints_route(request: web.Request) -> web.Response:
        """Runtime fault-injection admin (resilience/failpoints.py):
        GET lists the instrumented sites and every armed point (mode,
        remaining count, fired count, probability); POST arms/disarms —
        the chaos soak's and operators' no-restart failpoint driver.

        POST body (JSON), any combination, applied in this order:
            {"disarm_all": true}
            {"disarm": ["pipeline.submit", ...]}
            {"arm": [{"name": "pipeline.submit", "mode": "error",
                      "count": 3, "probability": 0.5, "delay_s": 0.0}]}
            {"spec": "matcher.device=error:3@0.5;kafka.read"}
        Responds with the resulting armed list."""
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        from banjax_tpu.resilience import failpoints

        if not getattr(deps.config_holder.get(),
                       "failpoints_admin_enabled", True):
            return web.json_response(
                {"error": "failpoints_admin_enabled is false"}, status=403
            )
        if request.method == "POST":
            try:
                body = await request.json()
            except Exception:  # noqa: BLE001 — client error, not ours
                return web.json_response(
                    {"error": "body must be JSON"}, status=400
                )
            if not isinstance(body, dict):
                return web.json_response(
                    {"error": "body must be a JSON object"}, status=400
                )
            if body.get("disarm_all"):
                failpoints.disarm()
            for name in body.get("disarm") or []:
                failpoints.disarm(str(name))
            arms = body.get("arm") or []
            if not isinstance(arms, list):
                return web.json_response(
                    {"error": "arm must be a list"}, status=400
                )
            for ent in arms:
                if not isinstance(ent, dict) or not ent.get("name"):
                    return web.json_response(
                        {"error": "each arm entry needs a name"},
                        status=400,
                    )
                mode = ent.get("mode", "error")
                if mode not in failpoints.MODES:
                    return web.json_response(
                        {"error": f"unknown mode {mode!r}"}, status=400
                    )
                count = ent.get("count")
                if count is not None:
                    try:
                        count = int(count)
                    except (TypeError, ValueError):
                        return web.json_response(
                            {"error": "count must be an integer"},
                            status=400,
                        )
                try:
                    probability = float(ent.get("probability", 1.0))
                    delay_s = float(ent.get("delay_s", 0.0))
                except (TypeError, ValueError):
                    return web.json_response(
                        {"error": "probability/delay_s must be numbers"},
                        status=400,
                    )
                failpoints.arm(
                    str(ent["name"]), mode=mode, count=count,
                    delay_s=delay_s, probability=probability,
                    seed=ent.get("seed"),
                )
            if isinstance(body.get("spec"), str):
                failpoints.arm_from_spec(body["spec"])
        return web.json_response({
            "enabled": True,
            "sites": list(failpoints.KNOWN_SITES),
            "armed": failpoints.snapshot(),
        })

    async def debug_incidents_route(request: web.Request) -> web.Response:
        """Flight-recorder surface: list bundles, fetch a manifest, or
        fetch one bundle file (?name=…&file=…)."""
        denied = _admin_denied(request)
        if denied is not None:
            return denied
        rec = deps.flightrec_getter() if deps.flightrec_getter else None
        if rec is None:
            return web.json_response({"enabled": False, "incidents": []})
        name = request.query.get("name", "")
        fname = request.query.get("file", "")
        if name and fname:
            data = rec.read_file(name, fname)
            if data is None:
                return web.json_response({"error": "not found"}, status=404)
            ctype = (
                "application/json" if fname.endswith(".json")
                else "text/plain"
            )
            return web.Response(body=data, content_type=ctype)
        if name:
            for entry in rec.list_incidents():
                if entry["name"] == name:
                    return web.json_response(entry)
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({
            "enabled": True,
            "directory": rec.directory,
            "incidents": rec.list_incidents(),
        })

    app.router.add_route("*", "/auth_request", auth_request)
    app.router.add_get("/info", info)
    if worker_proxy_sock is None:
        # /healthz, /metrics and /debug/trace are primary-owned (the
        # registries live there); workers reverse-proxy them like the
        # other cold routes
        app.router.add_get("/healthz", healthz)
        app.router.add_get("/metrics", metrics_route)
        app.router.add_get("/debug/trace", debug_trace_route)
        app.router.add_get("/decisions/explain", decisions_explain_route)
        app.router.add_get("/debug/incidents", debug_incidents_route)
        app.router.add_get("/debug/failpoints", debug_failpoints_route)
        app.router.add_post("/debug/failpoints", debug_failpoints_route)
        app.router.add_get("/traffic/top", traffic_top_route)
        app.router.add_get("/decision_lists", decision_lists_route)
        app.router.add_get("/rate_limit_states", rate_limit_states_route)
        app.router.add_get("/is_banned", is_banned)
        app.router.add_get("/ipset/list", ipset_list_route)
        app.router.add_get("/banned", banned_route)
        app.router.add_post("/unban", unban)
    else:
        from banjax_tpu.httpapi.workers import install_proxy_routes

        install_proxy_routes(app, worker_proxy_sock)

    if config0.standalone_testing:
        async def favicon(request: web.Request) -> web.Response:
            return web.Response(text="")
        app.router.add_get("/favicon.ico", favicon)

    if config0.profile:
        _register_profile_routes(app)

    return app


def _register_profile_routes(app: web.Application) -> None:
    """pprof-equivalent endpoints, registered when `profile: true`
    (reference: gin pprof + mutex profiling, http_server.go:314-317).

    /debug/pprof/profile?seconds=N   cProfile of the event-loop thread
    /debug/pprof/threads             stack dump of every thread
    /debug/jax/trace?seconds=N       jax.profiler trace (XLA/TPU timeline),
                                     returns the trace directory path
    """
    import asyncio
    import cProfile
    import io
    import pstats
    import sys
    import tempfile
    import traceback

    profiling = {"active": False}

    async def pprof_profile(request: web.Request) -> web.Response:
        seconds = min(float(request.query.get("seconds", "5")), 60.0)
        if profiling["active"]:
            return web.Response(status=409, text="profile already running\n")
        profiling["active"] = True
        prof = cProfile.Profile()
        prof.enable()
        try:
            # a client disconnect cancels the handler mid-sleep; disable in
            # finally or cProfile stays latched on the event-loop thread
            await asyncio.sleep(seconds)
        finally:
            prof.disable()
            profiling["active"] = False
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(60)
        return web.Response(text=buf.getvalue())

    async def pprof_threads(request: web.Request) -> web.Response:
        buf = io.StringIO()
        frames = sys._current_frames()
        import threading as _threading

        names = {t.ident: t.name for t in _threading.enumerate()}
        for ident, frame in frames.items():
            buf.write(f"--- thread {names.get(ident, '?')} ({ident}) ---\n")
            traceback.print_stack(frame, file=buf)
            buf.write("\n")
        return web.Response(text=buf.getvalue())

    async def jax_trace(request: web.Request) -> web.Response:
        seconds = min(float(request.query.get("seconds", "3")), 60.0)
        try:
            import jax
        except ImportError:
            return web.Response(status=501, text="jax unavailable\n")
        if profiling["active"]:
            return web.Response(status=409, text="profile already running\n")
        profiling["active"] = True
        trace_dir = tempfile.mkdtemp(prefix="banjax-jax-trace-")
        jax.profiler.start_trace(trace_dir)
        try:
            await asyncio.sleep(seconds)
        finally:
            try:
                jax.profiler.stop_trace()
            finally:
                profiling["active"] = False
        return web.json_response({
            "trace_dir": trace_dir,
            "hint": "open with xprof / tensorboard --logdir",
        })

    app.router.add_get("/debug/pprof/profile", pprof_profile)
    app.router.add_get("/debug/pprof/threads", pprof_threads)
    app.router.add_get("/debug/jax/trace", jax_trace)


class ServerHandle:
    """Uniform shutdown handle over the possible server layouts (aiohttp
    runner, fast-path asyncio server, temp unix-socket dir)."""

    def __init__(self, runner=None, fast_server=None, tmpdir=None,
                 fast_logs=()):
        self.runner = runner
        self.fast_server = fast_server
        self._tmpdir = tmpdir
        self._fast_logs = fast_logs

    async def cleanup(self) -> None:
        if self.fast_server is not None:
            self.fast_server.close()
            await self.fast_server.wait_closed()
        for lg in self._fast_logs:
            lg._flush()
        if self.runner is not None:
            await self.runner.cleanup()
        if self._tmpdir is not None:
            import shutil

            shutil.rmtree(self._tmpdir, ignore_errors=True)


async def run_http_server(
    deps: ServerDeps,
    reuse_port: bool = False,
    unix_path: Optional[str] = None,
    worker_proxy_sock: Optional[str] = None,
) -> ServerHandle:
    """Start the server; returns a handle for clean shutdown.

    Layouts (config key `http_fast_path`, default on):

      fast on  — the native protocol server (httpapi/fastserve.py) owns
        127.0.0.1:8081 and answers the hot routes; the full aiohttp app
        listens on a unix socket and receives everything else by raw
        proxy.  In multi-worker mode workers pass `worker_proxy_sock`
        (the primary's unix socket) and run NO local aiohttp at all.
      fast off — the aiohttp app serves 8081 directly (the r4 layout).

    Multi-worker mode (httpapi/workers.py): every process passes
    `reuse_port=True` so the kernel load-balances 127.0.0.1:8081 across
    them; the primary also passes `unix_path` (its cold-route listener for
    worker proxies)."""
    from banjax_tpu.httpapi.fastserve import start_fast_server

    config0 = deps.config_holder.get()
    fast = bool(getattr(config0, "http_fast_path", True))
    # bind address: empty config = the reference's hard-coded loopback.
    # Non-loopback without an admin token leaves /healthz, /metrics and
    # /debug/trace open to the network — allowed, but loudly.
    listen_host = getattr(config0, "http_listen_host", "") or LISTEN_HOST
    if not is_loopback_host(listen_host) and not getattr(
        config0, "admin_token", ""
    ):
        log.warning(
            "http listener binds non-loopback %s with no admin_token: the "
            "admin surface (/healthz /metrics /debug/trace "
            "/decisions/explain /debug/incidents /debug/failpoints "
            "/traffic/top) is open to "
            "the network",
            listen_host,
        )

    if not fast:
        app = build_app(deps, worker_proxy_sock=worker_proxy_sock,
                        listen_host=listen_host)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, listen_host, LISTEN_PORT,
                           reuse_port=reuse_port)
        await site.start()
        if unix_path is not None:
            await web.UnixSite(runner, unix_path).start()
        log.info("http server listening on %s:%s", listen_host, LISTEN_PORT)
        return ServerHandle(runner=runner)

    gin_log = (
        CoalescedLog(deps.gin_log_file) if deps.gin_log_file is not None
        else None
    )
    server_log = (
        CoalescedLog(deps.server_log_file)
        if (config0.standalone_testing and deps.server_log_file is not None)
        else None
    )
    fast_logs = [lg for lg in (gin_log, server_log) if lg is not None]

    if worker_proxy_sock is not None:
        # worker: the fast server IS the whole process surface; cold
        # routes raw-proxy to the primary's unix socket
        fast_server = await start_fast_server(
            deps, worker_proxy_sock, listen_host, LISTEN_PORT,
            reuse_port=True, coalesced_gin=gin_log,
            coalesced_server=server_log,
        )
        log.info("fast http worker listening on %s:%s",
                 listen_host, LISTEN_PORT)
        return ServerHandle(fast_server=fast_server, fast_logs=fast_logs)

    # primary / single process: full aiohttp app on a unix socket (the
    # fast server's cold-route upstream — and the worker proxy target in
    # multi-worker mode), fast server on the TCP port
    tmpdir = None
    if unix_path is None:
        import tempfile

        tmpdir = tempfile.mkdtemp(prefix="banjax-http-")
        unix_path = os.path.join(tmpdir, "app.sock")
    app = build_app(deps, listen_host=listen_host)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    await web.UnixSite(runner, unix_path).start()
    fast_server = await start_fast_server(
        deps, unix_path, listen_host, LISTEN_PORT, reuse_port=reuse_port,
        coalesced_gin=gin_log, coalesced_server=server_log,
    )
    log.info("fast http server on %s:%s (aiohttp upstream %s)",
             listen_host, LISTEN_PORT, unix_path)
    return ServerHandle(runner=runner, fast_server=fast_server,
                        tmpdir=tmpdir, fast_logs=fast_logs)
