"""The /auth_request decision chain.

Reference behavior: /root/reference/internal/http_server.go:347-1165 (spec:
PSEUDOCODE_DESCRIPTION.md:9-63). The priority chain, in order:

  1. valid password cookie for the host (or roaming) → priority pass
  2. password-protected path classification (exception beats protected)
  3. per-site IP list   4. per-site UA list
  5. global IP list     6. global UA list
  7. expiring (dynamic) list — session id first, with per-site SHA-inv path
     exceptions and the sites_to_disable_baskerville fall-through
  8. sitewide SHA-inv list, with password-exception paths passing
  9. default allow ("NoMention")

Every terminal response also runs the session-cookie endpoint, and each
request logs a DecisionForNginxResult JSON record.

This module is framework-agnostic: it consumes a RequestInfo and produces a
Response, so the chain can be unit-tested without an HTTP server and reused
by any frontend (aiohttp server in server.py).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from banjax_tpu.utils import go_query_unescape

from banjax_tpu.challenge import issuer as challenge_issuer
from banjax_tpu.challenge import verifier as challenge_verifier_mod
from banjax_tpu.config.schema import Config
from banjax_tpu.crypto.challenge import (
    CookieError,
    validate_password_cookie,
)
from banjax_tpu.resilience import failpoints
from banjax_tpu.crypto.integrity import (
    INTEGRITY_CHECK_COOKIE_NAME,
    IntegrityCheckPayloadWrapper,
    calc_bot_score_from_cookie,
)
from banjax_tpu.crypto.session import (
    SESSION_COOKIE_NAME,
    SessionCookieError,
    new_session_cookie,
    validate_session_cookie,
)
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists, ExpiringDecision
from banjax_tpu.decisions.model import Decision, FailAction
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths, PathType
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RateLimitResult,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import BannerInterface
from banjax_tpu.httpapi.rewrite import (
    CHALLENGE_COOKIE_NAME,
    PASSWORD_COOKIE_NAME,
    apply_args_to_password_page,
    apply_args_to_sha_inv_page,
)
from banjax_tpu.ingest.reports import report_passed_failed_banned_message
from banjax_tpu.obs import provenance, trace

log = logging.getLogger(__name__)


# ---------------------------------------------------------------- transport


@dataclasses.dataclass
class RequestInfo:
    """What the chain needs from a request (the Nginx-forwarded X-* headers
    plus cookies and method)."""

    client_ip: str = ""
    requested_host: str = ""
    requested_path: str = ""
    client_user_agent: str = ""
    method: str = "GET"
    cookies: Dict[str, str] = dataclasses.field(default_factory=dict)

    def cookie(self, name: str) -> Optional[str]:
        return self.cookies.get(name)


@dataclasses.dataclass
class SetCookie:
    name: str
    value: str
    max_age: int
    path: str = "/"
    domain: str = ""
    secure: bool = False
    http_only: bool = False


@dataclasses.dataclass
class Response:
    status: int = 200
    headers: Dict[str, str] = dataclasses.field(default_factory=dict)
    cookies: List[SetCookie] = dataclasses.field(default_factory=list)
    body: bytes = b""
    content_type: str = "text/plain"


# ------------------------------------------------------------ result enums


class ShaChallengeResult(enum.IntEnum):
    PASSED = 1
    FAILED_NO_COOKIE = 2
    FAILED_BAD_COOKIE = 3

    def __str__(self) -> str:
        return {
            ShaChallengeResult.PASSED: "ShaChallengePassed",
            ShaChallengeResult.FAILED_NO_COOKIE: "ShaChallengeFailedNoCookie",
            ShaChallengeResult.FAILED_BAD_COOKIE: "ShaChallengeFailedBadCookie",
        }[self]


class PasswordChallengeResult(enum.IntEnum):
    ERROR_NO_PASSWORD = 1
    PASSED = 2
    ROAMING_PASSED = 3
    FAILED_NO_COOKIE = 4
    FAILED_BAD_COOKIE = 5

    def __str__(self) -> str:
        return {
            PasswordChallengeResult.ERROR_NO_PASSWORD: "ErrorNoPassword",
            PasswordChallengeResult.PASSED: "PasswordChallengePassed",
            PasswordChallengeResult.ROAMING_PASSED: "PasswordChallengeRoamingPassed",
            PasswordChallengeResult.FAILED_NO_COOKIE: "PasswordChallengeFailedNoCookie",
            PasswordChallengeResult.FAILED_BAD_COOKIE: "PasswordChallengeFailedBadCookie",
        }[self]


class DecisionListResult(enum.IntEnum):
    """http_server.go:747-800 — the 23-value per-request outcome label."""

    PASSWORD_PROTECTED_PRIORITY_PASS = 1
    PASSWORD_PROTECTED_PATH = 2
    PASSWORD_PROTECTED_PATH_EXCEPTION = 3
    PER_SITE_ACCESS_GRANTED = 4
    PER_SITE_CHALLENGE = 5
    PER_SITE_BLOCK = 6
    GLOBAL_ACCESS_GRANTED = 7
    GLOBAL_CHALLENGE = 8
    GLOBAL_BLOCK = 9
    EXPIRING_ACCESS_GRANTED = 10
    EXPIRING_CHALLENGE = 11
    EXPIRING_BLOCK = 12
    PER_SITE_SHA_INV_PATH_EXCEPTION = 13
    SITE_WIDE_CHALLENGE = 14
    SITE_WIDE_CHALLENGE_EXCEPTION = 15
    PER_SITE_UA_ACCESS_GRANTED = 16
    PER_SITE_UA_CHALLENGE = 17
    PER_SITE_UA_BLOCK = 18
    GLOBAL_UA_ACCESS_GRANTED = 19
    GLOBAL_UA_CHALLENGE = 20
    GLOBAL_UA_BLOCK = 21
    NO_MENTION = 22
    NOT_SET = 23

    def __str__(self) -> str:
        return _DLR_TO_STRING[self]


_DLR_TO_STRING = {
    DecisionListResult.PASSWORD_PROTECTED_PRIORITY_PASS: "PasswordProtectedPriorityPass",
    DecisionListResult.PASSWORD_PROTECTED_PATH: "PasswordProtectedPath",
    DecisionListResult.PASSWORD_PROTECTED_PATH_EXCEPTION: "PasswordProtectedPathException",
    DecisionListResult.PER_SITE_ACCESS_GRANTED: "PerSiteAccessGranted",
    DecisionListResult.PER_SITE_CHALLENGE: "PerSiteChallenge",
    DecisionListResult.PER_SITE_BLOCK: "PerSiteBlock",
    DecisionListResult.GLOBAL_ACCESS_GRANTED: "GlobalAccessGranted",
    DecisionListResult.GLOBAL_CHALLENGE: "GlobalChallenge",
    DecisionListResult.GLOBAL_BLOCK: "GlobalBlock",
    DecisionListResult.EXPIRING_ACCESS_GRANTED: "ExpiringAccessGranted",
    DecisionListResult.EXPIRING_CHALLENGE: "ExpiringChallenge",
    DecisionListResult.EXPIRING_BLOCK: "ExpiringBlock",
    DecisionListResult.PER_SITE_SHA_INV_PATH_EXCEPTION: "PerSiteShaInvPathException",
    DecisionListResult.SITE_WIDE_CHALLENGE: "SiteWideChallenge",
    DecisionListResult.SITE_WIDE_CHALLENGE_EXCEPTION: "SiteWideChallengeException",
    DecisionListResult.PER_SITE_UA_ACCESS_GRANTED: "PerSiteUAAccessGranted",
    DecisionListResult.PER_SITE_UA_CHALLENGE: "PerSiteUAChallenge",
    DecisionListResult.PER_SITE_UA_BLOCK: "PerSiteUABlock",
    DecisionListResult.GLOBAL_UA_ACCESS_GRANTED: "GlobalUAAccessGranted",
    DecisionListResult.GLOBAL_UA_CHALLENGE: "GlobalUAChallenge",
    DecisionListResult.GLOBAL_UA_BLOCK: "GlobalUABlock",
    DecisionListResult.NO_MENTION: "NoMention",
    DecisionListResult.NOT_SET: "NotSet",
}


@dataclasses.dataclass
class DecisionForNginxResult:
    """http_server.go:816-825 — the per-request JSON log record."""

    client_ip: str = ""
    requested_host: str = ""
    requested_path: str = ""
    decision_list_result: DecisionListResult = DecisionListResult.NOT_SET
    password_challenge_result: Optional[PasswordChallengeResult] = None
    sha_challenge_result: Optional[ShaChallengeResult] = None
    too_many_failed_challenges_result: Optional[RateLimitResult] = None
    client_user_agent: str = ""

    def to_json(self) -> str:
        d = {
            "ClientIp": self.client_ip,
            "RequestedHost": self.requested_host,
            "RequestedPath": self.requested_path,
            "DecisionListResult": str(self.decision_list_result),
            "PasswordChallengeResult": (
                str(self.password_challenge_result)
                if self.password_challenge_result is not None
                else None
            ),
            "ShaChallengeResult": (
                str(self.sha_challenge_result)
                if self.sha_challenge_result is not None
                else None
            ),
            "TooManyFailedChallengesResult": (
                {
                    "MatchType": str(self.too_many_failed_challenges_result.match_type),
                    "Exceeded": self.too_many_failed_challenges_result.exceeded,
                }
                if self.too_many_failed_challenges_result is not None
                else None
            ),
            "ClientUserAgent": self.client_user_agent,
        }
        return json.dumps(d)


# ----------------------------------------------------------------- context


@dataclasses.dataclass
class ChainState:
    """Everything decisionForNginx needs, bundled (http_server.go:827-834)."""

    config: Config
    static_lists: StaticDecisionLists
    dynamic_lists: DynamicDecisionLists
    protected_paths: PasswordProtectedPaths
    failed_challenge_states: FailedChallengeRateLimitStates
    banner: BannerInterface
    # optional device-batched PoW verifier (challenge/verifier.py);
    # None = the pure-CPU reference path, decisions identical either way
    challenge_verifier: Optional[
        challenge_verifier_mod.DeviceVerifier
    ] = None


# --------------------------------------------------------- response helpers


def clean_requested_path(requested_path: str) -> str:
    """http_server.go:1138-1142."""
    path = "/" + requested_path.strip("/")
    return path.split("?")[0]


def _get_user_agent_or_ip(config: Config, req: RequestInfo) -> str:
    """Cookie binding selector (http_server.go:406-413)."""
    if req.requested_host in config.use_user_agent_in_cookie:
        return req.client_user_agent
    return req.client_ip


def _session_cookie_endpoint(config: Config, req: RequestInfo, resp: Response) -> None:
    """session_cookie.go:106-161 — validate-or-issue on every response."""
    dsc = req.cookie(SESSION_COOKIE_NAME)
    if dsc is not None:
        # the reference QueryUnescapes a second time on top of gin's read,
        # falling back to the original on error (session_cookie.go:122-129)
        try:
            url_decoded = go_query_unescape(dsc)
        except ValueError:
            url_decoded = dsc
        try:
            validate_session_cookie(
                url_decoded, config.session_cookie_hmac_secret, time.time(), req.client_ip
            )
            valid = True
        except SessionCookieError:
            valid = False
        if valid or config.session_cookie_not_verify:
            _attach_session_cookie(config, resp, url_decoded, False)
        else:
            new_dsc = new_session_cookie(
                config.session_cookie_hmac_secret,
                config.session_cookie_ttl_seconds,
                req.client_ip,
            )
            _attach_session_cookie(config, resp, new_dsc, True)
        return
    new_dsc = new_session_cookie(
        config.session_cookie_hmac_secret, config.session_cookie_ttl_seconds, req.client_ip
    )
    _attach_session_cookie(config, resp, new_dsc, True)


def _attach_session_cookie(config: Config, resp: Response, dsc: str, dsc_new: bool) -> None:
    if dsc_new:
        resp.cookies.append(
            SetCookie(
                SESSION_COOKIE_NAME, dsc, config.session_cookie_ttl_seconds,
                path="/", domain="", secure=False, http_only=True,
            )
        )
    resp.headers["X-Deflect-Session"] = dsc
    resp.headers["X-Deflect-Session-New"] = "true" if dsc_new else "false"


def _bot_score_headers(
    resp: Response, bot_score: float, top_factor: str, fingerprint: IntegrityCheckPayloadWrapper
) -> None:
    if bot_score >= 0:
        resp.headers["X-Banjax-Bot-Score"] = f"{bot_score:f}"
        resp.headers["X-Banjax-Bot-Score-Top-Factor"] = top_factor
        resp.headers["X-Banjax-Bot-Fingerprint"] = fingerprint.hash
        resp.headers["X-Banjax-Bot-Fingerprint-Full"] = json.dumps(
            fingerprint.payload.to_json_dict()
        )


def access_granted(
    config: Config,
    req: RequestInfo,
    decision_list_result_string: str,
    bot_score: float = -1.0,
    bot_score_top_factor: str = "",
    bot_fingerprint: Optional[IntegrityCheckPayloadWrapper] = None,
) -> Response:
    """http_server.go:347-365."""
    resp = Response(status=200, body=b"access granted\n")
    _bot_score_headers(resp, bot_score, bot_score_top_factor,
                       bot_fingerprint or IntegrityCheckPayloadWrapper())
    resp.headers["X-Banjax-Decision"] = decision_list_result_string
    resp.headers["X-Accel-Redirect"] = "@access_granted"
    _session_cookie_endpoint(config, req, resp)
    return resp


def access_denied(
    config: Config,
    req: RequestInfo,
    decision_list_result_string: str,
    bot_score: float = -1.0,
    bot_score_top_factor: str = "",
    bot_fingerprint: Optional[IntegrityCheckPayloadWrapper] = None,
) -> Response:
    """http_server.go:367-386."""
    resp = Response(status=403, body=b"access denied\n")
    _bot_score_headers(resp, bot_score, bot_score_top_factor,
                       bot_fingerprint or IntegrityCheckPayloadWrapper())
    resp.headers["X-Banjax-Decision"] = decision_list_result_string
    resp.headers["Cache-Control"] = "no-cache,no-store"
    resp.headers["X-Accel-Redirect"] = "@access_denied"
    _session_cookie_endpoint(config, req, resp)
    return resp


def _challenge_cookie(
    config: Config, req: RequestInfo, resp: Response, cookie_name: str,
    cookie_ttl_seconds: int, secret: str, set_domain_scope: bool,
) -> None:
    """http_server.go:388-404 — routed through the stateless issuer so
    every mint crosses the challenge.issue failpoint and counter."""
    new_cookie = challenge_issuer.issue(
        secret, cookie_ttl_seconds, _get_user_agent_or_ip(config, req)
    )
    domain_scope = req.requested_host if set_domain_scope else ""
    resp.cookies.append(
        SetCookie(cookie_name, new_cookie, cookie_ttl_seconds,
                  path="/", domain=domain_scope, secure=False, http_only=False)
    )
    resp.headers["Cache-Control"] = "no-cache,no-store"


def _get_per_site_cookie_ttl_or_default(config: Config, domain: str, default_ttl: int) -> int:
    return config.password_persite_cookie_ttl_seconds.get(domain, default_ttl)


def password_challenge(config: Config, req: RequestInfo, roaming: bool) -> Response:
    """http_server.go:415-421 — 401 + rewritten page + new unsolved cookie."""
    resp = Response(status=401, content_type="text/html")
    cookie_ttl = _get_per_site_cookie_ttl_or_default(
        config, req.requested_host, config.password_cookie_ttl_seconds
    )
    _challenge_cookie(config, req, resp, PASSWORD_COOKIE_NAME, cookie_ttl,
                      config.hmac_secret, roaming)
    _session_cookie_endpoint(config, req, resp)
    resp.body = apply_args_to_password_page(config.password_page_bytes, roaming, cookie_ttl)
    return resp


def sha_inv_challenge(config: Config, req: RequestInfo) -> Response:
    """http_server.go:423-428 — 429 + rewritten page + new unsolved cookie."""
    resp = Response(status=429, content_type="text/html")
    _challenge_cookie(config, req, resp, CHALLENGE_COOKIE_NAME,
                      config.sha_inv_cookie_ttl_seconds, config.hmac_secret, False)
    _session_cookie_endpoint(config, req, resp)
    resp.body = apply_args_to_sha_inv_page(config)
    return resp


# ----------------------------------------------------- challenge sub-flows


def too_many_failed_challenges(
    state: ChainState, req: RequestInfo, challenge_type: str
) -> RateLimitResult:
    """http_server.go:494-532 — on exceed, ban (NginxBlock if per-site
    allowlisted, else IptablesBlock) and write the failed-challenge ban log."""
    config = state.config
    result = state.failed_challenge_states.apply(req.client_ip, config)
    if result.exceeded:
        decision, found = state.static_lists.check_per_site(req.requested_host, req.client_ip)
        decision_type = Decision.IPTABLES_BLOCK
        if found and decision == Decision.ALLOW:
            log.info(
                "!! IP %s failed too many challenges on host %s but is allowlisted, no iptables ban",
                req.client_ip, req.requested_host,
            )
            decision_type = Decision.NGINX_BLOCK
        state.banner.ban_or_challenge_ip(config, req.client_ip, decision_type, req.requested_host)
        state.banner.log_failed_challenge_ban(
            config, req.client_ip, challenge_type, req.requested_host, req.requested_path,
            config.too_many_failed_challenges_threshold, req.client_user_agent,
            decision_type, req.method,
        )
        provenance.record(
            provenance.SOURCE_CHALLENGE, req.client_ip, decision_type,
            rule=f"failed challenge {challenge_type}",
            hits=config.too_many_failed_challenges_threshold,
        )
    return result


def send_or_validate_sha_challenge(
    state: ChainState, req: RequestInfo, fail_action: FailAction
) -> Tuple[Response, ShaChallengeResult, RateLimitResult]:
    """http_server.go:571-626."""
    config = state.config
    challenge_cookie = req.cookie(CHALLENGE_COOKIE_NAME)
    integrity_cookie = req.cookie(INTEGRITY_CHECK_COOKIE_NAME) or ""
    bot_score, top_factor, fingerprint = calc_bot_score_from_cookie(integrity_cookie)

    # one span around validate -> fail -> ban so a challenge_failure
    # provenance record carries the same trace id as the verification
    # that produced it (joinable in /decisions/explain and /debug/trace).
    # The HTTP path has no ambient pipeline span, so the span roots its
    # own trace id; new_trace() returns 0 (span stays inert) when off.
    tid = trace.current_trace_id() or trace.new_trace()
    with trace.span("challenge.sha_inv", trace_id=tid,
                    args={"ip": req.client_ip}):
        if challenge_cookie is not None:
            try:
                challenge_verifier_mod.verify_sha_inv(
                    config.hmac_secret, challenge_cookie, time.time(),
                    _get_user_agent_or_ip(config, req),
                    config.sha_inv_expected_zero_bits,
                    device=state.challenge_verifier,
                )
                resp = access_granted(
                    config, req, str(ShaChallengeResult.PASSED), bot_score, top_factor, fingerprint
                )
                report_passed_failed_banned_message(
                    config, "ip_passed_challenge", req.client_ip, req.requested_host
                )
                return resp, ShaChallengeResult.PASSED, RateLimitResult()
            except CookieError:
                sha_result = ShaChallengeResult.FAILED_BAD_COOKIE
        else:
            sha_result = ShaChallengeResult.FAILED_NO_COOKIE

        report_passed_failed_banned_message(
            config, "ip_failed_challenge", req.client_ip, req.requested_host
        )
        if fail_action == FailAction.BLOCK:
            rate_result = too_many_failed_challenges(state, req, "sha_inv")
            if rate_result.exceeded:
                report_passed_failed_banned_message(
                    config, "ip_banned", req.client_ip, req.requested_host
                )
                resp = access_denied(
                    config, req, "TooManyFailedChallenges", bot_score, top_factor, fingerprint
                )
                return resp, sha_result, rate_result
            return sha_inv_challenge(config, req), sha_result, rate_result
        return sha_inv_challenge(config, req), sha_result, RateLimitResult()


def send_or_validate_password(
    state: ChainState, req: RequestInfo
) -> Tuple[Response, PasswordChallengeResult, RateLimitResult]:
    """http_server.go:671-745."""
    config = state.config
    password_cookie = req.cookie(PASSWORD_COOKIE_NAME)

    if password_cookie is not None:
        expected_hash, ok = state.protected_paths.get_password_hash(req.requested_host)
        if not ok:
            log.error("!!!! BAD - missing password in config")
            # reference returns without any terminal response here
            # (http_server.go:688-691) — the request falls through with no
            # X-Accel-Redirect; reproduce as an empty 200 with no headers
            return Response(status=200), PasswordChallengeResult.ERROR_NO_PASSWORD, RateLimitResult()
        try:
            validate_password_cookie(
                config.hmac_secret, password_cookie, time.time(),
                _get_user_agent_or_ip(config, req), expected_hash,
            )
            resp = access_granted(config, req, str(PasswordChallengeResult.PASSED))
            report_passed_failed_banned_message(
                config, "ip_passed_challenge", req.client_ip, req.requested_host
            )
            return resp, PasswordChallengeResult.PASSED, RateLimitResult()
        except CookieError:
            roaming_hash, has_roaming = state.protected_paths.get_roaming_password_hash(
                req.requested_host
            )
            if has_roaming:
                try:
                    validate_password_cookie(
                        config.hmac_secret, password_cookie, time.time(),
                        _get_user_agent_or_ip(config, req), roaming_hash,
                    )
                    resp = access_granted(
                        config, req, str(PasswordChallengeResult.ROAMING_PASSED)
                    )
                    report_passed_failed_banned_message(
                        config, "ip_passed_challenge", req.client_ip, req.requested_host
                    )
                    return resp, PasswordChallengeResult.ROAMING_PASSED, RateLimitResult()
                except CookieError:
                    password_result = PasswordChallengeResult.FAILED_BAD_COOKIE
            else:
                password_result = PasswordChallengeResult.FAILED_BAD_COOKIE
    else:
        password_result = PasswordChallengeResult.FAILED_NO_COOKIE

    report_passed_failed_banned_message(
        config, "ip_failed_challenge", req.client_ip, req.requested_host
    )
    rate_result = too_many_failed_challenges(state, req, "password")
    if rate_result.exceeded:
        report_passed_failed_banned_message(
            config, "ip_banned", req.client_ip, req.requested_host
        )
        resp = access_denied(config, req, "TooManyFailedPassword")
        return resp, password_result, rate_result
    _, allow_roaming = state.protected_paths.get_expand_cookie_domain(req.requested_host)
    return password_challenge(config, req, allow_roaming), password_result, rate_result


# ------------------------------------------------------------ the chain


def _check_expiring_decision_lists(
    state: ChainState, req: RequestInfo
) -> Tuple[Optional[ExpiringDecision], bool]:
    """http_server.go:1144-1147 — session cookie id first, then IP (the
    cookie value was already query-unescaped at the request layer)."""
    session_id = req.cookie(SESSION_COOKIE_NAME) or ""
    return state.dynamic_lists.check(session_id, req.client_ip)


def _check_per_site_sha_inv_path_exceptions(config: Config, host: str, path: str) -> bool:
    """http_server.go:1149-1165 — prefix match on raw requested path."""
    for exception in config.sha_inv_path_exceptions.get(host, []):
        if path.startswith(exception):
            return True
    return False


def decision_for_nginx(
    state: ChainState, req: RequestInfo
) -> Tuple[Response, DecisionForNginxResult]:
    """Port of decisionForNginx2 (http_server.go:861-1136)."""
    # fault-injection seam: an armed `decision_chain` failpoint raises here
    # so tests/faults/ can prove the recovery middleware's fail-open
    # contract (500 + X-Accel-Redirect: @fail_open) end to end
    failpoints.check("decision_chain")
    config = state.config
    result = DecisionForNginxResult(
        client_ip=req.client_ip,
        requested_host=req.requested_host,
        requested_path=req.requested_path,
        decision_list_result=DecisionListResult.NOT_SET,
        client_user_agent=req.client_user_agent,
    )
    requested_protected_path = clean_requested_path(req.requested_path)

    # 1. priority pass with a valid password cookie (http_server.go:886-907)
    password_cookie = req.cookie(PASSWORD_COOKIE_NAME)
    if password_cookie is not None:
        grant = False
        expected_hash, has_hash = state.protected_paths.get_password_hash(req.requested_host)
        roaming_hash, has_roaming = state.protected_paths.get_roaming_password_hash(
            req.requested_host
        )
        if has_hash:
            try:
                validate_password_cookie(
                    config.hmac_secret, password_cookie, time.time(), req.client_ip,
                    expected_hash,
                )
                grant = True
            except CookieError:
                pass
        elif has_roaming:
            try:
                validate_password_cookie(
                    config.hmac_secret, password_cookie, time.time(), req.client_ip,
                    roaming_hash,
                )
                grant = True
            except CookieError:
                pass
        if grant:
            result.decision_list_result = DecisionListResult.PASSWORD_PROTECTED_PRIORITY_PASS
            resp = access_granted(config, req, str(result.decision_list_result))
            return resp, result

    # 2. password-protected path classification (http_server.go:909-930)
    path_type = state.protected_paths.classify_path(
        req.requested_host, requested_protected_path
    )
    if path_type == PathType.PASSWORD_PROTECTED:
        resp, password_result, rate_result = send_or_validate_password(state, req)
        result.decision_list_result = DecisionListResult.PASSWORD_PROTECTED_PATH
        result.password_challenge_result = password_result
        result.too_many_failed_challenges_result = rate_result
        return resp, result
    if path_type == PathType.PASSWORD_PROTECTED_EXCEPTION:
        result.decision_list_result = DecisionListResult.PASSWORD_PROTECTED_PATH_EXCEPTION
        resp = access_granted(config, req, str(result.decision_list_result))
        return resp, result

    # 3. per-site IP list (http_server.go:932-964)
    decision, found = state.static_lists.check_per_site(req.requested_host, req.client_ip)
    if found:
        outcome = _apply_static_decision(
            state, req, result, decision,
            DecisionListResult.PER_SITE_ACCESS_GRANTED,
            DecisionListResult.PER_SITE_CHALLENGE,
            DecisionListResult.PER_SITE_BLOCK,
        )
        if outcome is not None:
            return outcome, result

    # 4. per-site UA list (http_server.go:966-991)
    ua_decision, found = state.static_lists.check_per_site_user_agent(
        req.requested_host, req.client_user_agent
    )
    if found:
        outcome = _apply_static_decision(
            state, req, result, ua_decision,
            DecisionListResult.PER_SITE_UA_ACCESS_GRANTED,
            DecisionListResult.PER_SITE_UA_CHALLENGE,
            DecisionListResult.PER_SITE_UA_BLOCK,
            prov_source=provenance.SOURCE_UA,
        )
        if outcome is not None:
            return outcome, result

    # 5. global IP list (http_server.go:993-1021)
    decision, found = state.static_lists.check_global(req.client_ip)
    if found:
        outcome = _apply_static_decision(
            state, req, result, decision,
            DecisionListResult.GLOBAL_ACCESS_GRANTED,
            DecisionListResult.GLOBAL_CHALLENGE,
            DecisionListResult.GLOBAL_BLOCK,
        )
        if outcome is not None:
            return outcome, result

    # 6. global UA list (http_server.go:1023-1048)
    ua_decision, found = state.static_lists.check_global_user_agent(req.client_user_agent)
    if found:
        outcome = _apply_static_decision(
            state, req, result, ua_decision,
            DecisionListResult.GLOBAL_UA_ACCESS_GRANTED,
            DecisionListResult.GLOBAL_UA_CHALLENGE,
            DecisionListResult.GLOBAL_UA_BLOCK,
            prov_source=provenance.SOURCE_UA,
        )
        if outcome is not None:
            return outcome, result

    # 7. expiring (dynamic) lists (http_server.go:1054-1100)
    expiring_decision, found = _check_expiring_decision_lists(state, req)
    baskerville_disabled = req.requested_host in config.sites_to_disable_baskerville
    if found:
        if expiring_decision.decision == Decision.ALLOW:
            result.decision_list_result = DecisionListResult.EXPIRING_ACCESS_GRANTED
            resp = access_granted(config, req, str(result.decision_list_result))
            return resp, result
        if expiring_decision.decision == Decision.CHALLENGE:
            if _check_per_site_sha_inv_path_exceptions(
                config, req.requested_host, req.requested_path
            ):
                result.decision_list_result = DecisionListResult.PER_SITE_SHA_INV_PATH_EXCEPTION
                resp = access_granted(config, req, str(result.decision_list_result))
                return resp, result
            if expiring_decision.from_baskerville and baskerville_disabled:
                log.info(
                    "DIS-BASK: domain %s disabled baskerville, skip expiring challenge for %s",
                    req.requested_host, req.client_ip,
                )
            else:
                resp, sha_result, rate_result = send_or_validate_sha_challenge(
                    state, req, FailAction.BLOCK
                )
                result.decision_list_result = DecisionListResult.EXPIRING_CHALLENGE
                result.sha_challenge_result = sha_result
                result.too_many_failed_challenges_result = rate_result
                return resp, result
        elif expiring_decision.decision in (Decision.NGINX_BLOCK, Decision.IPTABLES_BLOCK):
            if expiring_decision.from_baskerville and baskerville_disabled:
                log.info(
                    "DIS-BASK: domain %s disabled baskerville, skip expiring block for %s",
                    req.requested_host, req.client_ip,
                )
            else:
                result.decision_list_result = DecisionListResult.EXPIRING_BLOCK
                resp = access_denied(config, req, str(result.decision_list_result))
                return resp, result

    # 8. sitewide SHA-inv list (http_server.go:1104-1128)
    fail_action, found = state.static_lists.check_sitewide_sha_inv(req.requested_host)
    if found:
        if state.protected_paths.is_exception(req.requested_host, requested_protected_path):
            result.decision_list_result = DecisionListResult.SITE_WIDE_CHALLENGE_EXCEPTION
            resp = access_granted(config, req, str(result.decision_list_result))
        else:
            resp, sha_result, rate_result = send_or_validate_sha_challenge(
                state, req, fail_action
            )
            result.decision_list_result = DecisionListResult.SITE_WIDE_CHALLENGE
            result.sha_challenge_result = sha_result
            result.too_many_failed_challenges_result = rate_result
        return resp, result

    # 9. default allow (http_server.go:1130-1135)
    if result.decision_list_result == DecisionListResult.NOT_SET:
        result.decision_list_result = DecisionListResult.NO_MENTION
    resp = access_granted(config, req, str(result.decision_list_result))
    return resp, result


def _apply_static_decision(
    state: ChainState,
    req: RequestInfo,
    result: DecisionForNginxResult,
    decision: Decision,
    granted: DecisionListResult,
    challenge: DecisionListResult,
    block: DecisionListResult,
    prov_source: str = provenance.SOURCE_STATIC,
) -> Optional[Response]:
    """The shared Allow/Challenge/Block arm for chain steps 3-6.

    Every acted-on list hit lands in the provenance ledger (the rule
    field carries the chain arm, e.g. "PerSiteBlock") — static and UA
    list hits are two of the four decision sources the reference
    attributes bans to (PAPER.md §0)."""
    config = state.config
    if decision == Decision.ALLOW:
        result.decision_list_result = granted
        provenance.record(prov_source, req.client_ip, decision,
                          rule=str(granted))
        return access_granted(config, req, str(granted))
    if decision == Decision.CHALLENGE:
        resp, sha_result, rate_result = send_or_validate_sha_challenge(
            state, req, FailAction.BLOCK
        )
        result.decision_list_result = challenge
        result.sha_challenge_result = sha_result
        result.too_many_failed_challenges_result = rate_result
        provenance.record(prov_source, req.client_ip, decision,
                          rule=str(challenge))
        return resp
    if decision in (Decision.NGINX_BLOCK, Decision.IPTABLES_BLOCK):
        result.decision_list_result = block
        provenance.record(prov_source, req.client_ip, decision,
                          rule=str(block))
        return access_denied(config, req, str(block))
    return None
