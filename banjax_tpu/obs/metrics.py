"""Metrics reporter: the 29-second JSON metrics line.

Reference behavior: /root/reference/banjax.go:231-275 + config.go:150-181 —
every 29 s write one JSON object {Time, LenExpiringChallenges,
LenExpiringBlocks, LenIpToRegexStates, LenFailedChallengeStates} to
metrics_log_file (or `list-metrics.log` in standalone testing).

The reference's five keys keep their exact names and meaning; the TPU
subsystem's production counters (matcher lines/sec, batch latency p50/p99,
device-windows occupancy/evictions — obs/stats.py) are ADDITIVE keys on the
same line, present when a matcher is wired in.

Every key this line can emit is declared in obs/registry.py — the same
registry /metrics (obs/exposition.py) renders from — so the two surfaces
cannot drift apart silently (tests/unit/test_exposition.py).  The line
keeps the resetting interval windows (snapshot()); /metrics reads only
the non-destructive peek() views.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional, TextIO

from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)

REPORT_INTERVAL_SECONDS = 29  # banjax.go:196


def _kafka_skipped_batches() -> int:
    """Lazy import: the metrics line must not pay a kafka import (or fail)
    when kafka is disabled."""
    try:
        from banjax_tpu.ingest import kafka_wire

        return kafka_wire.skipped_batch_count()
    except Exception:  # noqa: BLE001 — metrics must never take the reporter down
        return 0


def write_metrics_line(
    out: TextIO,
    dynamic_lists: DynamicDecisionLists,
    regex_states: RegexRateLimitStates,
    failed_challenge_states: FailedChallengeRateLimitStates,
    matcher=None,
    supervisor=None,
    health=None,
    pipeline=None,
    fabric=None,
) -> None:
    challenges, blocks = dynamic_lists.metrics()
    line = {
        "Time": time.strftime("%a, %d %b %Y %H:%M:%S %Z"),
        "LenExpiringChallenges": challenges,
        "LenExpiringBlocks": blocks,
        "LenIpToRegexStates": len(regex_states),
        "LenFailedChallengeStates": len(failed_challenge_states),
    }
    if matcher is not None:
        line.update(
            matcher.stats.snapshot(
                getattr(matcher, "device_windows", None), matcher
            )
        )
    if pipeline is not None:
        # streaming pipeline scheduler: per-stage EWMA latencies, queue
        # depths, shed/stale counters (banjax_tpu/pipeline/scheduler.py)
        line.update(pipeline.snapshot())
    if fabric is not None:
        # multi-host decision fabric: routed/forwarded/shed line counts,
        # replication + takeover counters (banjax_tpu/fabric/stats.py)
        line.update(fabric.peek())
    # challenge plane (banjax_tpu/challenge/stats.py — a leaf module):
    # issuance/verification totals + bounded failure-state occupancy,
    # present only when this process touched the challenge plane so the
    # reference's exact key set is preserved otherwise
    try:
        from banjax_tpu.challenge.stats import get_stats as _challenge_stats

        chal = _challenge_stats()
        chal_snap = chal.prom_snapshot() if chal.active() else None
    except Exception:  # noqa: BLE001 — a leaf must not break the line
        chal_snap = None
    if chal_snap is not None:
        line["ChallengeIssued"] = chal_snap["issued_total"]
        line["ChallengeVerifications"] = chal_snap["verifications_total"]
        line["ChallengeFailureStateEntries"] = chal_snap[
            "failure_state_entries"
        ]
        line["ChallengeFailureEvictions"] = chal_snap[
            "failure_evictions_total"
        ]
    # compiled serving fast path (httpapi/serve_stats.py — a leaf
    # module): same presence rule — only once the fast path ran here
    try:
        from banjax_tpu.httpapi.serve_stats import get_stats as _serve_stats

        serve = _serve_stats()
        serve_snap = serve.prom_snapshot() if serve.active() else None
    except Exception:  # noqa: BLE001 — a leaf must not break the line
        serve_snap = None
    if serve_snap is not None:
        line["ServeFastpathHits"] = serve_snap["hits_total"]
        line["ServeFastpathMisses"] = serve_snap["misses_total"]
        line["ServeFastpathFaults"] = serve_snap["faults_total"]
        line["ServeTableEntries"] = serve_snap["table_entries"]
        line["ServeTableDropped"] = serve_snap["table_dropped_total"]
        line["ServeMirrorErrors"] = serve_snap["mirror_errors_total"]
    # kernel-edge ban batching (effectors/ipset_stats.py — a leaf module)
    try:
        from banjax_tpu.effectors.ipset_stats import get_stats as _ipset_stats

        ipset = _ipset_stats()
        ipset_snap = ipset.prom_snapshot() if ipset.active() else None
    except Exception:  # noqa: BLE001 — a leaf must not break the line
        ipset_snap = None
    if ipset_snap is not None:
        line["IpsetBatchSends"] = ipset_snap["batch_sends_total"]
        line["IpsetBatchEntries"] = ipset_snap["batch_entries_total"]
        line["IpsetErrors"] = ipset_snap["errors_total"]
        line["IpsetFallbacks"] = ipset_snap["fallback_total"]
        line["IpsetQueueShed"] = ipset_snap["queue_shed_total"]
    # Kafka batches skipped for an undecodable codec (lz4/zstd — VERDICT
    # C17): surfaced only when nonzero so the reference's exact key set is
    # preserved on clean streams
    skipped = _kafka_skipped_batches()
    if skipped:
        line["KafkaSkippedBatches"] = skipped
    if supervisor is not None:
        # multi-worker serving health: nonzero respawns = workers crashed
        # and were healed (httpapi/workers.py monitor)
        line["HttpWorkers"] = supervisor.n_workers
        line["HttpWorkerRespawns"] = supervisor.respawn_count
        line["HttpFcDropped"] = getattr(
            failed_challenge_states, "dropped", 0
        )
    if health is not None:
        # component health (resilience/health.py): the /healthz aggregate,
        # flattened onto the line so degraded modes are greppable in the
        # same metrics stream operators already tail
        snap = health.snapshot()
        line["HealthStatus"] = snap["status"]
        for name, comp in sorted(snap["components"].items()):
            line[f"Health_{name}"] = comp["status"]
    out.write(json.dumps(line) + "\n")
    out.flush()


class MetricsReporter:
    def __init__(
        self,
        log_path: str,
        dynamic_lists: DynamicDecisionLists,
        regex_states: RegexRateLimitStates,
        failed_challenge_states: FailedChallengeRateLimitStates,
        interval_seconds: float = REPORT_INTERVAL_SECONDS,
        matcher_getter: Optional[Callable[[], object]] = None,
        supervisor_getter: Optional[Callable[[], object]] = None,
        health=None,
        pipeline_getter: Optional[Callable[[], object]] = None,
        fabric_getter: Optional[Callable[[], object]] = None,
    ):
        self.log_path = log_path
        self.dynamic_lists = dynamic_lists
        self.regex_states = regex_states
        self.failed_challenge_states = failed_challenge_states
        self.interval_seconds = interval_seconds
        # a getter, not the matcher itself: SIGHUP reload swaps the matcher
        self.matcher_getter = matcher_getter
        self.supervisor_getter = supervisor_getter
        self.health = health
        self.pipeline_getter = pipeline_getter
        self.fabric_getter = fabric_getter
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if not self.log_path:
            return
        self._thread = threading.Thread(target=self._run, name="metrics-reporter", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        with open(self.log_path, "w", encoding="utf-8") as out:
            while not self._stop.wait(self.interval_seconds):
                matcher = self.matcher_getter() if self.matcher_getter else None
                supervisor = (
                    self.supervisor_getter() if self.supervisor_getter else None
                )
                pipeline = (
                    self.pipeline_getter() if self.pipeline_getter else None
                )
                fabric = (
                    self.fabric_getter() if self.fabric_getter else None
                )
                write_metrics_line(
                    out, self.dynamic_lists, self.regex_states,
                    self.failed_challenge_states, matcher, supervisor,
                    self.health, pipeline, fabric,
                )
