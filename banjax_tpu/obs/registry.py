"""The single exposition-schema registry.

Every key the 29-second metrics line can emit and every Prometheus
family `/metrics` can expose is declared HERE — name, type, help — so a
renamed counter fails CI (tests/unit/test_exposition.py asserts real
snapshots against this table, and scripts/check_metrics_docs.py
cross-checks the README's documented metrics table) instead of silently
breaking dashboards.

Two namespaces share one declaration:

  * `line_key` — the additive CamelCase key on the legacy 29 s JSON
    line (obs/metrics.py).  The reference's five keys keep their exact
    bytes (REFERENCE_LINE_KEYS); everything else is additive.
  * `prom` — the `banjax_*` family `/metrics` exposes
    (obs/exposition.py).  Interval-window keys (lines/sec, per-interval
    deltas) are line-only: Prometheus computes rates server-side from
    the monotone totals, and exposing the resetting window would make
    scrapes steal the 29 s line's deltas.

Histograms (fixed buckets, cumulative) live here too so the recorder
(obs/stats.py, pipeline/scheduler.py) and the renderer agree on bucket
bounds by construction.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

# counter: monotone total; gauge: point-in-time value; histogram:
# fixed-bucket cumulative distribution (prom-only)
COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# the reference's exact five keys (config.go:158-181) — byte-identical,
# asserted by tests/unit/test_exposition.py
REFERENCE_LINE_KEYS = (
    "Time",
    "LenExpiringChallenges",
    "LenExpiringBlocks",
    "LenIpToRegexStates",
    "LenFailedChallengeStates",
)

# fixed latency buckets (seconds) shared by every duration histogram:
# sub-ms host stages through multi-second wedged-device tails
LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# fixed size buckets (bytes) for wire-frame histograms: a single small
# control frame through a maximally coalesced fabric_frame_max_bytes blob
FRAME_BYTES_BUCKETS = (
    256.0, 1024.0, 4096.0, 16384.0, 65536.0,
    262144.0, 1048576.0, 4194304.0,
)


@dataclasses.dataclass(frozen=True)
class Family:
    """One declared metric family.  `line_key` and/or `prom` may be
    empty — a family can live on one surface only."""

    kind: str
    help: str
    line_key: str = ""
    prom: str = ""
    labels: Tuple[str, ...] = ()


FAMILIES: List[Family] = [
    # ---- reference line keys (gauges; Time is the line's timestamp) ----
    Family(GAUGE, "metrics line timestamp (reference format)",
           line_key="Time"),
    Family(GAUGE, "expiring challenge decisions held",
           line_key="LenExpiringChallenges",
           prom="banjax_expiring_challenges"),
    Family(GAUGE, "expiring block decisions held",
           line_key="LenExpiringBlocks", prom="banjax_expiring_blocks"),
    Family(GAUGE, "per-IP regex rate-limit states held",
           line_key="LenIpToRegexStates",
           prom="banjax_ip_to_regex_states"),
    Family(GAUGE, "failed-challenge rate-limit states held",
           line_key="LenFailedChallengeStates",
           prom="banjax_failed_challenge_states"),
    # ---- matcher core ----
    Family(COUNTER, "log lines consumed by the matcher",
           line_key="MatcherLinesTotal", prom="banjax_matcher_lines_total"),
    Family(COUNTER, "matcher batches consumed",
           line_key="MatcherBatchesTotal",
           prom="banjax_matcher_batches_total"),
    Family(GAUGE, "lines/sec over the last reporting interval (line-only; "
           "Prometheus rates banjax_matcher_lines_total instead)",
           line_key="MatcherLinesPerSec"),
    Family(GAUGE, "p50 batch latency (ms) over the recent-latency ring",
           line_key="MatcherBatchLatencyP50Ms"),
    Family(GAUGE, "p99 batch latency (ms) over the recent-latency ring",
           line_key="MatcherBatchLatencyP99Ms"),
    Family(COUNTER, "host->device bytes moved by the matcher",
           line_key="MatcherH2dBytesTotal",
           prom="banjax_matcher_h2d_bytes_total"),
    Family(COUNTER, "device->host bytes moved by the matcher",
           line_key="MatcherD2hBytesTotal",
           prom="banjax_matcher_d2h_bytes_total"),
    Family(GAUGE, "h2d bytes per batch over this interval (the fused-path "
           "dense-reupload witness)", line_key="MatcherH2dBytesPerBatch"),
    Family(GAUGE, "d2h bytes per batch over this interval",
           line_key="MatcherD2hBytesPerBatch"),
    # ---- device windows ----
    Family(GAUGE, "device window slots occupied",
           line_key="DeviceWindowsOccupancy",
           prom="banjax_device_windows_occupancy"),
    Family(GAUGE, "device window slot capacity",
           line_key="DeviceWindowsCapacity",
           prom="banjax_device_windows_capacity"),
    Family(COUNTER, "device window LRU evictions (spill to host shadow)",
           line_key="DeviceWindowsEvictions",
           prom="banjax_device_windows_evictions_total"),
    Family(GAUGE, "evictions in this reporting interval (line-only delta)",
           line_key="DeviceWindowsEvictionsPerInterval"),
    Family(COUNTER, "device window capacity grows",
           line_key="DeviceWindowsGrows",
           prom="banjax_device_windows_grows_total"),
    Family(GAUGE, "1 when the native C slot manager is live, 0 on the "
           "Python dict path", line_key="SlotMgrNative",
           prom="banjax_slotmgr_native"),
    Family(GAUGE, "IPs with live window counters (evicted/spilled included)",
           line_key="DeviceWindowsShadowedIps",
           prom="banjax_device_windows_shadowed_ips"),
    # ---- mega-state tiering (README "Mega-state tiering") ----
    Family(COUNTER, "rows refused a device window slot by the sketch "
           "admission gate (matched and rate-limited statelessly on the "
           "host path — counted, never dropped)",
           line_key="SlotRefusals", prom="banjax_slot_refusals_total"),
    Family(COUNTER, "unseen IPs admitted to a slot because the count-min "
           "estimate reached the admission threshold",
           line_key="SketchAdmissions",
           prom="banjax_sketch_admissions_total"),
    Family(GAUGE, "fraction of sketch-admitted slots whose hot tenure "
           "ended with no window state (wasted admissions = collision "
           "noise; sizes traffic_sketch_width)",
           line_key="SketchAdmissionFpRate",
           prom="banjax_sketch_admission_fp_rate"),
    Family(COUNTER, "evicted slot window vectors spilled into the warm "
           "tier (native/shmstate.c)",
           line_key="WarmTierSpills", prom="banjax_warm_tier_spills_total"),
    Family(COUNTER, "warm-tier entries refilled into a device slot on "
           "re-admission",
           line_key="WarmTierRefills",
           prom="banjax_warm_tier_refills_total"),
    Family(COUNTER, "spills the warm tier refused (full of unexpired "
           "entries; state falls back losslessly to the host shadow — "
           "the raise-warm_tier_capacity signal)",
           line_key="WarmTierDropped",
           prom="banjax_warm_tier_dropped_total"),
    Family(GAUGE, "warm-tier entries occupied",
           line_key="WarmTierOccupancy", prom="banjax_warm_tier_occupancy"),
    Family(GAUGE, "warm-tier entry capacity",
           line_key="WarmTierCapacity", prom="banjax_warm_tier_capacity"),
    # ---- mesh ----
    Family(COUNTER, "sharded-mesh batches served by the fused two-stage path",
           line_key="MeshFusedBatches", prom="banjax_mesh_fused_batches_total"),
    Family(COUNTER, "sharded-mesh batches that fell back single-stage",
           line_key="MeshFallbackBatches",
           prom="banjax_mesh_fallback_batches_total"),
    Family(GAUGE, "EWMA mesh submit wall time (ms)",
           line_key="MeshSubmitMsEwma", prom="banjax_mesh_submit_ms_ewma"),
    Family(GAUGE, "EWMA mesh d2h merge wall time (ms)",
           line_key="MeshMergeMsEwma", prom="banjax_mesh_merge_ms_ewma"),
    Family(GAUGE, "slowest shard's d2h pull in the last merge (ms)",
           line_key="MeshShardMergeMsMax",
           prom="banjax_mesh_shard_merge_ms_max"),
    Family(GAUGE, "1 when the two-stage literal prefilter is active",
           line_key="PrefilterActive", prom="banjax_prefilter_active"),
    # ---- fused matcher+windows ----
    Family(COUNTER, "sync-path fused matcher+windows batches",
           line_key="PipelineFusedBatches",
           prom="banjax_fused_batches_total"),
    Family(COUNTER, "fallback batches (fused overflow / pipeline generic "
           "drain)", line_key="PipelineFallbackBatches",
           prom="banjax_fused_fallback_batches_total"),
    Family(COUNTER, "two-phase fused chunks committed via the pipeline",
           line_key="PipelinedFusedChunks",
           prom="banjax_pipelined_fused_chunks_total"),
    Family(COUNTER, "two-phase chunks replayed classically (overflow)",
           line_key="PipelinedFusedFallbacks",
           prom="banjax_pipelined_fused_fallbacks_total"),
    Family(GAUGE, "configured fused-drain resolve-ahead depth",
           line_key="DrainResolveAheadDepth",
           prom="banjax_drain_resolve_ahead_depth"),
    Family(GAUGE, "EWMA event-decode+replay ms hidden behind the next "
           "chunk's window program", line_key="DrainResolveOverlapMs",
           prom="banjax_drain_resolve_overlap_ms"),
    # ---- single-kernel fused path (kernels/fused_match_window.py) ----
    Family(COUNTER, "chunks committed by the single-kernel fused "
           "match+window program (one dispatch, one pull)",
           line_key="SingleKernelChunks",
           prom="banjax_single_kernel_chunks_total"),
    Family(COUNTER, "single-kernel chunks routed to the classic replay "
           "(in-kernel overflow or chain gate)",
           line_key="SingleKernelFallbacks",
           prom="banjax_single_kernel_fallbacks_total"),
    Family(GAUGE, "d2h bytes per committed single-kernel chunk (the "
           "one-pull witness: flags + pairs + events in ONE buffer)",
           line_key="SingleKernelD2hBytesPerBatch",
           prom="banjax_single_kernel_d2h_bytes_per_batch"),
    Family(GAUGE, "1 when drain_resolve_depth > 1 is configured but the "
           "single-kernel path makes it a no-op (no program-B dispatch "
           "left to overlap)",
           line_key="SingleKernelDepthIgnored",
           prom="banjax_single_kernel_depth_ignored"),
    # ---- breaker / degraded mode ----
    Family(GAUGE, "circuit breaker state (one-hot by state label)",
           line_key="MatcherBreakerState",
           prom="banjax_matcher_breaker_state", labels=("state",)),
    Family(COUNTER, "circuit breaker trips",
           line_key="MatcherBreakerTrips",
           prom="banjax_matcher_breaker_trips_total"),
    Family(COUNTER, "batches served by the CPU reference matcher (degraded)",
           line_key="MatcherCpuFallbackBatches",
           prom="banjax_matcher_cpu_fallback_batches_total"),
    Family(COUNTER, "matcher latency-budget breaches counted as breaker "
           "failures (validates the derived budget)",
           line_key="MatcherBudgetTrips",
           prom="banjax_matcher_budget_trips_total"),
    # ---- decision provenance / SLO / flight recorder ----
    Family(COUNTER, "decision insertions recorded by the provenance "
           "ledger (obs/provenance.py; /decisions/explain)",
           prom="banjax_decision_inserts_total",
           labels=("source", "decision")),
    Family(GAUGE, "SLO error-budget burn rate over the labeled window "
           "(1.0 = consuming the budget exactly at the sustainable rate)",
           prom="banjax_slo_burn_rate", labels=("slo", "window")),
    Family(GAUGE, "1 when the SLO burns >= 1.0 on every evaluated window "
           "(one-hot by slo label)",
           prom="banjax_slo_breached", labels=("slo",)),
    Family(COUNTER, "incident bundles captured by the flight recorder "
           "(obs/flightrec.py; /debug/incidents)",
           prom="banjax_flightrec_incidents_total"),
    # ---- adversarial scenario harness (banjax_tpu/scenarios/) ----
    Family(COUNTER, "scenario-harness runs completed in this process "
           "(bench --scenarios / the chaos soak)",
           prom="banjax_scenario_runs_total"),
    Family(COUNTER, "chaos failpoint episodes injected across scenario "
           "runs", prom="banjax_scenario_injected_episodes_total"),
    Family(COUNTER, "scenario invariant failures (accounting, leaked "
           "turns/pins, benign-SLO, bundle-per-episode)",
           prom="banjax_scenario_invariant_failures_total"),
    Family(GAUGE, "last run's end-to-end lines/sec for the labeled "
           "attack shape", prom="banjax_scenario_lines_per_sec",
           labels=("scenario",)),
    Family(GAUGE, "last run's (shed + drain-error) per admitted line "
           "for the labeled shape", prom="banjax_scenario_shed_ratio",
           labels=("scenario",)),
    Family(GAUGE, "last run's ban precision vs the generator oracle",
           prom="banjax_scenario_ban_precision", labels=("scenario",)),
    Family(GAUGE, "last run's ban recall vs the generator oracle",
           prom="banjax_scenario_ban_recall", labels=("scenario",)),
    Family(GAUGE, "last run's peak SLO burn rate across all SLOs and "
           "windows", prom="banjax_scenario_slo_burn_peak",
           labels=("scenario",)),
    # ---- traffic introspection plane (obs/sketch.py; /traffic/top) ----
    Family(COUNTER, "log lines folded into the device traffic sketch "
           "(count-min + HLL + rule pressure)",
           line_key="TrafficSketchLines",
           prom="banjax_traffic_sketch_lines_total"),
    Family(GAUGE, "estimated distinct client IPs (HyperLogLog registers, "
           "as of the last sketch pull)",
           line_key="TrafficDistinctIpsEst",
           prom="banjax_traffic_distinct_ips_estimate"),
    Family(GAUGE, "top heavy hitter's estimated share of sketched lines "
           "(count-min point estimate / lines folded)",
           line_key="TrafficHeavyHitterShare",
           prom="banjax_traffic_heavy_hitter_share"),
    Family(COUNTER, "bytes pulled device->host by periodic sketch "
           "refreshes (compact pulls, never per batch)",
           line_key="TrafficSketchPullBytes",
           prom="banjax_traffic_sketch_pull_bytes_total"),
    Family(GAUGE, "age of the newest sketch pull (s)",
           line_key="TrafficSketchPullAgeSeconds",
           prom="banjax_traffic_sketch_pull_age_seconds"),
    Family(COUNTER, "fired (line, rule) window events folded into the "
           "sketch, per rule — which rules absorb the flood",
           prom="banjax_traffic_rule_pressure", labels=("rule",)),
    # ---- multi-host decision fabric (banjax_tpu/fabric/) ----
    Family(GAUGE, "1 when the labeled fabric peer is alive in this "
           "node's membership view, 0 after it is declared dead",
           prom="banjax_fabric_peer_up", labels=("peer",)),
    Family(COUNTER, "lines forwarded to an owning peer and acked",
           line_key="FabricForwardedLines",
           prom="banjax_fabric_forwarded_lines_total"),
    Family(COUNTER, "lines received over the wire from a fabric peer",
           line_key="FabricReceivedLines",
           prom="banjax_fabric_received_lines_total"),
    Family(COUNTER, "lines owned locally and submitted in-process",
           line_key="FabricLocalLines",
           prom="banjax_fabric_local_lines_total"),
    Family(COUNTER, "lines with no alive owner — counted shed, never "
           "silently lost (the fabric half of admitted == processed + "
           "shed)", line_key="FabricShedLines",
           prom="banjax_fabric_shed_lines_total"),
    Family(COUNTER, "journal lines replayed to takeover successors "
           "after a peer death",
           line_key="FabricReplayedLines",
           prom="banjax_fabric_replayed_lines_total"),
    Family(COUNTER, "decisions produced to the Kafka command topic for "
           "fabric-wide replication",
           line_key="FabricReplicatedDecisions",
           prom="banjax_fabric_replicated_decisions_total"),
    Family(COUNTER, "replication produce attempts that failed (retried "
           "once, then counted and dropped — the local decision holds)",
           line_key="FabricReplicationErrors",
           prom="banjax_fabric_replication_errors_total"),
    Family(COUNTER, "replicated commands suppressed by the (origin, seq) "
           "deduper — own-origin echoes and duplicate inserts",
           line_key="FabricDuplicatesSuppressed",
           prom="banjax_fabric_duplicate_suppressed_total"),
    Family(COUNTER, "replicated peer decisions applied to the local "
           "dynamic lists",
           line_key="FabricReplicatedApplied",
           prom="banjax_fabric_replicated_applied_total"),
    Family(COUNTER, "range takeovers completed after a peer death",
           line_key="FabricTakeovers",
           prom="banjax_fabric_takeovers_total"),
    Family(HISTOGRAM, "takeover duration: peer declared dead -> journal "
           "fully replayed (s)",
           prom="banjax_fabric_takeover_duration_seconds"),
    Family(GAUGE, "gossip membership state of the labeled peer in this "
           "node's view (0=alive 1=suspect 2=dead 3=left)",
           prom="banjax_fabric_membership_state", labels=("peer",)),
    Family(COUNTER, "alive -> suspect transitions observed (direct + "
           "indirect probes all failed, or a suspicion digest arrived)",
           line_key="FabricMembershipSuspects",
           prom="banjax_fabric_membership_suspects_total"),
    Family(COUNTER, "suspicions that expired into confirmed-dead "
           "(drives mark_dead -> journal-replay takeover)",
           line_key="FabricMembershipConfirmedDead",
           prom="banjax_fabric_membership_confirmed_dead_total"),
    Family(COUNTER, "suspicions refuted by liveness evidence or an "
           "incarnation-bumped ALIVE from the suspect itself",
           line_key="FabricMembershipRefuted",
           prom="banjax_fabric_membership_refuted_total"),
    Family(COUNTER, "members joined or revived in this node's view "
           "(gossip join announce, rejoin, refute-after-dead)",
           line_key="FabricMembershipJoined",
           prom="banjax_fabric_membership_joined_total"),
    Family(COUNTER, "graceful LEFT departures observed (journal cleared "
           "without replay — the leaver drained first)",
           line_key="FabricMembershipLeft",
           prom="banjax_fabric_membership_left_total"),
    Family(COUNTER, "bytes of dedicated gossip probe traffic sent "
           "(digest piggybacks on data-path acks ride free)",
           line_key="FabricGossipBytes",
           prom="banjax_fabric_gossip_bytes_total"),
    Family(HISTOGRAM, "failure-detection latency: last liveness evidence "
           "for a member -> its death confirmed in this node's view (s)",
           prom="banjax_fabric_membership_detection_seconds"),
    # ---- fabric wire v2 transport (fabric/peer.py LinePipe) ----
    Family(COUNTER, "data-path frames sent to peers, by negotiated wire "
           "version (v2 binary / json fallback) and transport (tcp / shm)",
           prom="banjax_fabric_frames_total",
           labels=("version", "transport")),
    Family(COUNTER, "total data-path frames sent (all versions/transports "
           "— the 29s-line scalar of banjax_fabric_frames_total)",
           line_key="FabricFramesSent"),
    Family(COUNTER, "total data-path frame bytes sent to peers",
           line_key="FabricFrameBytes"),
    Family(HISTOGRAM, "size of each data-path frame sent (bytes) — how "
           "well send-side coalescing packs routed groups",
           prom="banjax_fabric_frame_bytes"),
    Family(COUNTER, "data-path acks received from peers (frames retired "
           "from the sliding window)",
           line_key="FabricAcksReceived",
           prom="banjax_fabric_acks_total"),
    Family(GAUGE, "frames currently in flight across all peer windows "
           "(bounded by fabric_inflight_frames per peer)",
           line_key="FabricInflightFrames",
           prom="banjax_fabric_inflight_frames"),
    Family(HISTOGRAM, "frame send -> ack round trip (s) through the "
           "pipelined window",
           prom="banjax_fabric_ack_rtt_seconds"),
    Family(GAUGE, "worst unread-byte fraction across this node's shm "
           "peer rings (0 when no ring transport is attached)",
           line_key="FabricRingOccupancy",
           prom="banjax_fabric_ring_occupancy"),
    Family(COUNTER, "takeover-replay lines skipped because their "
           "pre-death owner is still alive (already processed once — "
           "replaying would double-count rate-limit hits)",
           line_key="FabricReplaySkippedLines",
           prom="banjax_fabric_replay_skipped_lines_total"),
    # ---- pipeline scheduler ----
    Family(COUNTER, "lines+commands admitted into the pipeline",
           line_key="PipelineAdmittedLines",
           prom="banjax_pipeline_admitted_lines_total"),
    Family(COUNTER, "lines+commands fully drained",
           line_key="PipelineProcessedLines",
           prom="banjax_pipeline_processed_lines_total"),
    Family(COUNTER, "lines shed oldest-first under overload",
           line_key="PipelineShedLines",
           prom="banjax_pipeline_shed_lines_total"),
    Family(COUNTER, "lines lost to drain-stage failures (counted, never "
           "silent)", line_key="PipelineDrainErrorLines",
           prom="banjax_pipeline_drain_error_lines_total"),
    Family(COUNTER, "lines dropped stale at effector drain (10 s cutoff)",
           line_key="PipelineStaleDroppedLines",
           prom="banjax_pipeline_stale_dropped_lines_total"),
    Family(COUNTER, "pipeline batches drained",
           line_key="PipelineBatches", prom="banjax_pipeline_batches_total"),
    Family(COUNTER, "kafka command messages drained in admission order",
           line_key="PipelineCommandItems",
           prom="banjax_pipeline_command_items_total"),
    Family(COUNTER, "kafka command batches drained",
           line_key="PipelineCommandBatches",
           prom="banjax_pipeline_command_batches_total"),
    Family(COUNTER, "synthetic idle-probe failures",
           line_key="PipelineProbeFailures",
           prom="banjax_pipeline_probe_failures_total"),
    Family(GAUGE, "EWMA p99 of the device stage (ms) — feeds the derived "
           "breaker budget", line_key="PipelineDeviceP99Ms"),
    Family(GAUGE, "adaptive batch-size target (power-of-two bucket)",
           line_key="PipelineBatchTarget",
           prom="banjax_pipeline_batch_target"),
    Family(GAUGE, "command-batch take bound",
           line_key="PipelineCommandBatchTarget",
           prom="banjax_pipeline_command_batch_target"),
    Family(GAUGE, "EWMA encode-stage wall per batch (ms)",
           line_key="PipelineStageEncodeEwmaMs"),
    Family(GAUGE, "EWMA device-stage wall per batch (ms)",
           line_key="PipelineStageDeviceEwmaMs"),
    Family(GAUGE, "EWMA drain-stage wall per batch (ms)",
           line_key="PipelineStageDrainEwmaMs"),
    Family(GAUGE, "lines waiting in the admission buffer",
           line_key="PipelineBufferedLines",
           prom="banjax_pipeline_buffered_lines"),
    Family(GAUGE, "batches in flight across the stage ring",
           line_key="PipelineInflightBatches",
           prom="banjax_pipeline_inflight_batches"),
    Family(GAUGE, "configured in-flight ring size",
           line_key="PipelineRingSize", prom="banjax_pipeline_ring_size"),
    # ---- encode worker pool ----
    Family(GAUGE, "configured encode worker count (0 = single-thread)",
           line_key="EncodeWorkers", prom="banjax_encode_workers"),
    Family(COUNTER, "admission batches encoded via the sharded worker pool",
           line_key="EncodeShardedBatches",
           prom="banjax_encode_sharded_batches_total"),
    Family(GAUGE, "slowest encode shard's wall (ms) this interval",
           line_key="EncodeShardMsMax"),
    Family(GAUGE, "EWMA encode-pool utilization (1.0 = perfectly balanced)",
           line_key="EncodeWorkerUtilization",
           prom="banjax_encode_worker_utilization"),
    Family(GAUGE, "worst shard skew (max/mean shard wall) this interval",
           line_key="EncodeShardSkewMax",
           prom="banjax_encode_shard_skew_max"),
    Family(GAUGE, "EWMA per-worker busy fraction of fan-out wall (prom-"
           "only; per-shard-index label)",
           prom="banjax_encode_worker_busy_fraction", labels=("worker",)),
    # ---- kafka / http workers / health ----
    Family(COUNTER, "kafka record batches skipped (undecodable codec)",
           line_key="KafkaSkippedBatches",
           prom="banjax_kafka_skipped_batches_total"),
    Family(GAUGE, "live SO_REUSEPORT http worker processes",
           line_key="HttpWorkers", prom="banjax_http_workers"),
    Family(COUNTER, "http workers respawned after a crash",
           line_key="HttpWorkerRespawns",
           prom="banjax_http_worker_respawns_total"),
    Family(COUNTER, "failed-challenge states dropped by the shm limiter",
           line_key="HttpFcDropped", prom="banjax_http_fc_dropped_total"),
    Family(GAUGE, "aggregate health (0 healthy / 1 degraded / 2 failed)",
           line_key="HealthStatus", prom="banjax_health_status"),
    Family(GAUGE, "per-component health (0 healthy / 1 degraded / 2 "
           "failed); Health_<name> on the line",
           prom="banjax_health_component_status", labels=("component",)),
    # ---- challenge plane (banjax_tpu/challenge/) ----
    Family(COUNTER, "challenge cookies issued (stateless signed issuance, "
           "sha-inv + password)",
           line_key="ChallengeIssued", prom="banjax_challenge_issued_total"),
    Family(COUNTER, "sha-inv PoW cookie verifications by outcome and "
           "verifying path (cpu = reference oracle, device = batched "
           "sha256 kernel)",
           prom="banjax_challenge_verifications_total",
           labels=("result", "path")),
    Family(COUNTER, "sha-inv PoW cookie verifications, all outcomes and "
           "paths (line-only scalar of the labeled prom family)",
           line_key="ChallengeVerifications"),
    Family(GAUGE, "exact per-IP failed-challenge entries held by the "
           "bounded state (LRU + sketch spill/refill tiers excluded)",
           line_key="ChallengeFailureStateEntries",
           prom="banjax_challenge_failure_state_entries"),
    Family(COUNTER, "failed-challenge entries evicted from the bounded "
           "state under challenger pressure — bounded memory, never "
           "silent", line_key="ChallengeFailureEvictions",
           prom="banjax_challenge_failure_evictions_total"),
    # ---- compiled serving fast path (httpapi/fastpath.py) ----
    Family(COUNTER, "/auth_request responses served from the decision-"
           "table byte templates, by decision tier",
           prom="banjax_serve_fastpath_hits_total", labels=("tier",)),
    Family(COUNTER, "fast-path consultations that fell through to the "
           "decision chain, by reason",
           prom="banjax_serve_fastpath_misses_total", labels=("reason",)),
    Family(COUNTER, "fast-path hits, all tiers (line-only scalar of the "
           "labeled prom family)", line_key="ServeFastpathHits"),
    Family(COUNTER, "fast-path misses, all reasons (line-only scalar of "
           "the labeled prom family)", line_key="ServeFastpathMisses"),
    Family(COUNTER, "fast-path lookup faults (armed failpoint, torn "
           "seqlock read budget, unexpected error) — every one fell "
           "open to the chain", line_key="ServeFastpathFaults",
           prom="banjax_serve_fastpath_faults_total"),
    Family(GAUGE, "live entries in the shared decision table",
           line_key="ServeTableEntries",
           prom="banjax_serve_fastpath_table_entries"),
    Family(COUNTER, "inserts refused by a full decision table (the IP "
           "stays chain-served; live decisions are never evicted)",
           line_key="ServeTableDropped",
           prom="banjax_serve_fastpath_table_dropped_total"),
    Family(GAUGE, "session-id entries mirrored as a count (cookie-"
           "bearing requests defer to the chain while nonzero)",
           prom="banjax_serve_fastpath_table_session_entries"),
    Family(COUNTER, "dynamic-list -> decision-table mirror write "
           "failures (the table degrades to misses, never authority)",
           line_key="ServeMirrorErrors",
           prom="banjax_serve_fastpath_mirror_errors_total"),
    # ---- kernel-edge ban batching (effectors/ipset_netlink.py) ----
    Family(COUNTER, "coalesced netlink sendmsg batches acked clean by "
           "the kernel", line_key="IpsetBatchSends",
           prom="banjax_ipset_batch_sends_total"),
    Family(COUNTER, "ipset entries carried by those batches",
           line_key="IpsetBatchEntries",
           prom="banjax_ipset_batch_entries_total"),
    Family(COUNTER, "kernel-edge ban failures by path (netlink send/"
           "nack vs subprocess shim) — counted and routed, never "
           "raised into the ban path",
           prom="banjax_ipset_errors_total", labels=("path",)),
    Family(COUNTER, "kernel-edge ban failures, all paths (line-only "
           "scalar of the labeled prom family)", line_key="IpsetErrors"),
    Family(COUNTER, "entries re-routed from netlink to the per-entry "
           "subprocess fallback (lossless)", line_key="IpsetFallbacks",
           prom="banjax_ipset_fallback_total"),
    Family(COUNTER, "oldest queued bans shed by a full netlink queue "
           "(bounded memory, never blocks the ban path)",
           line_key="IpsetQueueShed", prom="banjax_ipset_queue_shed_total"),
    Family(GAUGE, "bans waiting in the netlink batch queue",
           prom="banjax_ipset_queue_depth"),
    # ---- fleet observability plane (obs/fleet.py) ----
    Family(GAUGE, "gossip-piggybacked health bits of the labeled fleet "
           "node (bit 1 slo_breached, bit 2 breaker open, bit 4 breaker "
           "half-open; 0 = healthy)",
           prom="banjax_fabric_peer_health", labels=("node",)),
    Family(GAUGE, "1 when the labeled peer could not be reached by the "
           "last /metrics?fleet=1 fan-out (its samples come from the "
           "stale cache or are absent — partial-but-honest view)",
           prom="banjax_fleet_peer_unreachable", labels=("instance",)),
    Family(GAUGE, "age (s) of the labeled peer's snapshot in the merged "
           "fleet exposition (near zero for a live pull)",
           prom="banjax_fleet_peer_staleness_seconds",
           labels=("instance",)),
    Family(HISTOGRAM, "tailer read -> effector commit end-to-end latency "
           "(s), by hop (local = owned by the tailing node, fabric = "
           "forwarded to its owner over the wire)",
           prom="banjax_e2e_latency_seconds", labels=("hop",)),
    # ---- histograms (prom-only) ----
    Family(HISTOGRAM, "device verification batch size (candidate "
           "solutions per sha256 kernel dispatch)",
           prom="banjax_challenge_verify_batch_size"),
    Family(HISTOGRAM, "end-to-end matcher batch latency (s)",
           prom="banjax_batch_latency_seconds"),
    Family(HISTOGRAM, "device stage (submit->collect) latency (s)",
           prom="banjax_device_stage_latency_seconds"),
    Family(HISTOGRAM, "per-stage pipeline span duration (s)",
           prom="banjax_stage_duration_seconds", labels=("stage",)),
]

# dynamic line-key prefixes (one key per registered component)
DYNAMIC_LINE_PREFIXES = ("Health_",)

LINE_KEYS: Dict[str, Family] = {
    f.line_key: f for f in FAMILIES if f.line_key
}
PROM_FAMILIES: Dict[str, Family] = {f.prom: f for f in FAMILIES if f.prom}


def is_declared_line_key(key: str) -> bool:
    if key in LINE_KEYS:
        return True
    return any(key.startswith(p) for p in DYNAMIC_LINE_PREFIXES)


class Histogram:
    """Thread-safe fixed-bucket histogram (Prometheus cumulative
    semantics at render time; counts stored per-bucket here)."""

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds: Tuple[float, ...] = LATENCY_BUCKETS_S):
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Tuple[Tuple[float, ...], List[int], float, int]:
        """(bounds, cumulative_counts incl. +Inf, sum, count)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return self.bounds, cum, s, total


class StageHistograms:
    """A labeled histogram set keyed by stage name, created lazily so
    only stages that actually run appear in the exposition."""

    __slots__ = ("_hists", "_lock")

    def __init__(self):
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def observe(self, stage: str, value_s: float) -> None:
        h = self._hists.get(stage)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(stage, Histogram())
        h.observe(value_s)

    def items(self):
        with self._lock:
            return sorted(self._hists.items())
