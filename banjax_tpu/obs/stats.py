"""Matcher runtime counters for production observability (VERDICT r1 weak
#8: a deployed instance must see the TPU subsystem's health, not just
bench.py).

MatcherStats is a thread-safe accumulator every Matcher carries; the
29-second metrics line (obs/metrics.py) snapshots it with ADDITIVE keys —
the reference's five keys keep their exact schema
(/root/reference/config.go:158-181).

Two consumers read these accumulators with different contracts:

  * `snapshot()` — the 29 s line's view: includes INTERVAL keys
    (lines/sec window, per-batch byte averages, eviction deltas) and
    resets them.  Read+reset is ONE atomic lock section (a scrape
    landing between a read and its reset used to lose or double-count
    the delta — tests/unit/test_observability.py hammers it now); the
    single-periodic-consumer assumption still applies to the VALUES
    (two competing periodic consumers would each see partial windows).
  * `peek()` — the Prometheus exposition's view (obs/exposition.py):
    monotone totals and point-in-time gauges only, never touching the
    window state, so scrapes at any cadence cannot steal the line's
    deltas.  Rate math belongs to the scraper.

Every key either view emits is declared in obs/registry.py — the
exposition-schema registry CI locks (test_exposition.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from banjax_tpu.obs.registry import Histogram, StageHistograms

_LATENCY_RING = 512  # recent batch latencies kept for the percentiles
_DEVICE_RING = 256   # recent device-stage latencies for the pipeline p99


def _r3(v):
    return None if v is None else round(v, 3)


class MatcherStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.lines_total = 0
        self.batches_total = 0
        self._latencies = [0.0] * _LATENCY_RING
        self._lat_n = 0
        self._window_lines = 0
        self._window_start = time.monotonic()
        self._last_evictions = 0
        # host<->device transfer accounting (the fusion-win witness: the
        # pipelined fused path must show the dense-bitmap re-upload gone)
        self.h2d_bytes_total = 0
        self.d2h_bytes_total = 0
        self._window_h2d = 0
        self._window_d2h = 0
        self._window_batches = 0
        # fixed-bucket batch-latency distribution for /metrics (registry
        # buckets; same observations as the p50/p99 ring)
        self.batch_latency_hist = Histogram()

    def record_batch(self, n_lines: int, elapsed_s: float) -> None:
        self.batch_latency_hist.observe(elapsed_s)
        with self._lock:
            self.lines_total += n_lines
            self.batches_total += 1
            self._latencies[self._lat_n % _LATENCY_RING] = elapsed_s
            self._lat_n += 1
            self._window_lines += n_lines
            self._window_batches += 1

    def note_xfer(self, h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
        """Bytes a device path moved across the host boundary (encoded
        input, dense bitmaps, sparse pulls).  Counted at the runner's choke
        points, not at every jnp.asarray — the point is comparability
        between the classic and fused paths, not a byte-perfect ledger."""
        with self._lock:
            self.h2d_bytes_total += int(h2d_bytes)
            self.d2h_bytes_total += int(d2h_bytes)
            self._window_h2d += int(h2d_bytes)
            self._window_d2h += int(d2h_bytes)

    def h2d_bytes_per_batch(self) -> float:
        """Lifetime average h2d bytes per recorded batch (bench/tests)."""
        with self._lock:
            return self.h2d_bytes_total / max(1, self.batches_total)

    def _percentiles_locked(self) -> Dict[str, object]:
        n = min(self._lat_n, _LATENCY_RING)
        lats = sorted(self._latencies[:n])
        return {
            "MatcherBatchLatencyP50Ms": (
                round(lats[n // 2] * 1e3, 3) if n else None
            ),
            "MatcherBatchLatencyP99Ms": (
                round(lats[min(n - 1, (n * 99) // 100)] * 1e3, 3) if n else None
            ),
        }

    @staticmethod
    def _derived(device_windows=None, matcher=None) -> Dict[str, object]:
        """Non-stats-owned keys (device windows, mesh, fused pipeline,
        breaker).  Reads foreign objects only — no stats lock, no resets
        — so both snapshot() and peek() share it."""
        out: Dict[str, object] = {}
        if device_windows is not None:
            out["DeviceWindowsOccupancy"] = device_windows.occupancy
            out["DeviceWindowsCapacity"] = device_windows.capacity
            # single read: an eviction landing between two reads must not
            # be dropped from the next interval's delta
            out["DeviceWindowsEvictions"] = device_windows.eviction_count
            out["DeviceWindowsGrows"] = getattr(device_windows, "grow_count", 0)
            # which slot-assignment path is live: the native C manager
            # (native/slotmgr.c) or the Python dict+LRU fallback/oracle
            out["SlotMgrNative"] = bool(
                getattr(device_windows, "slotmgr_native", False)
            )
            # shadowed IPs = all IPs with live counters (evicted included —
            # spill keeps them; see matcher/windows.py)
            out["DeviceWindowsShadowedIps"] = len(device_windows)
            # mega-state tiering: admission-gate and warm-tier telemetry.
            # Gate keys emit whenever the windows object carries them (a
            # zero refusal count under flood IS the signal the gate is
            # off); warm keys only when a tier is attached, so untiered
            # deployments keep their exact line schema.
            if hasattr(device_windows, "slot_refusals"):
                out["SlotRefusals"] = device_windows.slot_refusals
                out["SketchAdmissions"] = device_windows.sketch_admissions
                out["SketchAdmissionFpRate"] = round(
                    device_windows.sketch_admission_fp_rate, 4
                )
            if getattr(device_windows, "_warm", None) is not None:
                out["WarmTierSpills"] = device_windows.warm_spills
                out["WarmTierRefills"] = device_windows.warm_refills
                out["WarmTierDropped"] = device_windows.warm_dropped
                out["WarmTierOccupancy"] = device_windows.warm_occupancy
                out["WarmTierCapacity"] = device_windows.warm_capacity
        if matcher is not None:
            mm = getattr(matcher, "_mesh_matcher", None)
            if mm is not None:
                out["MeshFusedBatches"] = mm.fused_batches
                out["MeshFallbackBatches"] = mm.fallback_batches
                # sharded submit/drain latency (parallel/mesh.py): dispatch
                # wall time vs the per-shard d2h pull + line-order merge
                out["MeshSubmitMsEwma"] = _r3(
                    getattr(mm, "submit_ms_ewma", None)
                )
                out["MeshMergeMsEwma"] = _r3(
                    getattr(mm, "merge_ms_ewma", None)
                )
                shard_ms = getattr(mm, "last_shard_merge_ms", None) or []
                out["MeshShardMergeMsMax"] = _r3(
                    max(shard_ms) if shard_ms else None
                )
            if getattr(matcher, "_prefilter", None) is not None:
                out["PrefilterActive"] = True
            fw = getattr(matcher, "_fw_pipeline", None)
            if fw is not None:
                out["PipelineFusedBatches"] = fw.fused_batches
                out["PipelineFallbackBatches"] = fw.fallback_batches
                # two-phase (match-ahead, drain-commit) chunks driven by
                # the streaming pipeline, and its overflow fallbacks —
                # distinct from the sync-path counters above
                out["PipelinedFusedChunks"] = getattr(
                    matcher, "pipelined_fused_chunks", 0
                )
                out["PipelinedFusedFallbacks"] = getattr(
                    matcher, "pipelined_fused_fallbacks", 0
                )
                # depth-2 resolve-ahead drain: configured depth, and the
                # EWMA wall time of event decode + replay that ran while
                # the NEXT chunk's window program was already in flight —
                # the d2h latency the overlap is hiding
                out["DrainResolveAheadDepth"] = getattr(
                    matcher, "_drain_resolve_depth", 1
                )
                out["DrainResolveOverlapMs"] = _r3(
                    getattr(matcher, "drain_resolve_overlap_ms_ewma", None)
                )
                # single-kernel fused path: one program, one pull per
                # chunk — the resolve-pull elimination is visible as
                # SingleKernelChunks rising while DrainResolveOverlapMs
                # stays unset (nothing left for depth-2 to hide)
                if getattr(fw, "single_kernel", False):
                    out["SingleKernelChunks"] = fw.sk_chunks
                    out["SingleKernelFallbacks"] = fw.sk_fallbacks
                    out["SingleKernelD2hBytesPerBatch"] = round(
                        fw.sk_d2h_bytes_total / max(1, fw.sk_chunks), 1
                    )
                    # drain_resolve_depth configured but a no-op on this
                    # path (PR 7 silent-ignore made observable)
                    out["SingleKernelDepthIgnored"] = bool(
                        getattr(matcher, "single_kernel_depth_ignored",
                                False)
                    )
            # traffic introspection plane (obs/sketch.py): the sampled
            # summary — pull() self-throttles to its sampling interval,
            # so line snapshots and scrapes share one compact d2h
            ts = getattr(matcher, "traffic_sketch", None)
            if ts is not None:
                try:
                    s = ts.pull()
                    out["TrafficSketchLines"] = ts.lines_total
                    out["TrafficDistinctIpsEst"] = s[
                        "distinct_ips_estimate"
                    ]
                    out["TrafficHeavyHitterShare"] = s[
                        "heavy_hitter_share"
                    ]
                    out["TrafficSketchPullBytes"] = ts.pull_bytes_total
                    age = ts.pull_age_seconds()
                    out["TrafficSketchPullAgeSeconds"] = (
                        None if age is None else round(age, 3)
                    )
                except Exception:  # noqa: BLE001 — telemetry must not break metrics
                    pass
            # circuit breaker (resilience/breaker.py): the one place all
            # the ad-hoc fallback counters roll up for operators —
            # nonzero MatcherCpuFallbackBatches = batches served in
            # degraded (CPU reference) mode
            br = getattr(matcher, "breaker", None)
            if br is not None:
                out["MatcherBreakerState"] = br.state
                out["MatcherBreakerTrips"] = br.trip_count
                out["MatcherCpuFallbackBatches"] = getattr(
                    matcher, "fallback_batches", 0
                )
                # latency-budget breaches — distinct from device errors
                # in the trip accounting, so the ROADMAP's "derived
                # budget never validated" note has an observable counter
                out["MatcherBudgetTrips"] = getattr(
                    matcher, "budget_trips", 0
                )
        return out

    def snapshot(self, device_windows=None, matcher=None) -> Dict[str, object]:
        """Additive metrics-line keys; resets the interval windows.

        The foreign reads (_derived) happen OUTSIDE the stats lock; every
        read-then-reset of stats-owned window state — including the
        eviction-delta bookkeeping, which used to update `_last_evictions`
        unlocked — is one atomic section, so concurrent snapshot callers
        telescope cleanly instead of double-counting a delta."""
        derived = self._derived(device_windows, matcher)
        evictions = derived.get("DeviceWindowsEvictions")
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._window_start, 1e-9)
            lps = self._window_lines / dt
            self._window_lines = 0
            self._window_start = now
            out: Dict[str, object] = {
                "MatcherLinesTotal": self.lines_total,
                "MatcherBatchesTotal": self.batches_total,
                "MatcherLinesPerSec": round(lps, 1),
                **self._percentiles_locked(),
                "MatcherH2dBytesTotal": self.h2d_bytes_total,
                "MatcherD2hBytesTotal": self.d2h_bytes_total,
                # per-batch averages over THIS reporting interval: the
                # operator-visible witness that fused+pipelined killed the
                # ~16 MB/batch dense re-upload
                "MatcherH2dBytesPerBatch": round(
                    self._window_h2d / max(1, self._window_batches), 1
                ),
                "MatcherD2hBytesPerBatch": round(
                    self._window_d2h / max(1, self._window_batches), 1
                ),
            }
            self._window_h2d = 0
            self._window_d2h = 0
            self._window_batches = 0
            if evictions is not None:
                # churn rate: evictions in THIS reporting interval —
                # degraded (spill/restore) mode visible per 29 s line, not
                # only as a lifetime total.  Interval deltas assume a
                # single periodic consumer (the metrics loop); /metrics
                # scrapes use peek() and never touch this.
                out["DeviceWindowsEvictionsPerInterval"] = (
                    evictions - self._last_evictions
                )
                self._last_evictions = evictions
        out.update(derived)
        return out

    def peek(self, device_windows=None, matcher=None) -> Dict[str, object]:
        """Non-destructive view for the Prometheus exposition: totals,
        percentiles and derived gauges only — no interval keys, no
        resets.  Safe at any scrape cadence alongside the 29 s line."""
        derived = self._derived(device_windows, matcher)
        with self._lock:
            out: Dict[str, object] = {
                "MatcherLinesTotal": self.lines_total,
                "MatcherBatchesTotal": self.batches_total,
                **self._percentiles_locked(),
                "MatcherH2dBytesTotal": self.h2d_bytes_total,
                "MatcherD2hBytesTotal": self.d2h_bytes_total,
            }
        out.update(derived)
        return out


class PipelineStats:
    """Thread-safe counters for the streaming pipeline scheduler
    (banjax_tpu/pipeline/scheduler.py).

    The accounting invariant the fault suite asserts: after a flush,
    admitted_lines == processed_lines + shed_lines + drain_error_lines —
    every admitted item is either processed (a result was produced for
    it, old_line included) or counted as shed; nothing is silent.  Kafka
    command messages routed through the admission buffer count in the
    SAME admitted/processed/shed totals (the invariant spans both
    producers); command_items/command_batches break the command share
    out for operators.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.admitted_lines = 0
        self.processed_lines = 0
        self.shed_lines = 0         # oldest-first overload shed
        self.drain_error_lines = 0  # drain-stage failures, counted as shed
        self.stale_dropped_lines = 0  # aged past cutoff inside the pipeline
        self.batches = 0
        self.fallback_batches = 0   # drained generically via consume_lines
        self.command_items = 0      # kafka commands drained in admission order
        self.command_batches = 0
        self.probe_ok = 0
        self.probe_failed = 0
        self._device_ring = [0.0] * _DEVICE_RING
        self._device_n = 0
        self._device_p99_ewma: Optional[float] = None
        # sharded encode-worker pool (scheduler._begin_state): interval
        # max of the slowest shard's wall time (the merge barrier waits
        # on it), EWMA utilization = sum(shard wall) / (workers * fan-out
        # wall) — 1.0 means perfectly balanced shards, low values mean
        # the fan-out is overhead-bound and encode_workers is too high
        self.encode_sharded_batches = 0
        self._encode_shard_ms_max = 0.0  # reset each snapshot
        self._encode_util_ewma: Optional[float] = None
        # per-shard-index busy fraction (EWMA of shard wall / fan-out
        # wall) and max/mean skew — the real multi-core imbalance signal
        # the scalar utilization EWMA hides (ROADMAP PR 4 follow-up);
        # skew: interval max for the 29 s line, EWMA for /metrics
        self._worker_busy_ewma: List[float] = []
        self._shard_skew_max = 0.0       # reset each snapshot
        self._shard_skew_ewma: Optional[float] = None
        # fixed-bucket distributions for /metrics (obs/registry.py)
        self.device_latency_hist = Histogram()
        self.stage_hists = StageHistograms()
        # tailer read -> effector commit, keyed by hop (local lines vs
        # fabric-forwarded ones) — banjax_e2e_latency_seconds{hop}
        self.e2e_hists = StageHistograms()

    def note_admitted(self, n: int) -> None:
        with self._lock:
            self.admitted_lines += n

    def note_processed(self, n: int) -> None:
        with self._lock:
            self.processed_lines += n

    def note_shed(self, n: int) -> None:
        with self._lock:
            self.shed_lines += n

    def note_drain_error(self, n: int) -> None:
        with self._lock:
            self.drain_error_lines += n

    def note_stale(self, n: int) -> None:
        with self._lock:
            self.stale_dropped_lines += n

    def note_batch(self, fallback: bool) -> None:
        with self._lock:
            self.batches += 1
            if fallback:
                self.fallback_batches += 1

    def note_commands(self, n: int) -> None:
        with self._lock:
            self.command_items += n
            self.command_batches += 1

    def note_encode_shards(self, shard_ms: List[float],
                           wall_ms: float) -> None:
        """One sharded encode fan-out's timing (scheduler._begin_state):
        per-shard wall times plus the fan-out's total wall."""
        n_shards = len(shard_ms)
        if not n_shards:
            return
        wall = max(wall_ms, 1e-9)
        mean = sum(shard_ms) / n_shards
        skew = (max(shard_ms) / mean) if mean > 0 else 1.0
        util = min(1.0, max(0.0, sum(shard_ms) / (wall * n_shards)))
        with self._lock:
            self.encode_sharded_batches += 1
            if max(shard_ms) > self._encode_shard_ms_max:
                self._encode_shard_ms_max = max(shard_ms)
            self._encode_util_ewma = (
                util if self._encode_util_ewma is None
                else self._encode_util_ewma
                + 0.3 * (util - self._encode_util_ewma)
            )
            if skew > self._shard_skew_max:
                self._shard_skew_max = skew
            self._shard_skew_ewma = (
                skew if self._shard_skew_ewma is None
                else self._shard_skew_ewma
                + 0.3 * (skew - self._shard_skew_ewma)
            )
            while len(self._worker_busy_ewma) < n_shards:
                self._worker_busy_ewma.append(0.0)
            for k, ms in enumerate(shard_ms):
                frac = min(1.0, ms / wall)
                prev = self._worker_busy_ewma[k]
                self._worker_busy_ewma[k] = (
                    frac if self.encode_sharded_batches == 1
                    else prev + 0.3 * (frac - prev)
                )

    def worker_busy_fractions(self) -> List[float]:
        """Per-shard-index EWMA busy fraction of the fan-out wall —
        /metrics gauge banjax_encode_worker_busy_fraction{worker=k}."""
        with self._lock:
            return [round(v, 3) for v in self._worker_busy_ewma]

    def note_probe(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.probe_ok += 1
            else:
                self.probe_failed += 1

    def observe_device(self, elapsed_s: float) -> None:
        """One device-stage (submit→collect) wall time; feeds the p99 the
        breaker-budget satellite derives `matcher_latency_budget_ms` from."""
        self.device_latency_hist.observe(elapsed_s)
        with self._lock:
            self._device_ring[self._device_n % _DEVICE_RING] = elapsed_s
            self._device_n += 1
            n = min(self._device_n, _DEVICE_RING)
            lats = sorted(self._device_ring[:n])
            p99 = lats[min(n - 1, (n * 99) // 100)]
            self._device_p99_ewma = (
                p99 if self._device_p99_ewma is None
                else self._device_p99_ewma + 0.2 * (p99 - self._device_p99_ewma)
            )

    def observe_stages(self, stage_ms: Dict[str, float]) -> None:
        """Per-stage wall times for one drained batch → the labeled
        banjax_stage_duration_seconds histogram (scheduler drain loop)."""
        for stage, ms in stage_ms.items():
            self.stage_hists.observe(stage, ms / 1e3)

    def observe_e2e(self, hop: str, seconds: float) -> None:
        """One batch's oldest tailer-read stamp -> effector commit
        (banjax_e2e_latency_seconds{hop}); recorded at drain completion
        by the scheduler when the batch carried any read stamp."""
        self.e2e_hists.observe(hop, max(0.0, seconds))

    def device_p99_s(self) -> Optional[float]:
        with self._lock:
            return self._device_p99_ewma

    def suggested_latency_budget_s(self) -> float:
        """Derived breaker budget: 3x the EWMA device p99, floored at
        50 ms (ROADMAP breaker-tuning item).  0.0 until a p99 exists —
        the breaker treats 0 as 'no budget', same as the unset config."""
        with self._lock:
            if self._device_p99_ewma is None:
                return 0.0
            return max(0.05, 3.0 * self._device_p99_ewma)

    def _totals_locked(self) -> Dict[str, object]:
        return {
            "EncodeShardedBatches": self.encode_sharded_batches,
            "EncodeWorkerUtilization": (
                None if self._encode_util_ewma is None
                else round(self._encode_util_ewma, 3)
            ),
            "PipelineAdmittedLines": self.admitted_lines,
            "PipelineProcessedLines": self.processed_lines,
            "PipelineShedLines": self.shed_lines,
            "PipelineDrainErrorLines": self.drain_error_lines,
            "PipelineStaleDroppedLines": self.stale_dropped_lines,
            "PipelineBatches": self.batches,
            "PipelineFallbackBatches": self.fallback_batches,
            "PipelineCommandItems": self.command_items,
            "PipelineCommandBatches": self.command_batches,
            "PipelineProbeFailures": self.probe_failed,
            "PipelineDeviceP99Ms": (
                None if self._device_p99_ewma is None
                else round(self._device_p99_ewma * 1e3, 3)
            ),
        }

    def snapshot(self) -> Dict[str, object]:
        """29 s line view: totals plus the interval maxima, which reset
        here (read+reset is one atomic section)."""
        with self._lock:
            shard_max = self._encode_shard_ms_max
            self._encode_shard_ms_max = 0.0  # interval max, like a gauge
            skew_max = self._shard_skew_max
            self._shard_skew_max = 0.0
            out = self._totals_locked()
            out["EncodeShardMsMax"] = round(shard_max, 3)
            out["EncodeShardSkewMax"] = round(skew_max, 3)
            return out

    def peek(self) -> Dict[str, object]:
        """Prometheus view: totals and EWMAs only, no interval resets.
        Shard skew is the EWMA here (an interval max is meaningless
        across uncoordinated scrapers)."""
        with self._lock:
            out = self._totals_locked()
            out["EncodeShardSkewMax"] = (
                None if self._shard_skew_ewma is None
                else round(self._shard_skew_ewma, 3)
            )
            return out
