"""Matcher runtime counters for production observability (VERDICT r1 weak
#8: a deployed instance must see the TPU subsystem's health, not just
bench.py).

MatcherStats is a thread-safe accumulator every Matcher carries; the
29-second metrics line (obs/metrics.py) snapshots it with ADDITIVE keys —
the reference's five keys keep their exact schema
(/root/reference/config.go:158-181)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

_LATENCY_RING = 512  # recent batch latencies kept for the percentiles
_DEVICE_RING = 256   # recent device-stage latencies for the pipeline p99


class MatcherStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.lines_total = 0
        self.batches_total = 0
        self._latencies = [0.0] * _LATENCY_RING
        self._lat_n = 0
        self._window_lines = 0
        self._window_start = time.monotonic()
        self._last_evictions = 0

    def record_batch(self, n_lines: int, elapsed_s: float) -> None:
        with self._lock:
            self.lines_total += n_lines
            self.batches_total += 1
            self._latencies[self._lat_n % _LATENCY_RING] = elapsed_s
            self._lat_n += 1
            self._window_lines += n_lines

    def snapshot(self, device_windows=None, matcher=None) -> Dict[str, object]:
        """Additive metrics-line keys; resets the lines/sec window."""
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._window_start, 1e-9)
            lps = self._window_lines / dt
            self._window_lines = 0
            self._window_start = now
            n = min(self._lat_n, _LATENCY_RING)
            lats = sorted(self._latencies[:n])
            out: Dict[str, object] = {
                "MatcherLinesTotal": self.lines_total,
                "MatcherBatchesTotal": self.batches_total,
                "MatcherLinesPerSec": round(lps, 1),
                "MatcherBatchLatencyP50Ms": (
                    round(lats[n // 2] * 1e3, 3) if n else None
                ),
                "MatcherBatchLatencyP99Ms": (
                    round(lats[min(n - 1, (n * 99) // 100)] * 1e3, 3) if n else None
                ),
            }
        if device_windows is not None:
            out["DeviceWindowsOccupancy"] = device_windows.occupancy
            out["DeviceWindowsCapacity"] = device_windows.capacity
            # single read: an eviction landing between two reads must not be
            # dropped from the next interval's delta
            evictions = device_windows.eviction_count
            out["DeviceWindowsEvictions"] = evictions
            # churn rate: evictions in THIS reporting interval — degraded
            # (spill/restore) mode is visible per 29 s line, not only as a
            # lifetime total.  Interval deltas assume a single periodic
            # consumer (the metrics loop); ad-hoc snapshot() callers steal
            # the delta from the next metrics line.
            out["DeviceWindowsEvictionsPerInterval"] = (
                evictions - self._last_evictions
            )
            self._last_evictions = evictions
            out["DeviceWindowsGrows"] = getattr(device_windows, "grow_count", 0)
            # shadowed IPs = all IPs with live counters (evicted included —
            # spill keeps them; see matcher/windows.py)
            out["DeviceWindowsShadowedIps"] = len(device_windows)
        if matcher is not None:
            mm = getattr(matcher, "_mesh_matcher", None)
            if mm is not None:
                out["MeshFusedBatches"] = mm.fused_batches
                out["MeshFallbackBatches"] = mm.fallback_batches
            if getattr(matcher, "_prefilter", None) is not None:
                out["PrefilterActive"] = True
            fw = getattr(matcher, "_fw_pipeline", None)
            if fw is not None:
                out["PipelineFusedBatches"] = fw.fused_batches
                out["PipelineFallbackBatches"] = fw.fallback_batches
            # circuit breaker (resilience/breaker.py): the one place all
            # the ad-hoc fallback counters roll up for operators —
            # nonzero MatcherCpuFallbackBatches = batches served in
            # degraded (CPU reference) mode
            br = getattr(matcher, "breaker", None)
            if br is not None:
                out["MatcherBreakerState"] = br.state
                out["MatcherBreakerTrips"] = br.trip_count
                out["MatcherCpuFallbackBatches"] = getattr(
                    matcher, "fallback_batches", 0
                )
        return out


class PipelineStats:
    """Thread-safe counters for the streaming pipeline scheduler
    (banjax_tpu/pipeline/scheduler.py).

    The accounting invariant the fault suite asserts: after a flush,
    admitted_lines == processed_lines + shed_lines + drain_error_lines —
    every admitted line is either processed (a result was produced for
    it, old_line included) or counted as shed; nothing is silent.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.admitted_lines = 0
        self.processed_lines = 0
        self.shed_lines = 0         # oldest-first overload shed
        self.drain_error_lines = 0  # drain-stage failures, counted as shed
        self.stale_dropped_lines = 0  # aged past cutoff inside the pipeline
        self.batches = 0
        self.fallback_batches = 0   # drained generically via consume_lines
        self.probe_ok = 0
        self.probe_failed = 0
        self._device_ring = [0.0] * _DEVICE_RING
        self._device_n = 0
        self._device_p99_ewma: Optional[float] = None

    def note_admitted(self, n: int) -> None:
        with self._lock:
            self.admitted_lines += n

    def note_processed(self, n: int) -> None:
        with self._lock:
            self.processed_lines += n

    def note_shed(self, n: int) -> None:
        with self._lock:
            self.shed_lines += n

    def note_drain_error(self, n: int) -> None:
        with self._lock:
            self.drain_error_lines += n

    def note_stale(self, n: int) -> None:
        with self._lock:
            self.stale_dropped_lines += n

    def note_batch(self, fallback: bool) -> None:
        with self._lock:
            self.batches += 1
            if fallback:
                self.fallback_batches += 1

    def note_probe(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.probe_ok += 1
            else:
                self.probe_failed += 1

    def observe_device(self, elapsed_s: float) -> None:
        """One device-stage (submit→collect) wall time; feeds the p99 the
        breaker-budget satellite derives `matcher_latency_budget_ms` from."""
        with self._lock:
            self._device_ring[self._device_n % _DEVICE_RING] = elapsed_s
            self._device_n += 1
            n = min(self._device_n, _DEVICE_RING)
            lats = sorted(self._device_ring[:n])
            p99 = lats[min(n - 1, (n * 99) // 100)]
            self._device_p99_ewma = (
                p99 if self._device_p99_ewma is None
                else self._device_p99_ewma + 0.2 * (p99 - self._device_p99_ewma)
            )

    def device_p99_s(self) -> Optional[float]:
        with self._lock:
            return self._device_p99_ewma

    def suggested_latency_budget_s(self) -> float:
        """Derived breaker budget: 3x the EWMA device p99, floored at
        50 ms (ROADMAP breaker-tuning item).  0.0 until a p99 exists —
        the breaker treats 0 as 'no budget', same as the unset config."""
        with self._lock:
            if self._device_p99_ewma is None:
                return 0.0
            return max(0.05, 3.0 * self._device_p99_ewma)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            p99 = self._device_p99_ewma
            return {
                "PipelineAdmittedLines": self.admitted_lines,
                "PipelineProcessedLines": self.processed_lines,
                "PipelineShedLines": self.shed_lines,
                "PipelineDrainErrorLines": self.drain_error_lines,
                "PipelineStaleDroppedLines": self.stale_dropped_lines,
                "PipelineBatches": self.batches,
                "PipelineFallbackBatches": self.fallback_batches,
                "PipelineProbeFailures": self.probe_failed,
                "PipelineDeviceP99Ms": (
                    None if p99 is None else round(p99 * 1e3, 3)
                ),
            }
