"""Matcher runtime counters for production observability (VERDICT r1 weak
#8: a deployed instance must see the TPU subsystem's health, not just
bench.py).

MatcherStats is a thread-safe accumulator every Matcher carries; the
29-second metrics line (obs/metrics.py) snapshots it with ADDITIVE keys —
the reference's five keys keep their exact schema
(/root/reference/config.go:158-181)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

_LATENCY_RING = 512  # recent batch latencies kept for the percentiles


class MatcherStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.lines_total = 0
        self.batches_total = 0
        self._latencies = [0.0] * _LATENCY_RING
        self._lat_n = 0
        self._window_lines = 0
        self._window_start = time.monotonic()
        self._last_evictions = 0

    def record_batch(self, n_lines: int, elapsed_s: float) -> None:
        with self._lock:
            self.lines_total += n_lines
            self.batches_total += 1
            self._latencies[self._lat_n % _LATENCY_RING] = elapsed_s
            self._lat_n += 1
            self._window_lines += n_lines

    def snapshot(self, device_windows=None, matcher=None) -> Dict[str, object]:
        """Additive metrics-line keys; resets the lines/sec window."""
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._window_start, 1e-9)
            lps = self._window_lines / dt
            self._window_lines = 0
            self._window_start = now
            n = min(self._lat_n, _LATENCY_RING)
            lats = sorted(self._latencies[:n])
            out: Dict[str, object] = {
                "MatcherLinesTotal": self.lines_total,
                "MatcherBatchesTotal": self.batches_total,
                "MatcherLinesPerSec": round(lps, 1),
                "MatcherBatchLatencyP50Ms": (
                    round(lats[n // 2] * 1e3, 3) if n else None
                ),
                "MatcherBatchLatencyP99Ms": (
                    round(lats[min(n - 1, (n * 99) // 100)] * 1e3, 3) if n else None
                ),
            }
        if device_windows is not None:
            out["DeviceWindowsOccupancy"] = device_windows.occupancy
            out["DeviceWindowsCapacity"] = device_windows.capacity
            # single read: an eviction landing between two reads must not be
            # dropped from the next interval's delta
            evictions = device_windows.eviction_count
            out["DeviceWindowsEvictions"] = evictions
            # churn rate: evictions in THIS reporting interval — degraded
            # (spill/restore) mode is visible per 29 s line, not only as a
            # lifetime total.  Interval deltas assume a single periodic
            # consumer (the metrics loop); ad-hoc snapshot() callers steal
            # the delta from the next metrics line.
            out["DeviceWindowsEvictionsPerInterval"] = (
                evictions - self._last_evictions
            )
            self._last_evictions = evictions
            out["DeviceWindowsGrows"] = getattr(device_windows, "grow_count", 0)
            # shadowed IPs = all IPs with live counters (evicted included —
            # spill keeps them; see matcher/windows.py)
            out["DeviceWindowsShadowedIps"] = len(device_windows)
        if matcher is not None:
            mm = getattr(matcher, "_mesh_matcher", None)
            if mm is not None:
                out["MeshFusedBatches"] = mm.fused_batches
                out["MeshFallbackBatches"] = mm.fallback_batches
            if getattr(matcher, "_prefilter", None) is not None:
                out["PrefilterActive"] = True
            fw = getattr(matcher, "_fw_pipeline", None)
            if fw is not None:
                out["PipelineFusedBatches"] = fw.fused_batches
                out["PipelineFallbackBatches"] = fw.fallback_batches
            # circuit breaker (resilience/breaker.py): the one place all
            # the ad-hoc fallback counters roll up for operators —
            # nonzero MatcherCpuFallbackBatches = batches served in
            # degraded (CPU reference) mode
            br = getattr(matcher, "breaker", None)
            if br is not None:
                out["MatcherBreakerState"] = br.state
                out["MatcherBreakerTrips"] = br.trip_count
                out["MatcherCpuFallbackBatches"] = getattr(
                    matcher, "fallback_batches", 0
                )
        return out
