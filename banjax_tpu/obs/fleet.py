"""Fleet observability plane: the cluster as one observable system.

PR 19's fabric made N banjax nodes act as one *decision* plane — a line
tailed on shard A can ban an IP owned by shard B — but observability
stayed per-process: B's ledger said "fabric told me", A's trace ring
showed a forwarded chunk vanishing over the wire, and an operator
debugging a cross-shard ban had to correlate two /metrics scrapes and
two trace rings by wall clock.  This module closes that gap with four
cooperating layers (ISSUE 20):

  * **Cross-host trace propagation.**  The forwarding router allocates
    an origin trace id per admission chunk (fabric/router.py); the wire
    carries ``(origin_node_id, origin_trace_id)`` per contiguous run of
    lines (fabric/wire.py T_LINES_V2 origin section, JSON ``origin``
    key); the owner's chunk handler opens a linked ``fabric.
    remote-drain`` span under the *origin* trace id and feeds the
    ``OriginIndex`` here, which the provenance ledger consults at
    record time (obs/provenance.py ``set_origin_resolver``) — so
    ``/decisions/explain?ip=`` on the owner answers with the origin
    node and the trace id of the admission batch tailed over there.

  * **Federated metrics.**  ``FleetScraper`` fans a T_STATS
    ``{"metrics": true}`` pull out to every ALIVE member, and
    ``merge_expositions`` renders ONE strictly-parseable text payload:
    counters summed across instances, gauges re-emitted per instance
    with an added ``instance`` label, histograms merged on the union
    of bucket bounds with each instance's cumulative counts carried
    forward.  A dead peer mid-scrape degrades to its cached snapshot
    (or drops out entirely) and is flagged via
    ``banjax_fleet_peer_unreachable`` / ``…_staleness_seconds`` —
    partial but honest, never a 500.

  * **Cluster SLO + fleet health.**  ``fleet_collect`` turns the last
    merged scrape into the counter dict obs/slo.py burns over (a
    fleet-mode SloEngine via its ``collect_fn`` seam), and
    ``compute_health_bits`` packs (slo_breached, breaker open/half-
    open) into the compact health word the SWIM digests piggyback
    (fabric/membership.py), surfaced as ``banjax_fabric_peer_health``.

  * **Cluster incident capture.**  ``local_capture_files`` builds the
    per-node snapshot a peer returns for T_FLIGHTREC, and
    ``capture_fleet`` fans the request out to ALIVE members so the
    origin node's incident bundle grows a ``peers/<node_id>/`` tree
    (obs/flightrec.py ``fleet_capture_fn``).

Failpoints: ``obs.fleet.pull`` (per peer, metrics fan-out) and
``obs.fleet.capture`` (per peer, incident fan-out) — both degrade to
the partial view, proven by tests/faults.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from banjax_tpu.obs import registry
from banjax_tpu.obs.exposition import (
    COUNTER,
    HISTOGRAM,
    _esc,
    parse_text_format,
)
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import HALF_OPEN, OPEN

# ---------------------------------------------------------------------------
# health bits (gossip piggyback encoding — see fabric/stats.py peer_health)
# ---------------------------------------------------------------------------

HEALTH_SLO_BREACHED = 1      # any SLO currently breached
HEALTH_BREAKER_OPEN = 2      # matcher breaker OPEN
HEALTH_BREAKER_HALF_OPEN = 4  # matcher breaker HALF_OPEN


def compute_health_bits(slo=None, matcher=None) -> int:
    """Pack this node's health into the compact word SWIM digests carry.

    Reads are non-destructive and crash-proof: a health provider bug
    must never take down a gossip probe."""
    bits = 0
    if slo is not None:
        try:
            if any(slo.breached().values()):
                bits |= HEALTH_SLO_BREACHED
        except Exception:  # noqa: BLE001 — gossip must not die on a telemetry bug
            pass
    if matcher is not None:
        try:
            breaker = getattr(matcher, "breaker", None)
            state = getattr(breaker, "state", None)
            if state == OPEN:
                bits |= HEALTH_BREAKER_OPEN
            elif state == HALF_OPEN:
                bits |= HEALTH_BREAKER_HALF_OPEN
        except Exception:  # noqa: BLE001
            pass
    return bits


# ---------------------------------------------------------------------------
# origin index: ip -> (origin_node, origin_trace) for forwarded lines
# ---------------------------------------------------------------------------

class OriginIndex:
    """Bounded LRU mapping a forwarded line's IP to the node that tailed
    it and the trace id its router allocated at admission.

    Fed by the owner-side chunk handlers (fabric/service.py,
    fabric/worker.py) per line per origin run; consulted by the
    provenance ledger at record time (obs/provenance.py).  Bounded so a
    spray of distinct spoofed sources cannot grow it without limit —
    the oldest attribution is the right one to lose."""

    def __init__(self, max_entries: int = 8192,
                 clock: Callable[[], float] = time.monotonic):
        self.max_entries = max(16, int(max_entries))
        self._clock = clock
        self._lock = threading.Lock()
        # insertion-ordered dict as LRU: move_to_end on note, popitem
        # oldest on overflow
        self._map: Dict[str, Tuple[str, int, float]] = {}

    def note(self, ip: str, origin_node: str, origin_trace: int) -> None:
        if not origin_node:
            return
        with self._lock:
            m = self._map
            if ip in m:
                del m[ip]
            m[ip] = (origin_node, int(origin_trace), self._clock())
            while len(m) > self.max_entries:
                m.pop(next(iter(m)))

    def resolve(self, ip: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            ent = self._map.get(ip)
        if ent is None:
            return None
        return ent[0], ent[1]

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


# process-wide index, installed into provenance by the fabric wiring
_origin_index = OriginIndex()


def get_origin_index() -> OriginIndex:
    return _origin_index


# ---------------------------------------------------------------------------
# exposition merge (federated /metrics?fleet=1)
# ---------------------------------------------------------------------------

def _fmt_merged(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v) == int(v):
        return str(int(v))
    return repr(float(v))


def _label_str(pairs: Dict[str, str]) -> str:
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{_esc(v)}"' for k, v in sorted(pairs.items())
    )
    return "{" + inner + "}"


def _labelset_key(labels: Dict[str, str],
                  drop: Tuple[str, ...] = ()) -> tuple:
    return tuple(sorted(
        (k, v) for k, v in labels.items() if k not in drop
    ))


def merge_expositions(texts: Dict[str, str]) -> str:
    """Merge per-instance Prometheus texts into one strict exposition.

    ``texts`` maps instance id (node id) -> that node's full /metrics
    payload.  Semantics, per family kind:

      * counter — summed across instances per label set (a fleet total;
        no ``instance`` label, so existing single-node alert rules keep
        firing on the cluster aggregate)
      * gauge (and summary/untyped) — point-in-time state is NOT
        summable; each sample re-emitted with an added ``instance``
        label
      * histogram — merged per label set on the UNION of bucket bounds;
        an instance's cumulative count at a bound it never declared is
        carried forward from its largest declared bound below it
        (conservative undercount, preserves monotonicity); +Inf and
        _count are exact sums

    Output parses under obs/exposition.parse_text_format — the strict
    round-trip is a test invariant, not a hope."""
    parsed: Dict[str, Dict[str, dict]] = {
        inst: parse_text_format(text) for inst, text in sorted(texts.items())
    }
    # family -> (type, help) from the first instance declaring it
    fam_meta: Dict[str, Tuple[str, str]] = {}
    for inst in sorted(parsed):
        for fam, ent in parsed[inst].items():
            fam_meta.setdefault(fam, (ent["type"], ent["help"]))

    lines: List[str] = []
    for fam in sorted(fam_meta):
        kind, help_text = fam_meta[fam]
        declared = False

        def head():
            nonlocal declared
            if not declared:
                lines.append(f"# HELP {fam} {help_text}")
                lines.append(f"# TYPE {fam} {kind}")
                declared = True

        if kind == COUNTER:
            sums: Dict[tuple, float] = {}
            for inst in sorted(parsed):
                ent = parsed[inst].get(fam)
                if not ent:
                    continue
                for name, labels, value in ent["samples"]:
                    key = _labelset_key(labels)
                    sums[key] = sums.get(key, 0.0) + value
            for key in sorted(sums):
                head()
                lines.append(
                    f"{fam}{_label_str(dict(key))} {_fmt_merged(sums[key])}"
                )
        elif kind == HISTOGRAM:
            # labelset (sans le/instance) -> per-instance bucket maps
            merged: Dict[tuple, dict] = {}
            for inst in sorted(parsed):
                ent = parsed[inst].get(fam)
                if not ent:
                    continue
                per: Dict[tuple, dict] = {}
                for name, labels, value in ent["samples"]:
                    key = _labelset_key(labels, drop=("le",))
                    slot = per.setdefault(
                        key, {"buckets": {}, "sum": 0.0, "count": 0.0}
                    )
                    if name.endswith("_bucket"):
                        le = labels["le"]
                        bound = math.inf if le == "+Inf" else float(le)
                        slot["buckets"][bound] = value
                    elif name.endswith("_sum"):
                        slot["sum"] = value
                    elif name.endswith("_count"):
                        slot["count"] = value
                for key, slot in per.items():
                    merged.setdefault(key, {"series": [], "sum": 0.0,
                                            "count": 0.0})
                    merged[key]["series"].append(slot["buckets"])
                    merged[key]["sum"] += slot["sum"]
                    merged[key]["count"] += slot["count"]
            for key in sorted(merged, key=str):
                slot = merged[key]
                bounds = sorted({b for s in slot["series"] for b in s})
                if not bounds or bounds[-1] != math.inf:
                    bounds.append(math.inf)
                base = dict(key)
                head()
                for b in bounds:
                    total = 0.0
                    for series in slot["series"]:
                        # carry the instance's cumulative count forward
                        # from its largest declared bound <= b
                        at = [sb for sb in series if sb <= b]
                        total += series[max(at)] if at else 0.0
                    le = "+Inf" if b == math.inf else _fmt_bound(b)
                    lines.append(
                        f"{fam}_bucket{_label_str({**base, 'le': le})} "
                        f"{_fmt_merged(total)}"
                    )
                lines.append(
                    f"{fam}_sum{_label_str(base)} "
                    f"{repr(float(slot['sum']))}"
                )
                lines.append(
                    f"{fam}_count{_label_str(base)} "
                    f"{_fmt_merged(slot['count'])}"
                )
        else:  # gauge / summary / untyped: label per instance
            for inst in sorted(parsed):
                ent = parsed[inst].get(fam)
                if not ent:
                    continue
                for name, labels, value in ent["samples"]:
                    head()
                    out = dict(labels)
                    out["instance"] = inst
                    lines.append(
                        f"{name}{_label_str(out)} {_fmt_merged(value)}"
                    )
    return "\n".join(lines) + "\n" if lines else "\n"


def _fmt_bound(b: float) -> str:
    # bucket bounds render like the single-node writer (_fmt on floats)
    return repr(float(b))


# ---------------------------------------------------------------------------
# fleet scraper (the /metrics?fleet=1 backend)
# ---------------------------------------------------------------------------

class FleetScraper:
    """Fan-out + merge for the federated scrape.

    ``peers_fn()`` returns ``{node_id: pull}`` for every ALIVE remote
    member, where ``pull()`` fetches that node's full metrics text over
    the peer wire (T_STATS ``{"metrics": true}``) and raises on any
    failure.  Per-peer failures degrade to the last cached snapshot
    (flagged stale) or drop the instance (flagged unreachable) — the
    merged payload is always a valid 200."""

    def __init__(
        self,
        node_id: str,
        local_text_fn: Callable[[], str],
        peers_fn: Optional[Callable[[], Dict[str, Callable[[], str]]]] = None,
        timeout_s: float = 0.75,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.node_id = node_id or "local"
        self._local_text_fn = local_text_fn
        self._peers_fn = peers_fn
        self.timeout_s = max(0.05, float(timeout_s))
        self._clock = clock
        self._lock = threading.Lock()
        # node_id -> (text, fetched_at): survives a peer dying mid-scrape
        self._cache: Dict[str, Tuple[str, float]] = {}
        # node_id -> parsed families from the last scrape (fleet SLO feed)
        self._last_parsed: Dict[str, Dict[str, dict]] = {}

    def scrape(self) -> str:
        """One federated scrape: local + every ALIVE peer, merged."""
        now = self._clock()
        texts: Dict[str, str] = {}
        unreachable: Dict[str, int] = {}
        staleness: Dict[str, float] = {}

        local_text = self._local_text_fn()
        texts[self.node_id] = local_text
        unreachable[self.node_id] = 0
        staleness[self.node_id] = 0.0

        peers = {}
        if self._peers_fn is not None:
            try:
                peers = dict(self._peers_fn())
            except Exception:  # noqa: BLE001 — a membership bug must not 500 the scrape
                peers = {}
        for nid in sorted(peers):
            if nid == self.node_id:
                continue
            try:
                failpoints.check("obs.fleet.pull")
                text = peers[nid]()
                if not isinstance(text, str):
                    raise TypeError("peer metrics payload is not text")
                parse_text_format(text)  # reject a corrupt peer payload
                with self._lock:
                    self._cache[nid] = (text, now)
                texts[nid] = text
                unreachable[nid] = 0
                staleness[nid] = 0.0
            except Exception:  # noqa: BLE001 — partial-but-honest, never a 500
                unreachable[nid] = 1
                with self._lock:
                    cached = self._cache.get(nid)
                if cached is not None:
                    texts[nid] = cached[0]
                    staleness[nid] = max(0.0, now - cached[1])

        try:
            merged = merge_expositions(texts)
        except Exception:  # noqa: BLE001 — one bad cached text must not 500
            merged = merge_expositions({self.node_id: local_text})
            for nid in list(texts):
                if nid != self.node_id:
                    unreachable[nid] = 1
                    staleness.pop(nid, None)

        with self._lock:
            self._last_parsed = {
                inst: parse_text_format(t) for inst, t in texts.items()
            }

        lines = [merged.rstrip("\n")] if merged.strip() else []
        fam = registry.PROM_FAMILIES["banjax_fleet_peer_unreachable"]
        lines.append(f"# HELP {fam.prom} {fam.help}")
        lines.append(f"# TYPE {fam.prom} {fam.kind}")
        for nid in sorted(unreachable):
            lines.append(
                f'{fam.prom}{{instance="{_esc(nid)}"}} {unreachable[nid]}'
            )
        fam = registry.PROM_FAMILIES["banjax_fleet_peer_staleness_seconds"]
        lines.append(f"# HELP {fam.prom} {fam.help}")
        lines.append(f"# TYPE {fam.prom} {fam.kind}")
        for nid in sorted(staleness):
            lines.append(
                f'{fam.prom}{{instance="{_esc(nid)}"}} '
                f"{_fmt_merged(staleness[nid])}"
            )
        return "\n".join(lines) + "\n"

    # ---- fleet SLO feed ----

    _SLO_COUNTERS = {
        "admitted": "banjax_pipeline_admitted_lines_total",
        "processed": "banjax_pipeline_processed_lines_total",
        "stale": "banjax_pipeline_stale_dropped_lines_total",
    }
    _SLO_SHED = (
        "banjax_pipeline_shed_lines_total",
        "banjax_pipeline_drain_error_lines_total",
    )

    def fleet_collect(self) -> Dict[str, float]:
        """Cluster-wide counter sums from the last scrape, shaped for
        obs/slo.py ``collect_fn`` — the fleet-mode SloEngine burns the
        merged shed/stale streams exactly like a node burns its own."""
        with self._lock:
            parsed = self._last_parsed
        if not parsed:
            return {}

        def total(fam_name: str) -> float:
            out = 0.0
            for fams in parsed.values():
                ent = fams.get(fam_name)
                if ent:
                    out += sum(v for _, _, v in ent["samples"])
            return out

        vals: Dict[str, float] = {
            key: total(fam) for key, fam in self._SLO_COUNTERS.items()
        }
        vals["shed"] = sum(total(f) for f in self._SLO_SHED)
        return vals


# ---------------------------------------------------------------------------
# cluster incident capture (T_FLIGHTREC fan-out + per-node snapshot)
# ---------------------------------------------------------------------------

PEER_CAPTURE_FILES = (
    "trace.json", "metrics.prom", "provenance.json", "fabric.json",
)


def local_capture_files(
    metrics_text_fn: Optional[Callable[[], str]] = None,
    fabric_fn: Optional[Callable[[], Optional[dict]]] = None,
    provenance_tail: int = 256,
) -> Dict[str, str]:
    """This node's contribution to a REMOTE incident bundle — the body
    of a T_FLIGHTREC_R reply.  Mirrors obs/flightrec.FlightRecorder.
    _capture's per-file shape so the ``peers/<nid>/`` tree reads like a
    miniature bundle; every read is guarded — a partial snapshot beats
    none."""
    from banjax_tpu.obs import provenance, trace

    files: Dict[str, str] = {}
    try:
        files["trace.json"] = json.dumps(
            trace.get_tracer().export_chrome(), separators=(",", ":")
        )
    except Exception as e:  # noqa: BLE001 — partial snapshot beats none
        files["trace.json"] = json.dumps({"error": str(e)})
    if metrics_text_fn is not None:
        try:
            files["metrics.prom"] = metrics_text_fn()
        except Exception as e:  # noqa: BLE001
            files["metrics.prom"] = f"# capture failed: {e}\n"
    try:
        ledger = provenance.get_ledger()
        files["provenance.json"] = json.dumps(
            {
                "records": ledger.tail(provenance_tail),
                "counters": {
                    f"{src}/{dec}": v
                    for (src, dec), v in sorted(ledger.counters().items())
                },
            },
            indent=1,
        )
    except Exception as e:  # noqa: BLE001
        files["provenance.json"] = json.dumps({"error": str(e)})
    if fabric_fn is not None:
        try:
            fabric = fabric_fn()
        except Exception as e:  # noqa: BLE001
            fabric = {"enabled": False, "error": str(e)}
        files["fabric.json"] = json.dumps(
            fabric if fabric is not None else {"enabled": False},
            indent=1, default=str,
        )
    return files


def capture_fleet(
    incident_id: str,
    peers_fn: Callable[[], Dict[str, Callable[[str], Dict[str, str]]]],
) -> Dict[str, Dict[str, str]]:
    """Fan an incident capture out to every ALIVE peer.

    ``peers_fn()`` returns ``{node_id: capture}`` where
    ``capture(incident_id)`` performs the T_FLIGHTREC exchange and
    returns that peer's file map.  A failed peer contributes an
    ``error.txt`` instead of vanishing — the bundle records who could
    not answer, which during a shard failure is itself evidence."""
    out: Dict[str, Dict[str, str]] = {}
    try:
        peers = dict(peers_fn())
    except Exception:  # noqa: BLE001 — capture must never take down its trigger
        return out
    for nid in sorted(peers):
        try:
            failpoints.check("obs.fleet.capture")
            files = peers[nid](incident_id)
            if not isinstance(files, dict):
                raise TypeError("peer capture payload is not a file map")
            out[nid] = {
                str(fname): str(content)
                for fname, content in files.items()
                if str(fname) == str(fname).strip("/")
                and ".." not in str(fname)
            }
        except Exception as e:  # noqa: BLE001 — a dead peer is evidence, not an abort
            out[nid] = {"error.txt": f"capture failed: {e}\n"}
    return out
