"""Traffic introspection plane: device-resident streaming sketches.

PR 5/6 made the *engine* observable (spans, /metrics, provenance, SLO
burn, incident bundles); this module makes the *traffic* observable
mid-flood, before any ban fires: who the heavy hitters are, how many
distinct sources are active, and which rules are under pressure.

Three classic streaming structures live as flat device arrays and fold
every matcher chunk in-stream, as one more stateless array op next to
the fused match+window dispatch (zero interaction with window state —
the differential suite proves sketch-on == sketch-off on ban-log bytes,
result stream and window state):

  * a count–min sketch (Cormode & Muthukrishnan, 2005) over client-IP
    hashes — [depth * width] int32, conservative point estimates that
    never undercount, so the host-side top-K heap ranks heavy hitters
    from periodic compact pulls;
  * a HyperLogLog register array (Flajolet et al., 2007) — 2^p int32
    registers for distinct-source cardinality at ~1.04/sqrt(2^p)
    relative error.

Per-rule match-pressure accumulators (the "which rule is absorbing the
flood" view) ride the HOST side instead: every fired (line, rule)
window event already crosses to the host for the Banner replay, on
every path — fused commit, overflow fallback, classic apply — so
counting there is exact even for chunks whose device bitmap was
incomplete (candidate overflow), at O(events) cost the replay already
pays.

Zero extra per-row h2d traffic: the update keys on the per-row window
SLOT ids the fused path already uploads, gathered through a
device-resident slot→ip-hash table that the host refreshes only for
newly-assigned slots (`note_assignments`, fed from the same unique-IP
tables the slot manager walks anyway).  In steady state — the slot table
warm — a chunk's sketch update uploads nothing at all.

Pulls are PERIODIC, never per-batch: `pull()` is throttled by
`traffic_sketch_pull_seconds` (one compact d2h of ~depth*width*4 +
2^p*4 + n_rules*4 bytes, traced as a `sketch-pull` span), and every
consumer — `GET /traffic/top`, the 29 s line, /metrics, flight-recorder
bundles — reads the cached summary between refreshes.

This is deliberately the read-only half of ROADMAP item 1 (mega-state):
the cold-admission decision the mega-state PR needs can gate on exactly
these estimates; building the sketch first as telemetry de-risks it.
"""

from __future__ import annotations

import functools
import heapq
import logging
import math
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.obs import trace

log = logging.getLogger(__name__)

# xor seeds decorrelating the count-min rows (any fixed distinct values
# work: the row hash is fmix32(ip_hash ^ seed_j));  the golden-ratio
# constant seeds the independent HLL hash
_CM_SEEDS = (0x0000_0000, 0x7F4A_7C15, 0x94D0_49BB, 0xDE82_4AD5,
             0x1B87_3593, 0xC2B2_AE35, 0x27D4_EB2F, 0x1656_67B1)
_HLL_SEED = 0x9E37_79B9

_MIN_ROW_BUCKET = 64
_MIN_SLOT_TABLE = 1024


def _bucket(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    """murmur3 finalizer, numpy uint32 — the HOST mirror of the device
    mix below; the two must agree bit-for-bit or point estimates read
    the wrong buckets."""
    h = h.astype(np.uint32, copy=True)
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EB_CA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2_AE35)
    h ^= h >> np.uint32(16)
    return h


def _fmix32_jnp(h):
    h = h.astype(jnp.uint32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EB_CA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2_AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def hash_ip(ip: str) -> int:
    """The 32-bit base hash of one client-IP string (crc32 of the utf-8
    bytes).  Every derived hash — count-min rows, the HLL register pick
    — mixes from THIS value, on host and device alike."""
    return zlib.crc32(ip.encode("utf-8", "surrogatepass")) & 0xFFFF_FFFF


def hll_estimate(registers: np.ndarray) -> float:
    """Standard bias-corrected HyperLogLog estimate with the
    small-range (linear counting) correction; the large-range 32-bit
    correction is omitted on purpose — at 2^30+ distinct sources the
    answer "effectively unbounded" is the operational truth."""
    m = registers.size
    alpha = 0.7213 / (1.0 + 1.079 / m)
    raw = alpha * m * m / float(np.sum(np.exp2(-registers.astype(np.float64))))
    if raw <= 2.5 * m:
        zeros = int(np.count_nonzero(registers == 0))
        if zeros:
            return m * math.log(m / zeros)
    return raw


class TrafficSketch:
    """Device-resident traffic sketches with a host-side top-K view.

    Thread-safe: `note_assignments` / `update` / `pull` may race from
    the submit and drain threads; one lock serializes the donated-state
    device dispatches and the host bookkeeping.  A sketch failure must
    never cost a log line — callers wrap update hooks, and `pull`
    degrades to the last cached summary.
    """

    def __init__(
        self,
        rule_names: Sequence[str],
        *,
        depth: int = 4,
        width: int = 8192,
        hll_p: int = 12,
        pull_seconds: float = 5.0,
        topk: int = 32,
        max_candidates: int = 8192,
    ):
        if not 1 <= depth <= len(_CM_SEEDS):
            raise ValueError(f"sketch depth must be 1..{len(_CM_SEEDS)}")
        if width < 16:
            raise ValueError("sketch width must be >= 16")
        if not 4 <= hll_p <= 16:
            raise ValueError("hll_p must be 4..16")
        self.depth = int(depth)
        self.width = int(width)
        self.hll_p = int(hll_p)
        self.m = 1 << self.hll_p
        self.pull_seconds = max(0.0, float(pull_seconds))
        self.topk = max(1, int(topk))
        self.max_candidates = max(self.topk, int(max_candidates))
        self.rule_names = list(rule_names)
        self._n_rules = max(1, len(self.rule_names))

        self._lock = threading.Lock()
        # donated device state: (cm [depth*width], hll [m])
        self._state = (
            jnp.zeros((self.depth * self.width,), dtype=jnp.int32),
            jnp.zeros((self.m,), dtype=jnp.int32),
        )
        # per-rule pressure: host-side exact counts of fired (line, rule)
        # window events (note_rule_events, fed from the Banner replay)
        self._rule_hits = np.zeros(self._n_rules, dtype=np.int64)
        # HOST count-min mirror for slot-REFUSED rows: a refused row has
        # no slot, so it never reaches the device update — its count
        # accrues here (fold_refused) in the same bucket geometry.  An
        # unseen IP's own rows therefore land EXACTLY in this array, and
        # the device sketch contributes only collisions, so
        # estimate_ips >= the IP's true refused-row count no matter how
        # stale the cached device pull is — the conservatism the
        # admission gate's bounded-delay argument needs.
        self._cm_host = np.zeros((self.depth, self.width), dtype=np.int64)
        self.refused_rows_folded = 0
        # last-pulled device count-min (host copy): estimate_ips reads
        # this CACHE — the admission gate runs per batch and must never
        # force a d2h pull
        self._cm_cache: Optional[np.ndarray] = None
        # slot → ip-hash table: device copy gathered by the update op
        # (the per-row hashes are already on device once a slot is warm),
        # host mirror diffed per batch so only CHANGED slots scatter up
        self._slot_hash_dev = jnp.zeros((_MIN_SLOT_TABLE,), dtype=jnp.uint32)
        self._slot_hash_host = np.zeros(_MIN_SLOT_TABLE, dtype=np.uint32)
        # candidate heavy hitters: LRU of recently-seen distinct IPs and
        # their base hashes — the enumerable key set a count-min sketch
        # itself cannot provide.  A true heavy hitter recurs every batch,
        # so it cannot age out of a bound >> topk.
        self._candidates: "OrderedDict[str, int]" = OrderedDict()
        self._update_fns: Dict[tuple, object] = {}

        self.lines_total = 0          # lines folded into the sketch
        self.update_count = 0
        self.pull_count = 0
        self.pull_bytes_total = 0
        self._last_pull_mono: Optional[float] = None
        self._summary: Optional[dict] = None
        self._seeds = jnp.asarray(
            np.asarray(_CM_SEEDS[: self.depth], dtype=np.uint32)
        )

    # ---- host bookkeeping (slot table + candidates) ----

    def note_assignments(
        self, ips: Sequence[str], slots: np.ndarray
    ) -> None:
        """Refresh the slot→hash table for one batch's DISTINCT
        (ip, slot) pairs — the same unique tables the slot manager just
        walked.  Only slots whose owner changed scatter to the device;
        a warm table uploads nothing."""
        n = len(ips)
        if n == 0:
            return
        slots = np.asarray(slots, dtype=np.int64)
        with self._lock:
            cand = self._candidates
            hashes = np.empty(n, dtype=np.uint32)
            for k, ip in enumerate(ips):
                h = cand.get(ip)
                if h is None:
                    h = hash_ip(ip)
                cand[ip] = h  # insert or refresh recency
                cand.move_to_end(ip)
                hashes[k] = h
            while len(cand) > self.max_candidates:
                cand.popitem(last=False)

            need = int(slots.max()) + 1
            if need > self._slot_hash_host.size:
                new_size = _bucket(need, _MIN_SLOT_TABLE)
                grown = np.zeros(new_size, dtype=np.uint32)
                grown[: self._slot_hash_host.size] = self._slot_hash_host
                self._slot_hash_host = grown
                self._slot_hash_dev = jnp.concatenate([
                    self._slot_hash_dev,
                    jnp.zeros(
                        new_size - self._slot_hash_dev.shape[0],
                        dtype=jnp.uint32,
                    ),
                ])
            changed = self._slot_hash_host[slots] != hashes
            if changed.any():
                ch_slots = slots[changed]
                ch_hash = hashes[changed]
                self._slot_hash_host[ch_slots] = ch_hash
                # pow2-bucketed scatter (padded entries index out of
                # range and drop) so the jit cache stays bounded
                kk = _bucket(len(ch_slots), 64)
                idx = np.full(kk, self._slot_hash_host.size, dtype=np.int32)
                idx[: len(ch_slots)] = ch_slots
                val = np.zeros(kk, dtype=np.uint32)
                val[: len(ch_hash)] = ch_hash
                self._slot_hash_dev = _scatter_hashes(
                    self._slot_hash_dev, jnp.asarray(idx), jnp.asarray(val)
                )

    # ---- the per-chunk device update ----

    def _update_fn(self, Bp: int, cap: int):
        key = (Bp, cap)
        fn = self._update_fns.get(key)
        if fn is not None:
            return fn
        depth, width, p = self.depth, self.width, self.hll_p
        seeds = self._seeds
        low_bits = 32 - p

        @functools.partial(jax.jit, donate_argnums=(0,))
        def update(state, slot_hash, slots, n_real):
            cm, hll = state
            h = slot_hash[slots]                         # [Bp] uint32
            real = jax.lax.iota(jnp.int32, Bp) < n_real
            inc = real.astype(jnp.int32)
            # count-min: one bucket increment per row per line (scatter-
            # add accumulates duplicate indices — repeated IPs in a batch
            # land their full count)
            hx = h[None, :] ^ seeds[:, None]             # [depth, Bp]
            col = (_fmix32_jnp(hx) % jnp.uint32(width)).astype(jnp.int32)
            flat = col + (
                jnp.arange(depth, dtype=jnp.int32)[:, None] * width
            )
            cm = cm.at[flat.reshape(-1)].add(
                jnp.broadcast_to(inc[None, :], (depth, Bp)).reshape(-1)
            )
            # HLL: register = top p bits of an independent mix, rho =
            # leading zeros of the remaining bits + 1 (bit-smear +
            # popcount gives the MSB position exactly — no float log)
            g = _fmix32_jnp(h ^ jnp.uint32(_HLL_SEED))
            reg = (g >> jnp.uint32(low_bits)).astype(jnp.int32)
            w = g & jnp.uint32((1 << low_bits) - 1)
            fill = w
            for s in (1, 2, 4, 8, 16):
                fill = fill | (fill >> jnp.uint32(s))
            msb_cnt = jax.lax.population_count(fill).astype(jnp.int32)
            rho = low_bits - msb_cnt + 1
            hll = hll.at[reg].max(jnp.where(real, rho, 0))
            return cm, hll

        self._update_fns[key] = update
        return update

    def update(self, slots, n_real: int) -> None:
        """Fold one chunk's rows into the count-min and HLL sketches:
        `slots` per row (rows beyond `n_real` are masked; the row bucket
        pads to a power of two so the jit cache stays bounded).  One
        stateless donated-array dispatch; nothing is read back."""
        slots_np = np.asarray(slots, dtype=np.int32)
        Bp = _bucket(max(len(slots_np), 1), _MIN_ROW_BUCKET)
        if len(slots_np) != Bp:
            slots_np = np.concatenate(
                [slots_np, np.zeros(Bp - len(slots_np), dtype=np.int32)]
            )
        n_real = min(int(n_real), Bp)
        with self._lock:
            cap = int(self._slot_hash_dev.shape[0])
            fn = self._update_fn(Bp, cap)
            self._state = fn(
                self._state, self._slot_hash_dev, jnp.asarray(slots_np),
                jnp.int32(n_real),
            )
            self.lines_total += n_real
            self.update_count += 1

    def note_rule_events(self, rule_ids) -> None:
        """Fold fired (line, rule) window events into the per-rule
        pressure accumulators — called from the Banner replay with the
        event list every path already decodes, so pressure is EXACT even
        for chunks whose device bitmap overflowed."""
        ids = np.fromiter(
            (int(r) for r in rule_ids), dtype=np.int64
        )
        if not ids.size:
            return
        counts = np.bincount(
            ids[(ids >= 0) & (ids < self._n_rules)],
            minlength=self._n_rules,
        )
        with self._lock:
            self._rule_hits += counts

    # ---- the periodic compact pull ----

    def pull(self, force: bool = False) -> dict:
        """Refresh (throttled by `pull_seconds`) and return the host
        summary: top-K heavy hitters with conservative count-min
        estimates, the HLL distinct-IP estimate, per-rule pressure, and
        pull bookkeeping.  Between refreshes every consumer shares the
        cached summary — the sketch is pulled on a sampling interval,
        never per batch."""
        with self._lock:
            now_m = time.monotonic()
            if (
                not force
                and self._summary is not None
                and self._last_pull_mono is not None
                and now_m - self._last_pull_mono < self.pull_seconds
            ):
                return self._summary
            # a pull belongs to no admission batch: it gets its own
            # trace id (like shed instants), so the Perfetto view shows
            # WHEN the compact d2h ran relative to the batch spans
            sp = trace.begin(
                "sketch-pull", trace.new_trace(),
                args={"forced": bool(force)},
            )
            try:
                cm = np.asarray(self._state[0]).reshape(
                    self.depth, self.width
                )
                hll = np.asarray(self._state[1])
            finally:
                trace.end(sp)
            rule_hits = self._rule_hits  # host-side, no pull needed
            self._cm_cache = cm  # refresh the admission gate's cache
            self.pull_bytes_total += cm.nbytes + hll.nbytes
            self.pull_count += 1
            self._last_pull_mono = time.monotonic()

            top: List[dict] = []
            if self._candidates:
                ips = list(self._candidates)
                base = np.fromiter(
                    self._candidates.values(), dtype=np.uint32, count=len(ips)
                )
                est = None
                for j in range(self.depth):
                    col = _fmix32_np(base ^ np.uint32(_CM_SEEDS[j])) \
                        % np.uint32(self.width)
                    ci = col.astype(np.int64)
                    # device buckets + the refused-row host mirror: the
                    # estimate covers ALL of an IP's rows, slotted or not
                    vals = cm[j, ci] + self._cm_host[j, ci]
                    est = vals if est is None else np.minimum(est, vals)
                for k in heapq.nlargest(
                    self.topk, range(len(ips)), key=lambda i: int(est[i])
                ):
                    if est[k] <= 0:
                        break
                    top.append({"ip": ips[k], "est_count": int(est[k])})

            distinct = hll_estimate(hll)
            lines = self.lines_total
            share = (
                round(top[0]["est_count"] / lines, 4)
                if top and lines else 0.0
            )
            pressure = [
                {"rule": name, "index": i, "events": int(rule_hits[i])}
                for i, name in enumerate(self.rule_names)
                if i < rule_hits.size and rule_hits[i] > 0
            ]
            pressure.sort(key=lambda r: -r["events"])
            self._summary = {
                "top": top,
                "k_max": self.topk,
                "distinct_ips_estimate": round(distinct, 1),
                "heavy_hitter_share": share,
                "lines_total": lines,
                "rule_pressure": pressure,
                "sketch": {
                    "depth": self.depth,
                    "width": self.width,
                    "hll_registers": self.m,
                    "candidates": len(self._candidates),
                    "pull_count": self.pull_count,
                    "pull_bytes_total": self.pull_bytes_total,
                },
            }
            return self._summary

    def pull_age_seconds(self) -> Optional[float]:
        with self._lock:
            if self._last_pull_mono is None:
                return None
            return time.monotonic() - self._last_pull_mono

    def estimate_ip(self, ip: str) -> int:
        """Point estimate for one IP from the LAST pulled count-min
        state (tests; /traffic debugging).  Conservative: >= the true
        count folded in before that pull."""
        summary = self.pull()
        del summary
        with self._lock:
            cm = np.asarray(self._state[0]).reshape(self.depth, self.width)
            cm_host = self._cm_host
        base = np.uint32(hash_ip(ip))
        est = None
        for j in range(self.depth):
            col = int(
                _fmix32_np(np.asarray([base ^ np.uint32(_CM_SEEDS[j])],
                                      dtype=np.uint32))[0]
            ) % self.width
            v = int(cm[j, col]) + int(cm_host[j, col])
            est = v if est is None else min(est, v)
        return int(est or 0)

    # ---- the cold-tier admission surface (mega-state tiering) ----

    @staticmethod
    def base_hashes(ips: Sequence[str]) -> np.ndarray:
        """uint32 [n] base hashes for a distinct-ip list — computed once
        per batch by the runner and shared between estimate_ips and
        fold_refused (the crc32 walk is the per-unseen-ip host cost)."""
        return np.fromiter(
            (hash_ip(ip) for ip in ips), dtype=np.uint32, count=len(ips)
        )

    def _columns(self, base: np.ndarray) -> np.ndarray:
        """int64 [depth, n] count-min column per row for base hashes."""
        cols = np.empty((self.depth, len(base)), dtype=np.int64)
        for j in range(self.depth):
            cols[j] = (
                _fmix32_np(base ^ np.uint32(_CM_SEEDS[j]))
                % np.uint32(self.width)
            ).astype(np.int64)
        return cols

    def estimate_ips(
        self, ips: Sequence[str], hashes: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """Conservative count estimates, int64 [n], from the CACHED
        last-pulled device count-min plus the exact refused-row host
        mirror.  Never forces a pull — this runs in the admission gate,
        once per batch.  An unseen IP's own rows are all in the host
        mirror (fold_refused), so staleness of the device cache can only
        UNDER-estimate collision noise, never the IP's true count."""
        n = len(ips)
        if n == 0:
            return np.zeros(0, dtype=np.int64)
        base = self.base_hashes(ips) if hashes is None else hashes
        cols = self._columns(base)
        with self._lock:
            cache = self._cm_cache
            est: Optional[np.ndarray] = None
            for j in range(self.depth):
                vals = self._cm_host[j, cols[j]]
                if cache is not None:
                    vals = vals + cache[j, cols[j]]
                est = vals if est is None else np.minimum(est, vals)
        return est

    def fold_refused(
        self,
        ips: Sequence[str],
        counts: np.ndarray,
        hashes: Optional[np.ndarray] = None,
    ) -> None:
        """Fold one batch's REFUSED rows into the host count-min mirror:
        `counts[i]` rows for distinct ip `ips[i]`.  Exact (int64 adds,
        no sampling) — these rows never reach the device sketch, and the
        admission gate's bounded-delay argument needs every one of them
        counted."""
        n = len(ips)
        if n == 0:
            return
        base = self.base_hashes(ips) if hashes is None else hashes
        cols = self._columns(base)
        counts = np.asarray(counts, dtype=np.int64)
        with self._lock:
            for j in range(self.depth):
                np.add.at(self._cm_host[j], cols[j], counts)
            self.refused_rows_folded += int(counts.sum())

    def incident_snapshot(self) -> dict:
        """The flight-recorder view (`traffic.json`): a FORCED pull so
        the bundle shows the flood as of the incident, not the last
        sampling tick."""
        out = dict(self.pull(force=True))
        out["enabled"] = True
        return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_hashes(table, idx, val):
    return table.at[idx].set(val, mode="drop")
