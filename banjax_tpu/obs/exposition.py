"""Prometheus text-format exposition for the /metrics route.

Pull-based exposition (Prometheus exposition format 0.0.4) over the
same accumulators the 29-second line snapshots — WITHOUT renaming the
legacy line or stealing its interval windows: every value here comes
from the non-destructive `peek()` accessors (obs/stats.py), monotone
totals and point-in-time gauges, so any number of scrapers can pull at
any cadence alongside the line's single periodic consumer.

Every family is declared in obs/registry.py (name, type, help); the
renderer walks the registry, so an undeclared family cannot be emitted
and a renamed one fails the schema test, not a dashboard.

`parse_text_format()` is the strict parser the tests (and operators
debugging a scrape) use: it validates name/label syntax, HELP/TYPE
placement, histogram bucket monotonicity and the `le="+Inf"` == count
invariant — stricter than Prometheus' own forgiving ingest, on purpose.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

from banjax_tpu.obs import registry
from banjax_tpu.obs.registry import (
    COUNTER,
    FAMILIES,
    GAUGE,
    HISTOGRAM,
    Histogram,
)
from banjax_tpu.resilience.breaker import CLOSED, HALF_OPEN, OPEN

_HEALTH_LEVELS = {"healthy": 0, "degraded": 1, "failed": 2, "unknown": 1}
_BREAKER_STATES = (CLOSED, OPEN, HALF_OPEN)


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _esc(label_value: str) -> str:
    return (str(label_value).replace("\\", "\\\\")
            .replace("\n", "\\n").replace('"', '\\"'))


def _labels(pairs: Dict[str, object]) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in pairs.items())
    return "{" + inner + "}"


class _Writer:
    def __init__(self):
        self.lines: List[str] = []
        self._declared = set()

    def head(self, fam) -> None:
        if fam.prom in self._declared:
            return
        self._declared.add(fam.prom)
        self.lines.append(f"# HELP {fam.prom} {fam.help}")
        self.lines.append(f"# TYPE {fam.prom} {fam.kind}")

    def sample(self, fam, value, labels: Optional[dict] = None) -> None:
        self.head(fam)
        self.lines.append(f"{fam.prom}{_labels(labels or {})} {_fmt(value)}")

    def histogram(self, fam, hist: Histogram,
                  labels: Optional[dict] = None) -> None:
        self.head(fam)
        bounds, cum, total_sum, count = hist.snapshot()
        base = dict(labels or {})
        for b, c in zip(bounds, cum):
            self.lines.append(
                f"{fam.prom}_bucket{_labels({**base, 'le': _fmt(float(b))})} {c}"
            )
        self.lines.append(
            f"{fam.prom}_bucket{_labels({**base, 'le': '+Inf'})} {count}"
        )
        self.lines.append(f"{fam.prom}_sum{_labels(base)} {_fmt(total_sum)}")
        self.lines.append(f"{fam.prom}_count{_labels(base)} {count}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_prometheus(
    dynamic_lists,
    regex_states,
    failed_challenge_states,
    matcher=None,
    pipeline=None,
    health=None,
    supervisor=None,
    slo=None,
    flightrec=None,
    fabric=None,
) -> str:
    """Render the full /metrics payload.  Args mirror
    obs.metrics.write_metrics_line — same sources, non-destructive
    reads."""
    # line-key-shaped value map from the non-destructive accessors; the
    # registry maps line_key -> prom family for everything scalar
    values: Dict[str, object] = {}
    challenges, blocks = dynamic_lists.metrics()
    values["LenExpiringChallenges"] = challenges
    values["LenExpiringBlocks"] = blocks
    values["LenIpToRegexStates"] = len(regex_states)
    values["LenFailedChallengeStates"] = len(failed_challenge_states)
    if matcher is not None:
        values.update(matcher.stats.peek(
            getattr(matcher, "device_windows", None), matcher
        ))
    if pipeline is not None:
        values.update(pipeline.prom_snapshot())
    try:
        from banjax_tpu.ingest import kafka_wire

        values["KafkaSkippedBatches"] = kafka_wire.skipped_batch_count()
    except Exception:  # noqa: BLE001 — exposition must not require kafka
        values["KafkaSkippedBatches"] = 0
    if fabric is not None:
        values.update(fabric.peek())
    if supervisor is not None:
        values["HttpWorkers"] = supervisor.n_workers
        values["HttpWorkerRespawns"] = supervisor.respawn_count
        values["HttpFcDropped"] = getattr(failed_challenge_states, "dropped", 0)

    w = _Writer()
    breaker_state = values.pop("MatcherBreakerState", None)
    for fam in FAMILIES:
        if not fam.prom or fam.kind == HISTOGRAM or fam.labels:
            continue
        if fam.line_key and fam.line_key in values:
            v = values[fam.line_key]
            if v is not None:
                w.sample(fam, v)

    # breaker state: one-hot by state label so dashboards can alert on
    # `banjax_matcher_breaker_state{state="open"} == 1`
    if breaker_state is not None:
        fam = registry.PROM_FAMILIES["banjax_matcher_breaker_state"]
        for s in _BREAKER_STATES:
            w.sample(fam, 1 if breaker_state == s else 0, {"state": s})

    # per-worker encode busy fractions (prom-only labeled gauge)
    if pipeline is not None:
        fracs = pipeline.stats.worker_busy_fractions()
        if fracs:
            fam = registry.PROM_FAMILIES["banjax_encode_worker_busy_fraction"]
            for k, frac in enumerate(fracs):
                w.sample(fam, frac, {"worker": str(k)})

    # traffic introspection: per-rule match-pressure counters from the
    # device sketch's last compact pull (obs/sketch.py) — only rules
    # with any recorded pressure emit, so a 1k-rule config doesn't pay
    # 1k lines per scrape while idle
    sketch = getattr(matcher, "traffic_sketch", None) if matcher else None
    if sketch is not None:
        try:
            pressure = sketch.pull().get("rule_pressure", ())
        except Exception:  # noqa: BLE001 — telemetry must not break a scrape
            pressure = ()
        if pressure:
            fam = registry.PROM_FAMILIES["banjax_traffic_rule_pressure"]
            for row in sorted(pressure, key=lambda r: r["rule"]):
                w.sample(fam, row["events"], {"rule": row["rule"]})

    # decision provenance: per-(source, decision) insert totals from the
    # process ledger (obs/provenance.py) — the attribution counter family
    from banjax_tpu.obs import provenance as provenance_mod

    prov_counters = provenance_mod.get_ledger().counters()
    if prov_counters:
        fam = registry.PROM_FAMILIES["banjax_decision_inserts_total"]
        for (source, decision), v in sorted(prov_counters.items()):
            w.sample(fam, v, {"source": source, "decision": decision})

    # SLO burn rates + the one-hot breach gauge (obs/slo.py)
    if slo is not None:
        burn_fam = registry.PROM_FAMILIES["banjax_slo_burn_rate"]
        for slo_name, windows in sorted(slo.burn_rates().items()):
            for window, rate in sorted(windows.items()):
                w.sample(burn_fam, rate, {"slo": slo_name, "window": window})
        breach_fam = registry.PROM_FAMILIES["banjax_slo_breached"]
        for slo_name, hit in sorted(slo.breached().items()):
            w.sample(breach_fam, 1 if hit else 0, {"slo": slo_name})

    # incident flight recorder (obs/flightrec.py)
    if flightrec is not None:
        w.sample(
            registry.PROM_FAMILIES["banjax_flightrec_incidents_total"],
            flightrec.incident_count,
        )

    # adversarial scenario harness (banjax_tpu/scenarios/stats.py — a
    # leaf module): last-run rows per attack shape, rendered only when
    # this process actually ran scenarios
    try:
        from banjax_tpu.scenarios.stats import get_stats as _scen_stats

        scen = _scen_stats().prom_snapshot()
    except Exception:  # noqa: BLE001 — the harness must not break a scrape
        scen = None
    if scen is not None and scen["runs_total"]:
        w.sample(registry.PROM_FAMILIES["banjax_scenario_runs_total"],
                 scen["runs_total"])
        w.sample(
            registry.PROM_FAMILIES[
                "banjax_scenario_injected_episodes_total"
            ],
            scen["episodes_total"],
        )
        w.sample(
            registry.PROM_FAMILIES[
                "banjax_scenario_invariant_failures_total"
            ],
            scen["invariant_failures_total"],
        )
        per_gauge = {
            "lines_per_sec": "banjax_scenario_lines_per_sec",
            "shed_ratio": "banjax_scenario_shed_ratio",
            "precision": "banjax_scenario_ban_precision",
            "recall": "banjax_scenario_ban_recall",
            "slo_burn_peak": "banjax_scenario_slo_burn_peak",
        }
        for name, row in sorted(scen["scenarios"].items()):
            for field, fam_name in per_gauge.items():
                if field in row:
                    w.sample(registry.PROM_FAMILIES[fam_name],
                             row[field], {"scenario": name})

    # challenge plane (banjax_tpu/challenge/stats.py — a leaf module):
    # issuance / verification / bounded-failure-state families, rendered
    # only when this process touched the challenge plane
    try:
        from banjax_tpu.challenge.stats import get_stats as _challenge_stats

        chal = _challenge_stats()
        chal_snap = chal.prom_snapshot() if chal.active() else None
        chal_hist = chal.verify_batch_size
    except Exception:  # noqa: BLE001 — a leaf must not break a scrape
        chal_snap = None
        chal_hist = None
    if chal_snap is not None:
        w.sample(
            registry.PROM_FAMILIES["banjax_challenge_issued_total"],
            chal_snap["issued_total"],
        )
        fam = registry.PROM_FAMILIES["banjax_challenge_verifications_total"]
        for (result, path), v in sorted(chal_snap["verifications"].items()):
            w.sample(fam, v, {"result": result, "path": path})
        w.sample(
            registry.PROM_FAMILIES["banjax_challenge_failure_state_entries"],
            chal_snap["failure_state_entries"],
        )
        w.sample(
            registry.PROM_FAMILIES[
                "banjax_challenge_failure_evictions_total"
            ],
            chal_snap["failure_evictions_total"],
        )
        w.histogram(
            registry.PROM_FAMILIES["banjax_challenge_verify_batch_size"],
            chal_hist,
        )

    # compiled serving fast path (httpapi/serve_stats.py — a leaf
    # module): per-tier hits, per-reason misses, table gauges; rendered
    # only when this process consulted the fast path / attached a table
    try:
        from banjax_tpu.httpapi.serve_stats import get_stats as _serve_stats

        serve = _serve_stats()
        serve_snap = serve.prom_snapshot() if serve.active() else None
    except Exception:  # noqa: BLE001 — a leaf must not break a scrape
        serve_snap = None
    if serve_snap is not None:
        fam = registry.PROM_FAMILIES["banjax_serve_fastpath_hits_total"]
        for tier, v in sorted(serve_snap["hits"].items()):
            w.sample(fam, v, {"tier": tier})
        fam = registry.PROM_FAMILIES["banjax_serve_fastpath_misses_total"]
        for reason, v in sorted(serve_snap["misses"].items()):
            w.sample(fam, v, {"reason": reason})
        w.sample(
            registry.PROM_FAMILIES["banjax_serve_fastpath_faults_total"],
            serve_snap["faults_total"],
        )
        w.sample(
            registry.PROM_FAMILIES["banjax_serve_fastpath_table_entries"],
            serve_snap["table_entries"],
        )
        w.sample(
            registry.PROM_FAMILIES[
                "banjax_serve_fastpath_table_dropped_total"
            ],
            serve_snap["table_dropped_total"],
        )
        w.sample(
            registry.PROM_FAMILIES[
                "banjax_serve_fastpath_table_session_entries"
            ],
            serve_snap["table_session_entries"],
        )
        w.sample(
            registry.PROM_FAMILIES[
                "banjax_serve_fastpath_mirror_errors_total"
            ],
            serve_snap["mirror_errors_total"],
        )

    # kernel-edge ban batching (effectors/ipset_stats.py — a leaf
    # module): batch sends, routed failures, queue pressure
    try:
        from banjax_tpu.effectors.ipset_stats import get_stats as _ipset_stats

        ipset = _ipset_stats()
        ipset_snap = ipset.prom_snapshot() if ipset.active() else None
    except Exception:  # noqa: BLE001 — a leaf must not break a scrape
        ipset_snap = None
    if ipset_snap is not None:
        w.sample(
            registry.PROM_FAMILIES["banjax_ipset_batch_sends_total"],
            ipset_snap["batch_sends_total"],
        )
        w.sample(
            registry.PROM_FAMILIES["banjax_ipset_batch_entries_total"],
            ipset_snap["batch_entries_total"],
        )
        fam = registry.PROM_FAMILIES["banjax_ipset_errors_total"]
        for path, v in sorted(ipset_snap["errors"].items()):
            w.sample(fam, v, {"path": path})
        w.sample(
            registry.PROM_FAMILIES["banjax_ipset_fallback_total"],
            ipset_snap["fallback_total"],
        )
        w.sample(
            registry.PROM_FAMILIES["banjax_ipset_queue_shed_total"],
            ipset_snap["queue_shed_total"],
        )
        w.sample(
            registry.PROM_FAMILIES["banjax_ipset_queue_depth"],
            ipset_snap["queue_depth"],
        )

    # multi-host fabric: per-peer liveness gauge + takeover duration
    # histogram (banjax_tpu/fabric/stats.py; scalar totals merged above)
    if fabric is not None:
        peers = fabric.peers_snapshot()
        if peers:
            fam = registry.PROM_FAMILIES["banjax_fabric_peer_up"]
            for pid, up in sorted(peers.items()):
                w.sample(fam, 1 if up else 0, {"peer": pid})
        w.histogram(
            registry.PROM_FAMILIES["banjax_fabric_takeover_duration_seconds"],
            fabric.takeover_duration,
        )
        states = fabric.member_states_snapshot()
        if states:
            fam = registry.PROM_FAMILIES["banjax_fabric_membership_state"]
            enc = {"alive": 0, "suspect": 1, "dead": 2, "left": 3}
            for pid, state in sorted(states.items()):
                w.sample(fam, enc.get(state, 2), {"peer": pid})
        w.histogram(
            registry.PROM_FAMILIES[
                "banjax_fabric_membership_detection_seconds"
            ],
            fabric.detection_time,
        )
        frames = fabric.frames_snapshot()
        if frames:
            fam = registry.PROM_FAMILIES["banjax_fabric_frames_total"]
            for (version, transport), n in sorted(frames.items()):
                w.sample(fam, n,
                         {"version": version, "transport": transport})
        w.histogram(
            registry.PROM_FAMILIES["banjax_fabric_frame_bytes"],
            fabric.frame_bytes,
        )
        w.histogram(
            registry.PROM_FAMILIES["banjax_fabric_ack_rtt_seconds"],
            fabric.ack_rtt,
        )
        # gossip-piggybacked fleet health bits (obs/fleet.py encoding)
        peer_health = fabric.peer_health_snapshot()
        if peer_health:
            fam = registry.PROM_FAMILIES["banjax_fabric_peer_health"]
            for nid, bits in sorted(peer_health.items()):
                w.sample(fam, bits, {"node": nid})

    # component health: aggregate + one labeled gauge per component
    if health is not None:
        snap = health.snapshot()
        fam = registry.PROM_FAMILIES["banjax_health_status"]
        w.sample(fam, _HEALTH_LEVELS.get(snap["status"], 1))
        comp_fam = registry.PROM_FAMILIES["banjax_health_component_status"]
        for name, comp in sorted(snap["components"].items()):
            w.sample(comp_fam, _HEALTH_LEVELS.get(comp["status"], 1),
                     {"component": name})

    # histograms
    if matcher is not None:
        w.histogram(
            registry.PROM_FAMILIES["banjax_batch_latency_seconds"],
            matcher.stats.batch_latency_hist,
        )
    if pipeline is not None:
        w.histogram(
            registry.PROM_FAMILIES["banjax_device_stage_latency_seconds"],
            pipeline.stats.device_latency_hist,
        )
        stage_fam = registry.PROM_FAMILIES["banjax_stage_duration_seconds"]
        for stage, hist in pipeline.stats.stage_hists.items():
            w.histogram(stage_fam, hist, {"stage": stage})
        # tailer read -> effector commit, by hop (local vs fabric)
        e2e_fam = registry.PROM_FAMILIES["banjax_e2e_latency_seconds"]
        for hop, hist in pipeline.stats.e2e_hists.items():
            w.histogram(e2e_fam, hist, {"hop": hop})
    return w.text()


# ---------------------------------------------------------------------------
# strict text-format parser (tests + scrape debugging)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>-?\d+))?$"
)
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)'
)


class ExpositionError(ValueError):
    pass


def _family_of(sample_name: str, types: Dict[str, str]) -> str:
    """Map a sample name to its family (histogram samples use the
    _bucket/_sum/_count suffixes)."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if types.get(base) == HISTOGRAM:
                return base
    return sample_name


def parse_text_format(text: str) -> Dict[str, dict]:
    """Parse + validate Prometheus text format strictly.

    Returns {family: {"type", "help", "samples": [(name, labels, value)]}}.
    Raises ExpositionError on: missing trailing newline, samples without
    a preceding TYPE, bad metric/label syntax, unparsable values,
    histogram buckets that are non-monotone / missing +Inf / +Inf !=
    count, or a family declared twice.
    """
    if not text.endswith("\n"):
        raise ExpositionError("exposition must end with a newline")
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    fams: Dict[str, dict] = {}
    for ln, raw in enumerate(text.split("\n")[:-1], 1):
        if not raw:
            continue
        if raw.startswith("# HELP "):
            rest = raw[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ExpositionError(f"line {ln}: bad HELP name {name!r}")
            if name in helps:
                raise ExpositionError(f"line {ln}: duplicate HELP {name}")
            helps[name] = help_text
            continue
        if raw.startswith("# TYPE "):
            rest = raw[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ExpositionError(f"line {ln}: bad TYPE name {name!r}")
            if kind not in (COUNTER, GAUGE, HISTOGRAM, "summary", "untyped"):
                raise ExpositionError(f"line {ln}: bad TYPE kind {kind!r}")
            if name in types:
                raise ExpositionError(f"line {ln}: duplicate TYPE {name}")
            types[name] = kind
            fams[name] = {"type": kind, "help": helps.get(name, ""),
                          "samples": []}
            continue
        if raw.startswith("#"):
            continue  # plain comment
        m = _SAMPLE_RE.match(raw)
        if not m:
            raise ExpositionError(f"line {ln}: unparsable sample {raw!r}")
        name = m.group("name")
        labels: Dict[str, str] = {}
        label_text = m.group("labels")
        if label_text:
            pos = 0
            while pos < len(label_text):
                lm = _LABEL_RE.match(label_text, pos)
                if lm is None:
                    raise ExpositionError(
                        f"line {ln}: bad label syntax {label_text!r}"
                    )
                labels[lm.group(1)] = (
                    lm.group(2).replace('\\"', '"')
                    .replace("\\n", "\n").replace("\\\\", "\\")
                )
                pos = lm.end()
        vtext = m.group("value")
        try:
            value = float(vtext) if vtext not in ("+Inf", "-Inf", "NaN") else (
                math.inf if vtext == "+Inf"
                else (-math.inf if vtext == "-Inf" else math.nan)
            )
        except ValueError:
            raise ExpositionError(
                f"line {ln}: unparsable value {vtext!r}"
            ) from None
        family = _family_of(name, types)
        if family not in fams:
            raise ExpositionError(
                f"line {ln}: sample {name!r} precedes its TYPE declaration"
            )
        fams[family]["samples"].append((name, labels, value))

    # histogram invariants, per label set
    for family, ent in fams.items():
        if ent["type"] != HISTOGRAM:
            if ent["type"] == COUNTER:
                for name, labels, value in ent["samples"]:
                    if value < 0:
                        raise ExpositionError(
                            f"counter {name} negative: {value}"
                        )
            continue
        by_labelset: Dict[tuple, dict] = {}
        for name, labels, value in ent["samples"]:
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            slot = by_labelset.setdefault(
                key, {"buckets": [], "sum": None, "count": None}
            )
            if name.endswith("_bucket"):
                if "le" not in labels:
                    raise ExpositionError(f"{name}: bucket without le label")
                le = labels["le"]
                bound = math.inf if le == "+Inf" else float(le)
                slot["buckets"].append((bound, value))
            elif name.endswith("_sum"):
                slot["sum"] = value
            elif name.endswith("_count"):
                slot["count"] = value
        for key, slot in by_labelset.items():
            buckets = slot["buckets"]
            if not buckets or buckets[-1][0] != math.inf:
                raise ExpositionError(
                    f"{family}{dict(key)}: missing le=+Inf bucket"
                )
            bounds = [b for b, _ in buckets]
            if bounds != sorted(bounds):
                raise ExpositionError(
                    f"{family}{dict(key)}: bucket bounds out of order"
                )
            counts = [c for _, c in buckets]
            if any(b > a for a, b in zip(counts[1:], counts)):
                raise ExpositionError(
                    f"{family}{dict(key)}: bucket counts not monotone"
                )
            if slot["count"] is None or slot["sum"] is None:
                raise ExpositionError(
                    f"{family}{dict(key)}: missing _sum/_count"
                )
            if counts[-1] != slot["count"]:
                raise ExpositionError(
                    f"{family}{dict(key)}: +Inf bucket {counts[-1]} != "
                    f"count {slot['count']}"
                )
    return fams
