"""Incident flight recorder: capture the 30 seconds before things broke.

When an operator investigates an episode after the fact, the trace ring
has wrapped, /metrics shows the current (recovered) state, and the
provenance ledger has moved on.  The flight recorder freezes all three
at the moment of failure: on any SLO breach, breaker trip, or shed
burst (debounced by ``flightrec_min_interval_s``) it atomically writes
an incident bundle to ``flightrec_dir``:

    incident-<utc>-<seq>-<reason>/
        trace.json        Perfetto-loadable Chrome trace_event dump of
                          the span ring (obs/trace.py export_chrome)
        metrics.prom      full Prometheus text snapshot (parseable by
                          obs/exposition.parse_text_format)
        traffic.json      traffic-sketch snapshot (obs/sketch.py): top-K
                          heavy hitters, distinct-IP estimate, per-rule
                          pressure — what the flood looked like
        fabric.json       decision-fabric snapshot (when fabric_enabled):
                          peer table, hash-range ownership, last takeover
        provenance.json   last N decision-provenance records
        meta.json         reason, detail, timestamps, config hash,
                          health snapshot, SLO burn state

Bundles are written into a hidden ``.tmp`` directory and ``os.rename``d
into place, so a listed incident is always complete; the newest
``flightrec_keep`` are retained, older ones pruned.  ``GET
/debug/incidents`` lists and serves bundles (httpapi/server.py).

Trigger sites call the module-level ``notify(reason, detail)`` — one
None-check when no recorder is installed, so the drain thread, the
breaker's on_trip hook, and the scheduler's shed path pay nothing in
the common case.  Capture itself is synchronous but debounced (at most
one bundle per ``min_interval_s``) and swallows every exception: a
recorder bug must never take down the path that tripped it.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)

_SLUG_OK = "abcdefghijklmnopqrstuvwxyz0123456789-"


def _slug(reason: str) -> str:
    s = "".join(
        c if c in _SLUG_OK else "-" for c in (reason or "incident").lower()
    )
    return s.strip("-")[:48] or "incident"


class FlightRecorder:
    def __init__(
        self,
        directory: str,
        min_interval_s: float = 60.0,
        keep: int = 16,
        provenance_tail: int = 256,
        metrics_text_fn: Optional[Callable[[], str]] = None,
        config_hash_fn: Optional[Callable[[], str]] = None,
        health=None,
        slo_getter: Optional[Callable[[], object]] = None,
        traffic_fn: Optional[Callable[[], Optional[dict]]] = None,
        fabric_fn: Optional[Callable[[], Optional[dict]]] = None,
        fleet_capture_fn: Optional[
            Callable[[str], "dict[str, dict[str, str]]"]
        ] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.directory = directory
        self.min_interval_s = max(0.0, float(min_interval_s))
        self.keep = max(1, int(keep))
        self.provenance_tail = max(1, int(provenance_tail))
        self._metrics_text_fn = metrics_text_fn
        self._config_hash_fn = config_hash_fn
        self._health = health
        self._slo_getter = slo_getter
        self._traffic_fn = traffic_fn
        self._fabric_fn = fabric_fn
        self._fleet_capture_fn = fleet_capture_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._last_capture = float("-inf")
        self._seq = 0
        self.incident_count = 0
        os.makedirs(directory, exist_ok=True)

    # ---- capture ----

    def notify(self, reason: str, detail: str = "") -> Optional[str]:
        """Debounced capture trigger; returns the bundle name when one
        was captured, None when debounced or on failure."""
        with self._lock:
            now = self._clock()
            if now - self._last_capture < self.min_interval_s:
                return None
            self._last_capture = now
            self._seq += 1
            seq = self._seq
        try:
            return self._capture(reason, detail, seq)
        except Exception:  # noqa: BLE001 — a recorder bug must never propagate
            log.exception("flight recorder capture failed (reason=%s)", reason)
            return None

    def _capture(self, reason: str, detail: str, seq: int) -> str:
        from banjax_tpu.obs import provenance, trace

        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        name = f"incident-{stamp}-{seq:03d}-{_slug(reason)}"
        tmp = os.path.join(self.directory, f".{name}.tmp")
        final = os.path.join(self.directory, name)
        os.makedirs(tmp, exist_ok=True)

        files = {}
        files["trace.json"] = json.dumps(
            trace.get_tracer().export_chrome(), separators=(",", ":")
        )
        if self._metrics_text_fn is not None:
            try:
                files["metrics.prom"] = self._metrics_text_fn()
            except Exception as e:  # noqa: BLE001 — partial bundle beats none
                files["metrics.prom"] = f"# capture failed: {e}\n"
        # traffic snapshot (obs/sketch.py): what the flood looked like —
        # heavy hitters, distinct-source estimate, per-rule pressure —
        # as of THIS incident (a forced pull, not the last sampling tick)
        traffic: Optional[dict] = None
        if self._traffic_fn is not None:
            try:
                traffic = self._traffic_fn()
            except Exception as e:  # noqa: BLE001 — partial bundle beats none
                traffic = {"enabled": False, "error": str(e)}
        files["traffic.json"] = json.dumps(
            traffic if traffic is not None else {"enabled": False},
            indent=1,
        )
        # fabric snapshot (fabric/router.describe): peer table, hash-
        # range ownership, last takeover — a shard-failure capture is
        # self-describing without asking the survivors
        if self._fabric_fn is not None:
            fabric: Optional[dict] = None
            try:
                fabric = self._fabric_fn()
            except Exception as e:  # noqa: BLE001 — partial bundle beats none
                fabric = {"enabled": False, "error": str(e)}
            files["fabric.json"] = json.dumps(
                fabric if fabric is not None else {"enabled": False},
                indent=1, default=str,
            )
        files["provenance.json"] = json.dumps(
            {
                "records": provenance.get_ledger().tail(self.provenance_tail),
                "counters": {
                    f"{src}/{dec}": v
                    for (src, dec), v in sorted(
                        provenance.get_ledger().counters().items()
                    )
                },
            },
            indent=1,
        )
        # cluster incident capture (obs/fleet.py capture_fleet): every
        # ALIVE peer contributes its own trace/metrics/provenance/fabric
        # snapshot under peers/<node_id>/ — a cross-shard episode reads
        # as ONE bundle instead of N /debug/incidents to correlate.
        # Fan-out happens before meta.json so the manifest lists the
        # peer tree; a peer that cannot answer appears as error.txt.
        peer_files: dict = {}
        if self._fleet_capture_fn is not None:
            try:
                raw = self._fleet_capture_fn(name) or {}
            except Exception:  # noqa: BLE001 — fleet capture must not sink the bundle
                raw = {}
            for nid, pf in raw.items():
                nid_s = os.path.basename(str(nid))
                if not nid_s or nid_s.startswith("."):
                    continue
                clean = {}
                for fname, content in (pf or {}).items():
                    fname_s = os.path.basename(str(fname))
                    if fname_s and not fname_s.startswith("."):
                        clean[fname_s] = str(content)
                if clean:
                    peer_files[nid_s] = clean

        slo = self._slo_getter() if self._slo_getter else None
        meta = {
            "reason": reason,
            "detail": detail,
            "captured_unix": time.time(),
            "captured_monotonic": time.monotonic(),
            "config_hash": (
                self._config_hash_fn() if self._config_hash_fn else ""
            ),
            "health": self._health.snapshot() if self._health else None,
            "slo": slo.snapshot() if slo is not None else None,
            "files": sorted(files) + ["meta.json"] + sorted(
                f"peers/{nid}/{fname}"
                for nid, pf in peer_files.items() for fname in pf
            ),
        }
        files["meta.json"] = json.dumps(meta, indent=1)

        for fname, content in files.items():
            with open(os.path.join(tmp, fname), "w", encoding="utf-8") as f:
                f.write(content)
        for nid, pf in peer_files.items():
            pdir = os.path.join(tmp, "peers", nid)
            os.makedirs(pdir, exist_ok=True)
            for fname, content in pf.items():
                with open(
                    os.path.join(pdir, fname), "w", encoding="utf-8"
                ) as f:
                    f.write(content)
        os.rename(tmp, final)  # atomic publish: listed == complete
        with self._lock:
            self.incident_count += 1
        self._prune()
        log.warning("flight recorder captured incident %s (%s)", name, reason)
        return name

    def _prune(self) -> None:
        try:
            entries = sorted(
                e for e in os.listdir(self.directory)
                if e.startswith("incident-")
            )
            for stale in entries[: max(0, len(entries) - self.keep)]:
                shutil.rmtree(
                    os.path.join(self.directory, stale), ignore_errors=True
                )
            # a crash mid-capture can strand a .tmp dir; sweep old ones
            for e in os.listdir(self.directory):
                if e.startswith(".incident-") and e.endswith(".tmp"):
                    age = time.time() - os.path.getmtime(
                        os.path.join(self.directory, e)
                    )
                    if age > 3600:
                        shutil.rmtree(
                            os.path.join(self.directory, e),
                            ignore_errors=True,
                        )
        except OSError:
            pass

    # ---- queries (the /debug/incidents surface) ----

    def list_incidents(self) -> List[dict]:
        """Newest-first bundle manifests."""
        out = []
        try:
            entries = sorted(
                (e for e in os.listdir(self.directory)
                 if e.startswith("incident-")),
                reverse=True,
            )
        except OSError:
            return []
        for name in entries:
            entry = {"name": name}
            try:
                with open(
                    os.path.join(self.directory, name, "meta.json"),
                    encoding="utf-8",
                ) as f:
                    meta = json.load(f)
                entry.update({
                    "reason": meta.get("reason", ""),
                    "captured_unix": meta.get("captured_unix"),
                    "files": meta.get("files", []),
                })
            except (OSError, ValueError):
                entry["reason"] = "unreadable"
            out.append(entry)
        return out

    def read_file(self, name: str, fname: str) -> Optional[bytes]:
        """One bundle file's bytes; None when absent.  Both components
        are validated — no path traversal.  ``fname`` may be a top-level
        bundle file or a fleet capture path ``peers/<node_id>/<file>``
        (exactly three components, each a clean basename)."""
        if name != os.path.basename(name) or not name.startswith("incident-"):
            return None
        parts = fname.split("/")
        if len(parts) == 3 and parts[0] == "peers":
            parts = parts[1:]
        elif len(parts) != 1:
            return None
        for part in parts:
            if (not part or part != os.path.basename(part)
                    or part in (".", "..") or part.startswith(".")):
                return None
        path = os.path.join(self.directory, name, fname)
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            return None


# ---- module-level trigger hook --------------------------------------------
#
# Trigger sites (scheduler shed, breaker on_trip, SLO on_breach) call
# notify() unconditionally; with no recorder installed it is one
# None-check.  App-owned, not config-owned: cli.BanjaxApp installs its
# recorder at startup and uninstalls on shutdown so in-process tests
# never cross-contaminate.

_recorder: Optional[FlightRecorder] = None


def install(recorder: Optional[FlightRecorder]) -> None:
    global _recorder
    _recorder = recorder


def installed() -> Optional[FlightRecorder]:
    return _recorder


def notify(reason: str, detail: str = "") -> Optional[str]:
    rec = _recorder
    if rec is None:
        return None
    return rec.notify(reason, detail)
