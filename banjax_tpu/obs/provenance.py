"""Decision provenance ledger: why did banjax ban/challenge this IP?

The reference engine's whole value is *attributable* decisions from four
sources (PAPER.md §0): static config lists, the regex rate limiter,
Kafka commands from Baskerville, and repeated challenge failures.  PR 5
made the pipeline visible (spans, histograms) but an operator under
attack still couldn't answer the first question they ask: what exactly
made this IP blocked?  This module is the attribution layer — every
Decision insertion (and every expiry) appends one fixed-size record into
a lock-cheap per-source ring, queryable by IP through
``GET /decisions/explain?ip=…``.

Design constraints, in the trace recorder's mold (obs/trace.py):

  * **Off ≈ free.**  ``provenance_enabled`` gates every record path on a
    single attribute check.  On is the default (unlike tracing): records
    fire only on decision events — bans, list hits, expiries — which are
    orders of magnitude rarer than log lines, and bench.py
    ``--provenance-overhead`` banks the measured on/off delta.
  * **On = lock-cheap.**  One lock acquisition per record, a tuple store
    into a preallocated per-source ring (oldest overwritten), and one
    counter bump for the ``banjax_decision_inserts_total{source,
    decision}`` family.  Nothing is formatted per record; ``explain()``
    pays the formatting cost at query time.
  * **Passive by construction.**  Recording reads its inputs and writes
    only ledger-private state — the differential suite
    (tests/differential/test_provenance_differential.py) proves the
    enabled ledger is byte-identical on ban-log output.

Record fields (fixed tuple, one per insertion):
    ip, decision (string form), source, rule name, rule index,
    window hit count at fire time, trace id of the admitting batch
    (from the ambient span when the insert happens on a traced drain
    thread), monotonic timestamp, wall timestamp, origin node id,
    origin trace id.

The last two fields are the fleet join (PR 20): when the banned line
was tailed on ANOTHER node and forwarded here by the fabric, the
installed origin resolver (obs/fleet.py OriginIndex, fed by the
owner-side chunk handlers) maps the IP back to the forwarding node and
the trace id its router allocated at admission — so
``/decisions/explain`` on the owner shard answers with the origin
batch's trace id, joinable against the origin node's /debug/trace
ring.  Locally-tailed bans leave them empty ("" / 0) and the explain
payload omits the keys entirely.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from banjax_tpu.obs import trace

DEFAULT_RING_SIZE = 2048

# the decision sources the reference attributes bans to (PAPER.md §0),
# plus the ledger-only lifecycle source for expiries
SOURCE_STATIC = "static_list"
SOURCE_UA = "ua_list"
SOURCE_RATE_LIMIT = "rate_limit"
SOURCE_KAFKA = "kafka"
SOURCE_CHALLENGE = "challenge_failure"
SOURCE_EXPIRY = "expiry"

SOURCES = (
    SOURCE_STATIC,
    SOURCE_UA,
    SOURCE_RATE_LIMIT,
    SOURCE_KAFKA,
    SOURCE_CHALLENGE,
    SOURCE_EXPIRY,
)


class ProvenanceLedger:
    """Process-wide decision ledger; every method is thread-safe, and
    when ``enabled`` is False each one is a single attribute check."""

    def __init__(self, enabled: bool = True,
                 ring_size: int = DEFAULT_RING_SIZE):
        self.enabled = bool(enabled)
        self.ring_size = max(16, int(ring_size))
        # per-source ring + its own lock: sources fire from different
        # threads (drain thread, request handlers, kafka reader, the
        # sweeper) and must not contend on one global lock
        self._rings: Dict[str, List[Optional[tuple]]] = {
            s: [None] * self.ring_size for s in SOURCES
        }
        self._ns: Dict[str, int] = {s: 0 for s in SOURCES}
        self._locks: Dict[str, threading.Lock] = {
            s: threading.Lock() for s in SOURCES
        }
        self._counter_lock = threading.Lock()
        # (source, decision-string) -> monotone insert count; the
        # banjax_decision_inserts_total{source,decision} family
        self._counters: Dict[Tuple[str, str], int] = {}

    # ---- recording ----

    def record(self, source: str, ip: str, decision, rule: str = "",
               rule_index: int = -1, hits: Optional[int] = None,
               trace_id: Optional[int] = None) -> None:
        """Append one decision record.

        ``decision`` may be a Decision enum or string; stored in string
        form so the ledger never imports the decisions package.
        ``trace_id`` defaults to the ambient span's trace id — a ban
        fired on a traced pipeline drain thread is attributed to the
        admitting batch with no plumbing at the call site."""
        if not self.enabled:
            return
        if source not in self._rings:
            source = SOURCE_STATIC  # never raise from a record path
        if trace_id is None:
            trace_id = trace.current_trace_id()
        decision_s = str(decision)
        origin_node, origin_trace = "", 0
        resolver = _origin_resolver
        if resolver is not None:
            try:
                origin = resolver(ip)
                if origin:
                    origin_node, origin_trace = str(origin[0]), int(origin[1])
            except Exception:  # resolution must never break a record path
                pass
        rec = (ip, decision_s, source, rule, int(rule_index), hits,
               int(trace_id), time.monotonic(), time.time(),
               origin_node, origin_trace)
        lock = self._locks[source]
        with lock:
            n = self._ns[source]
            self._rings[source][n % self.ring_size] = rec
            self._ns[source] = n + 1
        key = (source, decision_s)
        with self._counter_lock:
            self._counters[key] = self._counters.get(key, 0) + 1

    # ---- queries ----

    def _source_records(self, source: str) -> List[tuple]:
        """One source's ring, oldest-first."""
        with self._locks[source]:
            n = self._ns[source]
            ring = self._rings[source]
            if n <= self.ring_size:
                recs = list(ring[:n])
            else:
                cut = n % self.ring_size
                recs = ring[cut:] + ring[:cut]
        return [r for r in recs if r is not None]

    @staticmethod
    def _to_dict(rec: tuple) -> dict:
        (ip, decision, source, rule, rule_index, hits, tid, t_mono,
         t_wall, origin_node, origin_trace) = rec
        out = {
            "ip": ip,
            "decision": decision,
            "source": source,
            "rule": rule,
            "rule_index": rule_index,
            "hits": hits,
            "trace_id": tid,
            "t_monotonic": round(t_mono, 6),
            "time_unix": round(t_wall, 6),
        }
        if origin_node:
            out["origin_node"] = origin_node
            out["origin_trace_id"] = origin_trace
        return out

    def explain(self, ip: str) -> List[dict]:
        """Full ledger history for one IP across every source, oldest
        first (the /decisions/explain payload)."""
        out = []
        for source in SOURCES:
            out.extend(r for r in self._source_records(source) if r[0] == ip)
        out.sort(key=lambda r: r[7])  # monotonic timestamp
        return [self._to_dict(r) for r in out]

    def tail(self, n: int = 256) -> List[dict]:
        """Newest ``n`` records across all sources, oldest-first — the
        flight recorder's provenance capture."""
        recs: List[tuple] = []
        for source in SOURCES:
            recs.extend(self._source_records(source))
        recs.sort(key=lambda r: r[7])
        return [self._to_dict(r) for r in recs[-max(0, int(n)):]]

    def counters(self) -> Dict[Tuple[str, str], int]:
        """{(source, decision): total inserts} — the exposition family."""
        with self._counter_lock:
            return dict(self._counters)

    def total_records(self) -> int:
        return sum(self._ns[s] for s in SOURCES)


# ---- process-wide ledger ---------------------------------------------------

_ledger = ProvenanceLedger(enabled=True)

# ip -> (origin_node_id, origin_trace_id) | None: installed by the
# fabric wiring (obs/fleet.py OriginIndex.resolve) so forwarded-line
# bans carry their cross-host admission attribution; survives a
# configure() ledger swap
_origin_resolver: Optional[Callable[[str], Optional[Tuple[str, int]]]] = None


def set_origin_resolver(
    fn: Optional[Callable[[str], Optional[Tuple[str, int]]]],
) -> None:
    global _origin_resolver
    _origin_resolver = fn


def get_ledger() -> ProvenanceLedger:
    return _ledger


def configure(enabled: bool = True,
              ring_size: int = DEFAULT_RING_SIZE) -> ProvenanceLedger:
    """(Re)configure the process ledger — called by cli.BanjaxApp from
    config (`provenance_enabled`, `provenance_ring_size`) and by tests.
    Swaps the singleton so a disabled ledger keeps the one-attribute-
    check fast path."""
    global _ledger
    _ledger = ProvenanceLedger(enabled=enabled, ring_size=ring_size)
    return _ledger


# module-level delegates: call sites read the CURRENT singleton each time

def enabled() -> bool:
    return _ledger.enabled


def record(source: str, ip: str, decision, rule: str = "",
           rule_index: int = -1, hits: Optional[int] = None,
           trace_id: Optional[int] = None) -> None:
    _ledger.record(source, ip, decision, rule, rule_index, hits, trace_id)
