"""Pipeline tracing: a lock-cheap, ring-buffered span recorder.

The reference banjax exposes a 29-second status line and nothing else;
this reproduction has four overlapped pipeline stages, a fused
two-program device path, sharded encode workers, and a resolve-ahead
drain — none of it visible per-batch.  This module is the Dapper-style
propagation layer: every admission batch gets a trace id at the
scheduler's take-time and carries it through encode (per-shard child
spans), submit (program-A dispatch, mesh shard submits), collect, and
drain (program-B commit, effector replay), with breaker/fallback/shed
events as instant annotations.

Design constraints, in order:

  * **Off ≈ free.**  `trace_enabled` defaults false; every record path
    starts with one attribute check and returns a shared no-op object —
    no allocation, no lock, no clock read.  bench.py --trace-overhead
    banks the measured on/off delta (BENCH_trace_overhead.json).
  * **On = lock-cheap.**  A completed span is one lock acquisition and
    a handful of stores into a preallocated ring (`trace_ring_size`
    slots, oldest overwritten).  Nothing is formatted or allocated per
    span beyond the record tuple; export pays the formatting cost.
  * **Cross-thread spans are explicit.**  A batch's root span begins on
    the encode thread and ends on the drain thread, so the root rides
    the batch object (`begin`/`end`), while single-thread stage spans
    use the context-manager form, which also maintains a thread-local
    ambient parent — nested spans recorded inside the matcher (program
    B, effector replay, mesh shard pulls) auto-parent without the
    matcher knowing about the scheduler's ids.

Export: `export_chrome()` renders the ring as Chrome `trace_event`
JSON — load the `/debug/trace` dump straight into Perfetto
(https://ui.perfetto.dev) or chrome://tracing.  Span args become event
`args`; thread names are emitted as metadata events so each pipeline
stage gets its own named track.

JAX bridge: with `trace_jax_annotations` on, context-manager spans also
enter `jax.profiler.TraceAnnotation(name)` so host spans line up with
the XLA/TPU device timeline whenever a profiler session (the
/debug/jax/trace route, or an external `jax.profiler.start_trace`) is
active; the annotations are no-ops otherwise.  The root batch span
additionally wraps its submit stage in `StepTraceAnnotation` with the
trace id as the step number, which Perfetto/xprof group per step.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional

DEFAULT_RING_SIZE = 4096

# the five pipeline stage span names the acceptance test asserts on
STAGES = ("admission", "encode", "encode-shard", "submit", "collect", "drain")


class _NoopSpan:
    """Shared do-nothing span: returned whenever recording is off (or the
    caller has no trace), so call sites never branch on enablement."""

    __slots__ = ()
    trace_id = 0
    span_id = 0

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def note(self, key: str, value) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class Span:
    """One live span.  Mutable while open; recorded into the ring on
    `end()`/`__exit__`.  `note()` attaches args visible in the export
    (breaker state, fallback reasons, row counts)."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "t0", "args", "_thread_name", "_jax_ctx")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 parent_id: int, args: Optional[dict]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = next(tracer._ids)
        self.parent_id = parent_id
        self.name = name
        self.args = dict(args) if args else None
        self.t0 = time.perf_counter()
        self._thread_name = threading.current_thread().name
        self._jax_ctx = None

    def note(self, key: str, value) -> None:
        if self.args is None:
            self.args = {}
        self.args[key] = value

    # -- context-manager form (single-thread spans; maintains the ambient
    # parent stack and the optional jax annotation) --

    def __enter__(self) -> "Span":
        stack = self.tracer._ambient.__dict__.setdefault("stack", [])
        stack.append(self)
        if self.tracer.jax_annotations:
            self._jax_ctx = self.tracer._enter_jax(self.name)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001 — tracing must never raise
                pass
            self._jax_ctx = None
        stack = self.tracer._ambient.__dict__.get("stack")
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is not None:
            self.note("error", repr(exc))
        self.tracer.end(self)


class Tracer:
    """Process-wide span recorder.  All public methods are safe to call
    from any thread; when `enabled` is False every one of them is a
    single attribute check."""

    def __init__(self, enabled: bool = False,
                 ring_size: int = DEFAULT_RING_SIZE,
                 jax_annotations: bool = False):
        self.enabled = bool(enabled)
        self.jax_annotations = bool(jax_annotations)
        self.ring_size = max(16, int(ring_size))
        self._lock = threading.Lock()
        self._ring: List[Optional[tuple]] = [None] * self.ring_size
        self._n = 0  # monotone record count; ring index = _n % ring_size
        self._dropped = 0
        self._ids = itertools.count(1)
        self._traces = itertools.count(1)
        self._ambient = threading.local()
        self._epoch = time.perf_counter()
        self._epoch_wall = time.time()

    # ---- recording ----

    def new_trace(self) -> int:
        """Allocate a trace id for one admission batch; 0 when disabled
        (0 propagates as 'don't record' through every span call)."""
        if not self.enabled:
            return 0
        return next(self._traces)

    def begin(self, name: str, trace_id: int, parent: int = 0,
              args: Optional[dict] = None):
        """Open a span explicitly (cross-thread form: `end()` may run on
        a different thread).  Does NOT touch the ambient stack."""
        if not self.enabled or not trace_id:
            return NOOP_SPAN
        return Span(self, name, trace_id, parent, args)

    def end(self, span) -> None:
        """Close a span opened with `begin()` (or via __exit__)."""
        if span is NOOP_SPAN or not isinstance(span, Span):
            return
        dur_us = (time.perf_counter() - span.t0) * 1e6
        t0_us = (span.t0 - self._epoch) * 1e6
        rec = (span.trace_id, span.span_id, span.parent_id, span.name,
               t0_us, dur_us, span._thread_name, span.args)
        with self._lock:
            self._ring[self._n % self.ring_size] = rec
            self._n += 1

    def span(self, name: str, trace_id: Optional[int] = None,
             parent: Optional[int] = None, args: Optional[dict] = None):
        """Context-manager span.  With no explicit ids it parents under
        the thread's current ambient span — and records nothing when
        there is none, so instrumented library code (matcher, mesh) is
        inert outside a traced pipeline batch."""
        if not self.enabled:
            return NOOP_SPAN
        if trace_id is None or parent is None:
            stack = self._ambient.__dict__.get("stack")
            top = stack[-1] if stack else None
            if trace_id is None:
                if top is None:
                    return NOOP_SPAN
                trace_id = top.trace_id
            if parent is None:
                parent = top.span_id if top is not None else 0
        if not trace_id:
            return NOOP_SPAN
        return Span(self, name, trace_id, parent, args)

    def instant(self, name: str, args: Optional[dict] = None,
                trace_id: int = 0) -> None:
        """Point event (shed, breaker trip, fallback): zero duration,
        recorded even without a trace id so stream-level events (an
        admission-buffer shed belongs to no single batch) still land in
        the ring."""
        if not self.enabled:
            return
        t0_us = (time.perf_counter() - self._epoch) * 1e6
        rec = (trace_id, next(self._ids), 0, name, t0_us, None,
               threading.current_thread().name, dict(args) if args else None)
        with self._lock:
            self._ring[self._n % self.ring_size] = rec
            self._n += 1

    def current_trace_id(self) -> int:
        """Trace id of the thread's ambient span (0 when none / off) —
        lets passive observers (the provenance ledger) attribute an
        effect to the admitting batch without any id plumbing."""
        if not self.enabled:
            return 0
        stack = self._ambient.__dict__.get("stack")
        return stack[-1].trace_id if stack else 0

    # ---- export ----

    def snapshot(self, clear: bool = False) -> List[dict]:
        """Ring contents oldest-first as plain dicts (tests, debugging).

        ``clear=True`` snapshots AND empties the ring in one lock
        section: a span recorded between a separate dump and clear would
        be silently dropped, and two concurrent clearing dumps could
        each report the same span — /debug/trace?clear=1 uses this
        atomic form (tests/unit/test_trace.py hammers it)."""
        with self._lock:
            n = self._n
            if n <= self.ring_size:
                recs = [r for r in self._ring[:n]]
            else:
                cut = n % self.ring_size
                recs = self._ring[cut:] + self._ring[:cut]
            if clear:
                self._ring = [None] * self.ring_size
                self._n = 0
        out = []
        for r in recs:
            if r is None:
                continue
            tid, sid, pid, name, t0_us, dur_us, thread, args = r
            out.append({
                "trace_id": tid, "span_id": sid, "parent_id": pid,
                "name": name, "t0_us": t0_us, "dur_us": dur_us,
                "thread": thread, "args": args or {},
            })
        return out

    def export_chrome(self, clear: bool = False) -> dict:
        """Chrome trace_event JSON (Perfetto / chrome://tracing).

        Complete ('X') events for spans, instant ('i') events for
        annotations; one virtual pid, one tid per recorded thread name
        with 'M' metadata naming the track.  Span/trace ids ride in
        args so Perfetto's query surface can join parent/child.
        ``clear=True`` drains the ring atomically with the read (the
        /debug/trace?clear=1 contract — no span dropped or duplicated
        against a concurrent scrape)."""
        spans = self.snapshot(clear=clear)
        tids: Dict[str, int] = {}
        events = []
        pid = os.getpid()
        for s in spans:
            tid = tids.setdefault(s["thread"], len(tids) + 1)
            args = dict(s["args"])
            args["trace_id"] = s["trace_id"]
            args["span_id"] = s["span_id"]
            if s["parent_id"]:
                args["parent_span_id"] = s["parent_id"]
            ev = {
                "name": s["name"],
                "cat": "banjax",
                "ph": "X" if s["dur_us"] is not None else "i",
                "ts": round(s["t0_us"], 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
            if s["dur_us"] is not None:
                ev["dur"] = round(s["dur_us"], 3)
            else:
                ev["s"] = "g"  # global-scope instant
            events.append(ev)
        meta = [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": thread}}
            for thread, tid in tids.items()
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "banjax-tpu trace ring",
                "ring_size": self.ring_size,
                "recorded": self._n,
                "epoch_unix": self._epoch_wall,
            },
        }

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.ring_size
            self._n = 0

    # ---- jax profiler bridge ----

    def _enter_jax(self, name: str):
        try:
            import jax

            ctx = jax.profiler.TraceAnnotation(name)
            ctx.__enter__()
            return ctx
        except Exception:  # noqa: BLE001 — the bridge is best-effort
            return None

    def step_annotation(self, trace_id: int):
        """StepTraceAnnotation for one batch's device submit (xprof
        groups device work per step).  Returns a context manager; a
        no-op one when the bridge is off or jax is unavailable."""
        if not (self.enabled and self.jax_annotations and trace_id):
            return NOOP_SPAN
        try:
            import jax

            return jax.profiler.StepTraceAnnotation(
                "banjax-batch", step_num=trace_id
            )
        except Exception:  # noqa: BLE001
            return NOOP_SPAN


# ---- process-wide tracer -------------------------------------------------

_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _tracer


def configure(enabled: bool, ring_size: int = DEFAULT_RING_SIZE,
              jax_annotations: bool = False) -> Tracer:
    """(Re)configure the process tracer — called by cli.BanjaxApp from
    config (`trace_enabled`, `trace_ring_size`, `trace_jax_annotations`)
    and by tests.  Swaps the module singleton so a disabled tracer keeps
    its zero-cost fast path (no indirection through a config object)."""
    global _tracer
    _tracer = Tracer(enabled=enabled, ring_size=ring_size,
                     jax_annotations=jax_annotations)
    return _tracer


# module-level delegates: call sites read the CURRENT singleton each time
# so a configure() mid-run (tests, SIGHUP) takes effect everywhere

def enabled() -> bool:
    return _tracer.enabled


def new_trace() -> int:
    return _tracer.new_trace()


def begin(name: str, trace_id: int, parent: int = 0,
          args: Optional[dict] = None):
    return _tracer.begin(name, trace_id, parent, args)


def end(span) -> None:
    _tracer.end(span)


def span(name: str, trace_id: Optional[int] = None,
         parent: Optional[int] = None, args: Optional[dict] = None):
    return _tracer.span(name, trace_id, parent, args)


def instant(name: str, args: Optional[dict] = None, trace_id: int = 0) -> None:
    _tracer.instant(name, args, trace_id)


def current_trace_id() -> int:
    return _tracer.current_trace_id()


def step_annotation(trace_id: int):
    return _tracer.step_annotation(trace_id)
