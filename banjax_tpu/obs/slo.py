"""SLO burn-rate engine: multi-window error-budget accounting.

ROADMAP item 5 (scenario harness + soak) needs SLO definitions and
per-episode evidence to judge breaker/shed defaults against; this module
turns the accumulators PR 5 already exposes — the fixed-bucket latency
histogram, the pipeline shed/stale counters, the breaker — into the
standard SRE multi-window burn-rate signal:

    burn_rate = (observed bad fraction over a window)
              / (the SLO's error-budget fraction)

evaluated over a fast (5 m) and a slow (1 h) window.  1.0 means the
error budget is being consumed exactly at the sustainable rate; an SLO
is **breached** when every window burns ≥ 1.0 — the fast window catches
the spike, the slow window keeps a 30-second blip from paging.

Declared SLOs (config keys in parentheses):

  * ``batch_latency`` — fraction of matcher batches inside the latency
    budget (``pipeline_latency_budget_ms``), target
    ``slo_batch_latency_target``.  Evaluated from the cumulative
    ``banjax_batch_latency_seconds`` histogram buckets: the count at the
    smallest bucket bound ≥ the budget is "good" — no new accumulator,
    no destructive read.
  * ``shed_ratio`` — (shed + drain-error) lines per admitted line vs
    ``slo_shed_ratio_max``.
  * ``stale_ratio`` — drain-staleness drops per processed line vs
    ``slo_stale_ratio_max``.
  * ``breaker_open`` — breaker-OPEN seconds per wall second vs
    ``slo_breaker_open_ratio_max`` (CircuitBreaker.open_seconds_total).
  * ``budget_trips`` — matcher latency-budget trips per batch vs
    ``slo_budget_trip_ratio_max`` (the ROADMAP "derived budget never
    validated/observed" counter, banjax_matcher_budget_trips_total).

Every input is a **non-destructive** cumulative read (peek-style), so
the engine can sample at any cadence alongside the 29 s line and any
number of scrapers.  Samples are (timestamp, counters) tuples in a
bounded deque; a window's burn is the delta between now and the oldest
sample inside the window (when the engine is younger than the window,
the available span substitutes — standard young-service behavior).

Exposition: ``banjax_slo_burn_rate{slo,window}`` gauges and the one-hot
``banjax_slo_breached{slo}`` gauge (obs/exposition.py).  On a breach
transition the engine fires ``on_breach`` — cli.BanjaxApp wires that to
the incident flight recorder (obs/flightrec.py).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

# (label, seconds): the classic fast/slow alerting pair
WINDOWS = (("5m", 300.0), ("1h", 3600.0))

SLO_BATCH_LATENCY = "batch_latency"
SLO_SHED = "shed_ratio"
SLO_STALE = "stale_ratio"
SLO_BREAKER_OPEN = "breaker_open"
SLO_BUDGET_TRIPS = "budget_trips"

SLO_NAMES = (
    SLO_BATCH_LATENCY,
    SLO_SHED,
    SLO_STALE,
    SLO_BREAKER_OPEN,
    SLO_BUDGET_TRIPS,
)


class SloEngine:
    """Samples cumulative counters and evaluates windowed burn rates.

    All inputs are injected getters so the engine never holds a stale
    matcher/pipeline across a SIGHUP swap; the clock is injectable for
    deterministic tests."""

    def __init__(
        self,
        matcher_getter: Optional[Callable[[], object]] = None,
        pipeline_getter: Optional[Callable[[], object]] = None,
        batch_budget_s_fn: Optional[Callable[[], float]] = None,
        batch_latency_target: float = 0.99,
        shed_ratio_max: float = 0.001,
        stale_ratio_max: float = 0.001,
        breaker_open_ratio_max: float = 0.01,
        budget_trip_ratio_max: float = 0.01,
        on_breach: Optional[Callable[[str, dict], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        max_samples: int = 512,
        collect_fn: Optional[Callable[[], Dict[str, float]]] = None,
    ):
        if not 0.0 < batch_latency_target < 1.0:
            raise ValueError(
                f"batch_latency_target must be in (0, 1), got "
                f"{batch_latency_target}"
            )
        for name, v in (
            ("shed_ratio_max", shed_ratio_max),
            ("stale_ratio_max", stale_ratio_max),
            ("breaker_open_ratio_max", breaker_open_ratio_max),
            ("budget_trip_ratio_max", budget_trip_ratio_max),
        ):
            if v <= 0:
                raise ValueError(f"{name} must be positive, got {v}")
        self._matcher_getter = matcher_getter
        self._pipeline_getter = pipeline_getter
        self._batch_budget_s_fn = batch_budget_s_fn
        self.batch_latency_target = batch_latency_target
        self.shed_ratio_max = shed_ratio_max
        self.stale_ratio_max = stale_ratio_max
        self.breaker_open_ratio_max = breaker_open_ratio_max
        self.budget_trip_ratio_max = budget_trip_ratio_max
        self._on_breach = on_breach
        self._collect_fn = collect_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=max(8, int(max_samples)))
        self._burn: Dict[str, Dict[str, float]] = {}
        self._breached: Dict[str, bool] = {s: False for s in SLO_NAMES}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, config, matcher_getter=None, pipeline_getter=None,
                    on_breach=None) -> "SloEngine":
        budget_ms = getattr(config, "pipeline_latency_budget_ms", 250.0)
        return cls(
            matcher_getter=matcher_getter,
            pipeline_getter=pipeline_getter,
            batch_budget_s_fn=lambda: budget_ms / 1e3,
            batch_latency_target=getattr(
                config, "slo_batch_latency_target", 0.99
            ),
            shed_ratio_max=getattr(config, "slo_shed_ratio_max", 0.001),
            stale_ratio_max=getattr(config, "slo_stale_ratio_max", 0.001),
            breaker_open_ratio_max=getattr(
                config, "slo_breaker_open_ratio_max", 0.01
            ),
            budget_trip_ratio_max=getattr(
                config, "slo_budget_trip_ratio_max", 0.01
            ),
            on_breach=on_breach,
        )

    # ---- collection (non-destructive reads only) ----

    def _collect(self) -> Dict[str, float]:
        # fleet mode (obs/fleet.py FleetScraper.fleet_collect): the
        # injected collector replaces the local getters entirely — the
        # engine burns over CLUSTER counter sums with identical window
        # mechanics, so fleet and node SLOs stay comparable
        if self._collect_fn is not None:
            try:
                return {
                    k: float(v) for k, v in (self._collect_fn() or {}).items()
                }
            except Exception:  # noqa: BLE001 — a collector bug must not stop sampling
                return {}
        vals: Dict[str, float] = {}
        matcher = self._matcher_getter() if self._matcher_getter else None
        if matcher is not None:
            stats = getattr(matcher, "stats", None)
            hist = getattr(stats, "batch_latency_hist", None)
            if hist is not None:
                bounds, cum, _sum, count = hist.snapshot()
                vals["batches_total"] = count
                budget_s = 0.0
                if self._batch_budget_s_fn is not None:
                    try:
                        budget_s = max(0.0, float(self._batch_budget_s_fn()))
                    except Exception:  # noqa: BLE001 — a budget bug must not stop sampling
                        budget_s = 0.0
                if budget_s > 0:
                    # good = observations ≤ the smallest bucket bound that
                    # covers the budget (cumulative counts, so one index)
                    idx = bisect.bisect_left(bounds, budget_s)
                    vals["batches_in_budget"] = (
                        cum[idx] if idx < len(bounds) else count
                    )
                else:
                    vals["batches_in_budget"] = count  # no budget = all good
            vals["budget_trips"] = float(getattr(matcher, "budget_trips", 0))
            breaker = getattr(matcher, "breaker", None)
            if breaker is not None and hasattr(breaker, "open_seconds_total"):
                vals["breaker_open_s"] = breaker.open_seconds_total()
        pipeline = self._pipeline_getter() if self._pipeline_getter else None
        if pipeline is not None:
            peek = pipeline.stats.peek()  # the non-destructive view
            vals["admitted"] = float(peek.get("PipelineAdmittedLines", 0))
            vals["shed"] = float(
                peek.get("PipelineShedLines", 0)
                + peek.get("PipelineDrainErrorLines", 0)
            )
            vals["processed"] = float(peek.get("PipelineProcessedLines", 0))
            vals["stale"] = float(peek.get("PipelineStaleDroppedLines", 0))
        return vals

    # ---- evaluation ----

    @staticmethod
    def _delta(cur: Dict[str, float], base: Dict[str, float],
               key: str) -> float:
        return max(0.0, cur.get(key, 0.0) - base.get(key, 0.0))

    def _burn_for(self, cur, base, span_s: float) -> Dict[str, float]:
        """One window's burn rate per SLO from (base → cur) deltas."""
        out: Dict[str, float] = {}
        d_batches = self._delta(cur, base, "batches_total")
        if "batches_total" in cur:
            if d_batches > 0:
                bad = d_batches - self._delta(cur, base, "batches_in_budget")
                bad_frac = min(1.0, max(0.0, bad / d_batches))
            else:
                bad_frac = 0.0
            out[SLO_BATCH_LATENCY] = bad_frac / (
                1.0 - self.batch_latency_target
            )
            d_trips = self._delta(cur, base, "budget_trips")
            trip_frac = d_trips / d_batches if d_batches > 0 else 0.0
            out[SLO_BUDGET_TRIPS] = min(1.0, trip_frac) / (
                self.budget_trip_ratio_max
            )
        if "breaker_open_s" in cur and span_s > 0:
            open_frac = min(
                1.0, self._delta(cur, base, "breaker_open_s") / span_s
            )
            out[SLO_BREAKER_OPEN] = open_frac / self.breaker_open_ratio_max
        if "admitted" in cur:
            d_adm = self._delta(cur, base, "admitted")
            shed_frac = (
                min(1.0, self._delta(cur, base, "shed") / d_adm)
                if d_adm > 0 else 0.0
            )
            out[SLO_SHED] = shed_frac / self.shed_ratio_max
            d_proc = self._delta(cur, base, "processed")
            stale_frac = (
                min(1.0, self._delta(cur, base, "stale") / d_proc)
                if d_proc > 0 else 0.0
            )
            out[SLO_STALE] = stale_frac / self.stale_ratio_max
        return {k: round(v, 4) for k, v in out.items()}

    def sample(self, now: Optional[float] = None) -> List[str]:
        """Take one sample and re-evaluate every window.  Returns the
        SLOs that newly transitioned into breach (the flight-recorder
        trigger list)."""
        t = self._clock() if now is None else now
        vals = self._collect()
        newly_breached: List[str] = []
        with self._lock:
            self._samples.append((t, vals))
            burn: Dict[str, Dict[str, float]] = {}
            for label, w_s in WINDOWS:
                base_t, base = self._oldest_within_locked(t, w_s)
                if base is None or base is vals:
                    continue
                span = max(1e-9, t - base_t)
                for slo, rate in self._burn_for(vals, base, span).items():
                    burn.setdefault(slo, {})[label] = rate
            self._burn = burn
            for slo in SLO_NAMES:
                windows = burn.get(slo)
                # breached = every evaluated window burning ≥ 1.0 (fast
                # catches the spike, slow keeps blips from paging); no
                # window data = not breached
                hit = bool(windows) and all(
                    v >= 1.0 for v in windows.values()
                )
                if hit and not self._breached[slo]:
                    newly_breached.append(slo)
                self._breached[slo] = hit
        if newly_breached and self._on_breach is not None:
            for slo in newly_breached:
                try:
                    self._on_breach(slo, self._burn.get(slo, {}))
                except Exception:  # noqa: BLE001 — a recorder bug must not stop sampling
                    pass
        return newly_breached

    def _oldest_within_locked(self, now: float, window_s: float):
        """(t, sample) of the oldest sample inside the window; the very
        oldest available when the engine is younger than the window."""
        base_t, base = None, None
        for t, vals in self._samples:
            if now - t <= window_s:
                if base is None or t < base_t:
                    base_t, base = t, vals
                break  # deque is time-ordered; first hit is the oldest
        if base is None and self._samples:
            base_t, base = self._samples[0]
        if base is not None and self._samples and (
            base is self._samples[-1][1] and len(self._samples) > 1
        ):
            # never diff a sample against itself when history exists
            base_t, base = self._samples[-2]
        return base_t, base

    # ---- views (exposition) ----

    def burn_rates(self) -> Dict[str, Dict[str, float]]:
        """{slo: {window_label: burn}} — banjax_slo_burn_rate."""
        with self._lock:
            return {k: dict(v) for k, v in self._burn.items()}

    def breached(self) -> Dict[str, bool]:
        """{slo: breached} — the one-hot banjax_slo_breached gauge."""
        with self._lock:
            return dict(self._breached)

    def snapshot(self) -> dict:
        """JSON-ready state for incident bundles / debugging."""
        return {
            "burn_rates": self.burn_rates(),
            "breached": self.breached(),
            "windows": {label: s for label, s in WINDOWS},
            "targets": {
                SLO_BATCH_LATENCY: self.batch_latency_target,
                SLO_SHED: self.shed_ratio_max,
                SLO_STALE: self.stale_ratio_max,
                SLO_BREAKER_OPEN: self.breaker_open_ratio_max,
                SLO_BUDGET_TRIPS: self.budget_trip_ratio_max,
            },
        }

    # ---- background sampling ----

    def start(self, interval_s: float = 15.0) -> None:
        if interval_s <= 0 or self._thread is not None:
            return

        def run():
            while not self._stop.wait(interval_s):
                try:
                    self.sample()
                except Exception:  # noqa: BLE001 — sampling must never die
                    pass

        self._thread = threading.Thread(
            target=run, name="slo-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
