"""Kafka report messages and the drop-don't-block producer queue.

Reference behavior: /root/reference/internal/kafka.go:285-350 — challenge
outcome events (ip_passed_challenge / ip_failed_challenge / ip_banned) and a
19s status heartbeat are marshalled to JSON and handed to the writer through
a channel with a NON-BLOCKING send: when the writer goroutine isn't draining
(disconnected, not started), messages are dropped, never queued unboundedly
and never blocking the request path.

Here the channel is a small bounded queue drained by the Kafka writer task
(banjax_tpu/ingest/kafka_io.py); put_nowait + drop-on-full reproduces the
drop-don't-block property.
"""

from __future__ import annotations

import json
import logging
import queue
import time
from typing import Optional

from banjax_tpu.config.schema import Config

log = logging.getLogger(__name__)

# module-level like the reference's global messageChan (kafka.go:349-350)
_message_queue: "queue.Queue[bytes]" = queue.Queue(maxsize=256)

# in an HTTP worker process (httpapi/worker_serve.py) there is no kafka
# writer draining the queue: reports are forwarded to the primary instead
_forwarder = None


def set_forwarder(fn) -> None:
    """Route report bytes through `fn` instead of the local queue (worker
    processes forward to the primary's control socket)."""
    global _forwarder
    _forwarder = fn


def get_message_queue() -> "queue.Queue[bytes]":
    return _message_queue


def _send_bytes(data: bytes) -> None:
    """Non-blocking send; drop when the writer isn't draining (kafka.go:334-346)."""
    if _forwarder is not None:
        _forwarder(data)
        return
    try:
        _message_queue.put_nowait(data)
    except queue.Full:
        log.debug("KAFKA: did not put message on queue (writer not draining)")


def report_status_message(config: Config) -> None:
    """kafka.go:291-306 — the `status` heartbeat."""
    message = {
        "id": config.hostname,
        "name": "status",
        "timestamp": int(time.time()),
    }
    _send_bytes(json.dumps(message).encode())


def report_passed_failed_banned_message(config: Config, name: str, ip: str, site: str) -> None:
    """kafka.go:308-332 — name is ip_passed_challenge, ip_failed_challenge,
    or ip_banned."""
    if config.disable_kafka:
        return
    message = {
        "id": config.hostname,
        "name": name,
        "value_ip": ip,
        "value_site": site,
        "timestamp": int(time.time()),
    }
    _send_bytes(json.dumps(message).encode())
