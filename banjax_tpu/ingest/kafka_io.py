"""Kafka command reader and report writer.

Reference behavior: /root/reference/internal/kafka.go —
  * Reader: infinite reconnect loop (5 s backoff), pinned to partition
    dnet_to_partition[dnet] (default 0) at the LAST offset, optional mTLS;
    parses commandMessage{Name, Value, Host, SessionId, Source, PrintLog} and
    dispatches challenge_ip / block_ip / challenge_session / block_session
    into the dynamic decision lists with per-site TTL overrides — note the
    reference's swapped-looking defaults: block_ip starts from
    block_session_ttl_seconds and vice versa (kafka.go:176-192), preserved
    here verbatim;
  * Writer: drains the report queue (drop-don't-block producer side, see
    banjax_tpu/ingest/reports.py) into the report topic, reconnecting on
    failure.  The reference's flat 5 s reconnect clocks are replaced on
    both loops by the shared capped jittered backoff
    (resilience/backoff.reconnect_backoff — the same implementation the
    tailer and the fabric peer links use).

Transport: pluggable `KafkaTransport` interface. The default is the real
broker client — `banjax_tpu.ingest.kafka_wire.WireKafkaTransport`, a pure-
stdlib Kafka binary-protocol implementation (TLS/mTLS, version-negotiated,
partition-pinned LastOffset consumer, acks=1 producer). Tests inject
`InMemoryTransport`; `NullTransport` models a permanently-unreachable
broker. All reference behaviors above live OUTSIDE the transport, so they
are fully exercised in tests regardless of the wire client.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from typing import Iterator, List, Optional

from banjax_tpu.utils import go_query_unescape

from banjax_tpu.config.holder import ConfigHolder
from banjax_tpu.config.schema import Config
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.ingest.reports import get_message_queue
from banjax_tpu.obs import provenance
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.backoff import Backoff, reconnect_backoff
from banjax_tpu.resilience.health import ComponentHealth

log = logging.getLogger(__name__)

RECONNECT_SECONDS = 5  # kafka.go:169 — now a backoff-CAP input, not a fixed sleep


def _reconnect_backoff() -> Backoff:
    """The shared reconnect policy (resilience/backoff.reconnect_backoff
    — one implementation for kafka, the tailer, and fabric peers),
    capped at 6x the reference's flat 5 s clock."""
    return reconnect_backoff(cap=6 * RECONNECT_SECONDS)


def get_dnet_partition(config: Config) -> int:
    """kafka.go:47-55."""
    partition = config.dnet_to_partition.get(config.dnet)
    if partition is not None:
        log.info("KAFKA: using dnet %s mapping to partition %d", config.dnet, partition)
        return partition
    log.info("KAFKA: dnet %s not found in dnet_to_partition mapping, using partition 0",
             config.dnet)
    return 0


# ------------------------------------------------------------- transports


class KafkaTransport:
    """Minimal transport contract: blocking message iteration + send."""

    def read_messages(self, config: Config, topic: str, partition: int) -> Iterator[bytes]:
        raise NotImplementedError

    def send(self, config: Config, topic: str, value: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullTransport(KafkaTransport):
    """Behaves like a permanently-unreachable broker."""

    def read_messages(self, config: Config, topic: str, partition: int) -> Iterator[bytes]:
        raise ConnectionError("no kafka client available")

    def send(self, config: Config, topic: str, value: bytes) -> None:
        raise ConnectionError("no kafka client available")


class InMemoryTransport(KafkaTransport):
    """Test transport: push commands in, collect reports out."""

    def __init__(self) -> None:
        self.incoming: "queue.Queue[bytes]" = queue.Queue()
        self.sent: List[bytes] = []
        self._closed = threading.Event()

    def push_command(self, obj: dict) -> None:
        self.incoming.put(json.dumps(obj).encode())

    def read_messages(self, config: Config, topic: str, partition: int) -> Iterator[bytes]:
        while not self._closed.is_set():
            try:
                yield self.incoming.get(timeout=0.1)
            except queue.Empty:
                continue

    def send(self, config: Config, topic: str, value: bytes) -> None:
        self.sent.append(value)

    def close(self) -> None:
        self._closed.set()


def default_transport() -> KafkaTransport:
    from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

    return WireKafkaTransport()


# ----------------------------------------------------------- TTL selection


def get_block_ip_ttl(config: Config, host: str) -> int:
    """kafka.go:176-183 — note: default comes from block_session_ttl_seconds
    (reference quirk, preserved)."""
    ttl = config.sites_to_block_ip_ttl_seconds.get(host)
    if ttl is not None:
        log.info("KAFKA: found site-specific block_ip ttl %s %d", host, ttl)
        return ttl
    return config.block_session_ttl_seconds


def get_block_session_ttl(config: Config, host: str) -> int:
    """kafka.go:185-192 — default from block_ip_ttl_seconds (same quirk)."""
    ttl = config.sites_to_block_session_ttl_seconds.get(host)
    if ttl is not None:
        log.info("KAFKA: found site-specific block_session ttl %s %d", host, ttl)
        return ttl
    return config.block_ip_ttl_seconds


# ------------------------------------------------------------- dispatching


def handle_command(config: Config, command: dict, decision_lists: DynamicDecisionLists) -> None:
    """kafka.go:194-226."""
    host = command.get("host", "")
    name = command.get("Name", "")

    # reference quirk (kafka.go:200-203): the skip-and-return only fires when
    # the host is disabled AND debug is on; in production the command is
    # stored and neutralized at serve time by the DIS-BASK chain check
    if host in config.sites_to_disable_baskerville and config.debug:
        log.info("KAFKA: %s disabled baskerville, skipping %s", host, name)
        return

    if name == "challenge_ip":
        _handle_ip_command(config, command, decision_lists, Decision.CHALLENGE,
                           config.expiring_decision_ttl_seconds)
    elif name == "block_ip":
        _handle_ip_command(config, command, decision_lists, Decision.NGINX_BLOCK,
                           get_block_ip_ttl(config, host))
    elif name == "challenge_session":
        _handle_session_command(config, command, decision_lists, Decision.CHALLENGE,
                                config.expiring_decision_ttl_seconds)
    elif name == "block_session":
        _handle_session_command(config, command, decision_lists, Decision.NGINX_BLOCK,
                                get_block_session_ttl(config, host))
    elif config.debug:
        log.info("KAFKA: unrecognized command name: %s", name)


def _handle_ip_command(
    config: Config, command: dict, decision_lists: DynamicDecisionLists,
    decision: Decision, expire_duration: int,
) -> None:
    """kafka.go:228-253."""
    value = command.get("Value", "")
    if len(value) <= 4:
        log.warning("KAFKA: command value looks malformed: %s", value)
        return
    decision_lists.update(
        value,
        time.time() + expire_duration,
        decision,
        True,  # from baskerville
        command.get("host", ""),
    )
    provenance.record(
        provenance.SOURCE_KAFKA, value, decision,
        rule=command.get("Name", ""),
    )


def _handle_session_command(
    config: Config, command: dict, decision_lists: DynamicDecisionLists,
    decision: Decision, expire_duration: int,
) -> None:
    """kafka.go:255-283 — session ids are url-decoded (gin cookie parity)."""
    session_id_raw = command.get("session_id", "")
    try:
        session_id = go_query_unescape(session_id_raw)
    except ValueError:
        log.warning("KAFKA: fail to urldecode session_id %s, skip command", session_id_raw)
        return
    decision_lists.update_by_session_id(
        command.get("Value", ""),
        session_id,
        time.time() + expire_duration,
        decision,
        True,
        command.get("host", ""),
    )
    provenance.record(
        provenance.SOURCE_KAFKA, command.get("Value", ""), decision,
        rule=command.get("Name", ""),
    )


# -------------------------------------------------------------- the loops


class KafkaReader:
    """kafka.go:93-174 — reconnect loop around the transport.

    With `pipeline` set (the streaming pipeline scheduler), each received
    message is admitted into the pipeline's buffer instead of dispatched
    inline: commands then get the same bounded-block/oldest-first-shed
    backpressure accounting as tailer lines (admitted == processed + shed
    spans both producers) and execute on the drain thread in admission
    order.  Without a pipeline the reference's inline dispatch is kept."""

    def __init__(
        self,
        config_holder: ConfigHolder,
        decision_lists: DynamicDecisionLists,
        transport: Optional[KafkaTransport] = None,
        backoff: Optional[Backoff] = None,
        health: Optional[ComponentHealth] = None,
        pipeline=None,
    ):
        self.config_holder = config_holder
        self.decision_lists = decision_lists
        self.transport = transport or default_transport()
        self.backoff = backoff or _reconnect_backoff()
        self.health = health
        self.pipeline = pipeline
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="kafka-reader", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.transport.close()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            config = self.config_holder.get()
            partition = get_dnet_partition(config)
            try:
                failpoints.check("kafka.read")
                for raw in self.transport.read_messages(
                    config, config.kafka_command_topic, partition
                ):
                    if self._stop.is_set():
                        return
                    # a delivered message is the success signal: reset the
                    # reconnect backoff and report healthy
                    self.backoff.reset()
                    if self.health is not None:
                        self.health.ok()
                    if self.pipeline is not None:
                        # admission-buffer path: backpressure + shed
                        # accounting shared with the tailer; dispatched by
                        # the drain stage in admission order
                        self.pipeline.submit_commands([raw], self.dispatch_raw)
                    else:
                        self.dispatch_raw(raw)
            except Exception as e:  # noqa: BLE001 — any transport failure → reconnect
                log.warning("KAFKA: reader failed: %s", e)
                if self.health is not None:
                    self.health.degraded(f"reconnecting: {e}")
            if self.backoff.wait(self._stop):
                return
            log.info("KAFKA: reconnecting kafka reader (attempt %d)",
                     self.backoff.attempt)

    def dispatch_raw(self, raw: bytes) -> None:
        """Parse + dispatch one command message (the reference's loop
        body).  Own method so the pipeline's drain stage can run it per
        admitted message; a malformed message loses itself, never the
        stream."""
        config = self.config_holder.get()
        try:
            command = json.loads(raw)
        except json.JSONDecodeError:
            log.warning("KAFKA: unmarshal failed: %r", raw[:200])
            return
        if not isinstance(command, dict):
            return
        if config.debug or command.get("print_log"):
            log.info("KAFKA: message N: %s, V: %s, S: %s, Src: %s",
                     command.get("Name"), command.get("Value"),
                     command.get("session_id"), command.get("source"))
        handle_command(config, command, self.decision_lists)


class KafkaWriter:
    """kafka.go:353-406 — drain the report queue into the report topic."""

    def __init__(
        self,
        config_holder: ConfigHolder,
        transport: Optional[KafkaTransport] = None,
        backoff: Optional[Backoff] = None,
        health: Optional[ComponentHealth] = None,
    ):
        self.config_holder = config_holder
        self.transport = transport or default_transport()
        self.backoff = backoff or _reconnect_backoff()
        self.health = health
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="kafka-writer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.transport.close()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        message_queue = get_message_queue()
        # the dequeued-but-unsent report: held across a transport failure
        # and retried first after reconnect, so a send crash never drops
        # the in-flight message (the producer side is drop-don't-block;
        # the drain side must not lose what it already accepted)
        pending: Optional[bytes] = None
        while not self._stop.is_set():
            config = self.config_holder.get()
            try:
                while not self._stop.is_set():
                    if pending is None:
                        try:
                            pending = message_queue.get(timeout=0.2)
                        except queue.Empty:
                            continue
                    failpoints.check("kafka.send")
                    self.transport.send(config, config.kafka_report_topic, pending)
                    pending = None
                    self.backoff.reset()
                    if self.health is not None:
                        self.health.ok()
            except Exception as e:  # noqa: BLE001 — any transport failure → reconnect
                log.warning("KAFKA: writer failed: %s%s", e,
                            " (1 report held for retry)" if pending else "")
                if self.health is not None:
                    self.health.degraded(f"reconnecting: {e}")
            if self.backoff.wait(self._stop):
                return
