"""Minimal Kafka wire-protocol client (pure stdlib).

No Kafka client library is baked into this image, so this module speaks the
Kafka binary protocol directly over sockets (TLS/mTLS per config) — the
real-broker transport behind banjax_tpu/ingest/kafka_io.py. It covers
exactly the surface the reference uses kafka-go for
(/root/reference/internal/kafka.go:57-91 dialer+mTLS, :93-174 partition-
pinned reader at LastOffset, :353-406 report writer):

  * ApiVersions v0 to negotiate, then per-API the newest version this
    module implements that the broker supports — the "legacy" ladder
    (Metadata v1 / ListOffsets v1 / Fetch v2 / Produce v2, message-set v1)
    for old brokers, and the "modern" ladder (Metadata v7 / ListOffsets v4 /
    Fetch v10 / Produce v7, record-batch v2 with crc32c + varints) which
    Kafka 4.x brokers require after KIP-896 removed the pre-2.1 versions.
  * Metadata for leader discovery over the bootstrap broker list.
  * ListOffsets(latest) for the reference's LastOffset start position.
  * Fetch long-polling with min_bytes/max_wait from config; gzip-, snappy-
    and lz4-compressed batches are decompressed in pure stdlib (snappy raw
    blocks per the record-batch v2 spec plus the xerial framing old
    producers wrap message-sets in — VERDICT C17; lz4 frame format with
    the block sequence decoder below, header checksums skipped so the
    broken legacy v0/v1 framing decodes too); zstd batches are logged once
    per codec, counted (skipped_batch_count → the metrics line's
    KafkaSkippedBatches), and skipped.
  * Produce acks=1 round-robining the report topic's partitions (the
    reference writer's default balancer behavior).

TLS mirrors getDialer: client cert + key (+password) and CA root when
configured, with hostname/chain verification disabled exactly like the
reference's InsecureSkipVerify (kafka.go:80, XXX noted there too).
"""

from __future__ import annotations

import gzip
import io
import logging
import socket
import ssl
import struct
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from banjax_tpu.config.schema import Config

log = logging.getLogger(__name__)

_CLIENT_ID = "banjax-tpu"

# api keys
_PRODUCE, _FETCH, _LIST_OFFSETS, _METADATA = 0, 1, 2, 3
_API_VERSIONS = 18

# error codes we act on
_ERR_NONE = 0
_ERR_OFFSET_OUT_OF_RANGE = 1
_ERR_UNKNOWN_TOPIC = 3
_ERR_LEADER_NOT_AVAILABLE = 5
_ERR_NOT_LEADER = 6


class KafkaWireError(ConnectionError):
    """Any protocol/transport failure; callers reconnect with backoff."""


# ------------------------------------------------------------ codec skip counter

_skip_lock = threading.Lock()
_skipped_batches = 0
_skip_logged_codecs: set = set()
_CODEC_NAMES = {0: "none", 1: "gzip", 2: "snappy", 3: "lz4", 4: "zstd"}


def _skip_batch(codec: int, why: str = "unsupported compression codec") -> None:
    """Count a batch dropped for an undecodable codec; log once per codec
    (not once per batch — a misconfigured producer would flood the log)."""
    global _skipped_batches
    with _skip_lock:
        _skipped_batches += 1
        first = codec not in _skip_logged_codecs
        _skip_logged_codecs.add(codec)
    if first:
        log.warning(
            "KAFKA: %s %s; batches with this codec are skipped "
            "(KafkaSkippedBatches on the metrics line counts them)",
            why, _CODEC_NAMES.get(codec, f"#{codec}"),
        )


def skipped_batch_count() -> int:
    with _skip_lock:
        return _skipped_batches


def reset_skipped_batches() -> None:
    """Test hook: zero the counter and the per-codec log-once set."""
    global _skipped_batches
    with _skip_lock:
        _skipped_batches = 0
        _skip_logged_codecs.clear()


# ------------------------------------------------------------ snappy (codec 2)

_XERIAL_MAGIC = b"\x82SNAPPY\x00"


def snappy_decompress(data: bytes) -> bytes:
    """Pure-stdlib snappy decode: a raw block (what record-batch v2
    carries) or the xerial stream framing (magic + version/compat header
    and length-prefixed raw blocks) the old Java producers wrap
    message-set payloads in."""
    if data[: len(_XERIAL_MAGIC)] == _XERIAL_MAGIC:
        out = bytearray()
        pos = 16  # 8-byte magic + i32 version + i32 compat
        while pos + 4 <= len(data):
            (block_len,) = struct.unpack(">i", data[pos : pos + 4])
            pos += 4
            if block_len < 0 or pos + block_len > len(data):
                raise KafkaWireError("snappy: truncated xerial block")
            out += _snappy_decode_block(data[pos : pos + block_len])
            pos += block_len
        return bytes(out)
    return _snappy_decode_block(data)


def _snappy_decode_block(data: bytes) -> bytes:
    """One raw snappy block: unsigned-LEB128 uncompressed length, then a
    tag stream of literals and back-copies (possibly overlapping — the
    RLE idiom)."""
    pos = 0
    ulen = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise KafkaWireError("snappy: truncated length preamble")
        b = data[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = (tag >> 2) + 1
            if ln > 60:  # 61..64 encode a 1..4-byte little-endian length
                nbytes = ln - 60
                if pos + nbytes > len(data):
                    raise KafkaWireError("snappy: truncated literal length")
                ln = int.from_bytes(data[pos : pos + nbytes], "little") + 1
                pos += nbytes
            if pos + ln > len(data):
                raise KafkaWireError("snappy: truncated literal")
            out += data[pos : pos + ln]
            pos += ln
            continue
        if kind == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            if pos >= len(data):
                raise KafkaWireError("snappy: truncated copy")
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise KafkaWireError("snappy: copy offset out of range")
        while ln > 0:  # overlapping copies replicate the trailing bytes
            take = min(ln, off)
            start = len(out) - off
            out += out[start : start + take]
            ln -= take
    if len(out) != ulen:
        raise KafkaWireError(
            f"snappy: decoded {len(out)} bytes, preamble said {ulen}"
        )
    return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Literal-only raw-block encoder (valid snappy, no back-references) —
    enough for the report producer path and the test fixtures."""
    ulen = len(data)
    out = bytearray()
    v = ulen
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | 0x80 if v else b)
        if not v:
            break
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        ln = len(chunk) - 1
        if ln < 60:
            out.append(ln << 2)
        else:
            out.append(61 << 2)  # upper-6-bits 61: 2-byte length follows
            out += ln.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


# ------------------------------------------------------------ lz4 (codec 3)

_LZ4_MAGIC = 0x184D2204


def xxh32(data: bytes, seed: int = 0) -> int:
    """xxHash32 (the lz4 frame checksum function) — needed only to WRITE
    valid frame headers (lz4_compress); reads skip checksum verification."""
    P1, P2, P3, P4, P5 = (
        2654435761, 2246822519, 3266489917, 668265263, 374761393,
    )
    M = 0xFFFFFFFF

    def rotl(x: int, r: int) -> int:
        return ((x << r) | (x >> (32 - r))) & M

    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i + 16 <= n:
            for k, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 4 * k : i + 4 * k + 4], "little")
                v = (v + lane * P2) & M
                v = (rotl(v, 13) * P1) & M
                if k == 0:
                    v1 = v
                elif k == 1:
                    v2 = v
                elif k == 2:
                    v3 = v
                else:
                    v4 = v
            i += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 4 <= n:
        h = (h + int.from_bytes(data[i : i + 4], "little") * P3) & M
        h = (rotl(h, 17) * P4) & M
        i += 4
    while i < n:
        h = (h + data[i] * P5) & M
        h = (rotl(h, 11) * P1) & M
        i += 1
    h ^= h >> 15
    h = (h * P2) & M
    h ^= h >> 13
    h = (h * P3) & M
    h ^= h >> 16
    return h


def lz4_decompress(data: bytes) -> bytes:
    """Pure-stdlib lz4 FRAME decode (what Kafka codec 3 carries in both
    the record-batch v2 payload and the legacy message-set wrapper).
    Checksums (header/block/content) are parsed past but not verified —
    deliberately: the pre-KIP-57 Java clients computed the header checksum
    over the wrong span, and verifying would reject their batches."""
    if len(data) < 7 or int.from_bytes(data[:4], "little") != _LZ4_MAGIC:
        raise KafkaWireError("lz4: bad frame magic")
    flg = data[4]
    if flg >> 6 != 1:
        raise KafkaWireError(f"lz4: unsupported frame version {flg >> 6}")
    block_checksum = (flg >> 4) & 1
    content_size = (flg >> 3) & 1
    dict_id = flg & 1
    pos = 6  # magic + FLG + BD
    if content_size:
        pos += 8
    if dict_id:
        pos += 4
    pos += 1  # HC byte (not verified, see docstring)
    out = bytearray()
    while True:
        if pos + 4 > len(data):
            raise KafkaWireError("lz4: truncated block header")
        word = int.from_bytes(data[pos : pos + 4], "little")
        pos += 4
        if word == 0:  # EndMark
            break
        size = word & 0x7FFFFFFF
        if pos + size > len(data):
            raise KafkaWireError("lz4: truncated block")
        blk = data[pos : pos + size]
        pos += size
        if block_checksum:
            pos += 4
        if word & 0x80000000:  # stored uncompressed
            out += blk
        else:
            out += _lz4_decode_block(blk)
    return bytes(out)


def _lz4_decode_block(data: bytes) -> bytes:
    """One lz4 compressed block: a sequence stream of (token, literals,
    offset, match) with possibly-overlapping back-copies; the last
    sequence is literals-only."""
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        token = data[pos]
        pos += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if pos >= n:
                    raise KafkaWireError("lz4: truncated literal length")
                b = data[pos]
                pos += 1
                lit += b
                if b != 255:
                    break
        if pos + lit > n:
            raise KafkaWireError("lz4: truncated literals")
        out += data[pos : pos + lit]
        pos += lit
        if pos == n:
            break  # last sequence carries no match
        if pos + 2 > n:
            raise KafkaWireError("lz4: truncated match offset")
        off = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        if off == 0 or off > len(out):
            raise KafkaWireError("lz4: match offset out of range")
        mlen = token & 0xF
        if mlen == 15:
            while True:
                if pos >= n:
                    raise KafkaWireError("lz4: truncated match length")
                b = data[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        while mlen > 0:  # overlapping copies replicate the trailing bytes
            take = min(mlen, off)
            start = len(out) - off
            out += out[start : start + take]
            mlen -= take
    return bytes(out)


def lz4_compress(data: bytes) -> bytes:
    """Literal-only lz4 frame encoder (valid lz4, no back-references) —
    the fixture/producer counterpart of lz4_decompress, mirroring
    snappy_compress. Header checksum is the real xxh32 so strict decoders
    accept the frames too."""
    flg = 0x60  # version 01, block-independent, no checksums/size/dict
    bd = 0x70   # 4 MB max block size
    hc = (xxh32(bytes([flg, bd])) >> 8) & 0xFF
    out = bytearray(struct.pack("<I", _LZ4_MAGIC)) + bytes([flg, bd, hc])
    for pos in range(0, max(1, len(data)), 65536):
        chunk = data[pos : pos + 65536]
        lit = len(chunk)
        blk = bytearray()
        if lit < 15:
            blk.append(lit << 4)
        else:
            blk.append(0xF0)
            rem = lit - 15
            while rem >= 255:
                blk.append(255)
                rem -= 255
            blk.append(rem)
        blk += chunk
        out += struct.pack("<I", len(blk)) + blk
    out += struct.pack("<I", 0)  # EndMark
    return bytes(out)


# ------------------------------------------------------------ crc32c (Castagnoli)

_CRC32C_TABLE = []


def _crc32c_init() -> None:
    poly = 0x82F63B78
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC32C_TABLE.append(c)


_crc32c_init()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    tab = _CRC32C_TABLE
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ------------------------------------------------------------ wire primitives


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _varint(n: int) -> bytes:
    v = _zigzag_encode(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise KafkaWireError("short response")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8", "replace")

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)

    def varint(self) -> int:
        shift = 0
        v = 0
        while True:
            b = self._take(1)[0]
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (v >> 1) ^ -(v & 1)  # zigzag decode

    def remaining(self) -> int:
        return len(self.data) - self.pos


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


# ------------------------------------------------------------ broker connection


def _ssl_context(config: Config) -> Optional[ssl.SSLContext]:
    """getDialer's TLS setup (kafka.go:57-91): client keypair + CA when
    kafka_ssl_cert is set, else plain TLS when the protocol asks for it;
    verification disabled to match InsecureSkipVerify."""
    want_tls = bool(config.kafka_ssl_cert) or (
        config.kafka_security_protocol or ""
    ).lower() in ("ssl", "tls")
    if not want_tls:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if config.kafka_ssl_ca:
        # an explicitly configured trust root is honored: verify the broker
        # chain and hostname against it (the reference's InsecureSkipVerify
        # would silently ignore it — surprising enough to diverge from)
        ctx.check_hostname = True
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(config.kafka_ssl_ca)
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE  # reference: InsecureSkipVerify (XXX)
        log.warning(
            "KAFKA: no kafka_ssl_ca configured; broker certificate "
            "verification is DISABLED"
        )
    if config.kafka_ssl_cert:
        ctx.load_cert_chain(
            config.kafka_ssl_cert,
            keyfile=config.kafka_ssl_key or None,
            password=config.kafka_ssl_key_password or None,
        )
    return ctx


class BrokerConn:
    """One TCP(/TLS) connection to a broker, with api-version negotiation."""

    def __init__(self, host: str, port: int, config: Config):
        self.host, self.port = host, port
        timeout = config.kafka_dialer_timeout_seconds or 10
        sock = socket.create_connection((host, port), timeout=timeout)
        keepalive = config.kafka_dialer_keep_alive_seconds or 0
        if keepalive:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        ctx = _ssl_context(config)
        if ctx is not None:
            sock = ctx.wrap_socket(sock, server_hostname=host)
        self.sock = sock
        self._corr = 0
        self._lock = threading.Lock()
        self.api_versions: Dict[int, Tuple[int, int]] = {}
        self._negotiate()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _negotiate(self) -> None:
        resp = self.request(_API_VERSIONS, 0, b"")
        r = _Reader(resp)
        err = r.i16()
        if err:
            raise KafkaWireError(f"ApiVersions error {err}")
        for _ in range(r.i32()):
            key, vmin, vmax = r.i16(), r.i16(), r.i16()
            self.api_versions[key] = (vmin, vmax)

    def pick_version(self, api_key: int, ours: List[int]) -> int:
        """Newest version in `ours` inside the broker's supported range."""
        if not self.api_versions:
            return ours[0]
        vmin, vmax = self.api_versions.get(api_key, (ours[0], ours[0]))
        for v in sorted(ours, reverse=True):
            if vmin <= v <= vmax:
                return v
        raise KafkaWireError(
            f"no common version for api {api_key}: broker [{vmin},{vmax}], "
            f"client {ours}"
        )

    def request(self, api_key: int, version: int, body: bytes,
                timeout: Optional[float] = None) -> bytes:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = struct.pack(">hhi", api_key, version, corr) + _string(_CLIENT_ID)
            msg = header + body
            old_timeout = self.sock.gettimeout()
            try:
                if timeout is not None:
                    self.sock.settimeout(timeout)
                self.sock.sendall(struct.pack(">i", len(msg)) + msg)
                raw = self._read_exact(4)
                (size,) = struct.unpack(">i", raw)
                resp = self._read_exact(size)
            except (OSError, ssl.SSLError) as e:
                raise KafkaWireError(f"broker io error: {e}") from None
            finally:
                try:
                    self.sock.settimeout(old_timeout)
                except OSError:
                    pass
        (got_corr,) = struct.unpack(">i", resp[:4])
        if got_corr != corr:
            raise KafkaWireError(f"correlation mismatch {got_corr} != {corr}")
        return resp[4:]

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise KafkaWireError("broker closed connection")
            buf.extend(chunk)
        return bytes(buf)


# ------------------------------------------------------------ metadata


def _parse_broker_list(config: Config) -> List[Tuple[str, int]]:
    out = []
    for b in config.kafka_brokers:
        host, _, port = b.rpartition(":")
        if not host:
            host, port = b, "9092"
        out.append((host, int(port)))
    if not out:
        raise KafkaWireError("no kafka_brokers configured")
    return out


def get_metadata(conn: BrokerConn, topic: str):
    """→ (brokers {node_id: (host, port)}, partitions {id: leader_node})."""
    v = conn.pick_version(_METADATA, [1, 7])
    body = struct.pack(">i", 1) + _string(topic)
    if v >= 4:
        body += struct.pack(">?", False)  # allow_auto_topic_creation
    r = _Reader(conn.request(_METADATA, v, body))
    if v >= 3:
        r.i32()  # throttle
    brokers: Dict[int, Tuple[str, int]] = {}
    for _ in range(r.i32()):
        node, host, port = r.i32(), r.string(), r.i32()
        r.string()  # rack (nullable, v1+)
        brokers[node] = (host or "", port)
    if v >= 2:
        r.string()  # cluster_id
    r.i32()  # controller_id
    partitions: Dict[int, int] = {}
    for _ in range(r.i32()):
        err, name = r.i16(), r.string()
        r.i8()  # is_internal (v1+)
        n_parts = r.i32()
        for _ in range(n_parts):
            p_err, pid, leader = r.i16(), r.i32(), r.i32()
            if v >= 7:
                r.i32()  # leader_epoch
            for _ in range(r.i32()):
                r.i32()  # replicas
            for _ in range(r.i32()):
                r.i32()  # isr
            if v >= 5:
                for _ in range(r.i32()):
                    r.i32()  # offline_replicas
            if p_err in (_ERR_NONE, _ERR_LEADER_NOT_AVAILABLE):
                partitions[pid] = leader
        if err not in (_ERR_NONE, _ERR_LEADER_NOT_AVAILABLE):
            raise KafkaWireError(f"metadata error {err} for topic {name!r}")
    return brokers, partitions


def list_latest_offset(conn: BrokerConn, topic: str, partition: int) -> int:
    """LastOffset positioning (kafka.go:127 kafka.LastOffset)."""
    v = conn.pick_version(_LIST_OFFSETS, [1, 4])
    body = struct.pack(">i", -1)  # replica_id
    if v >= 2:
        body += struct.pack(">b", 0)  # isolation_level read_uncommitted
    body += struct.pack(">i", 1) + _string(topic) + struct.pack(">i", 1)
    body += struct.pack(">i", partition)
    if v >= 4:
        body += struct.pack(">i", -1)  # current_leader_epoch
    body += struct.pack(">q", -1)  # timestamp: latest
    if v == 0:
        body += struct.pack(">i", 1)  # max_num_offsets
    r = _Reader(conn.request(_LIST_OFFSETS, v, body))
    if v >= 2:
        r.i32()  # throttle
    for _ in range(r.i32()):
        r.string()  # topic
        for _ in range(r.i32()):
            pid, err = r.i32(), r.i16()
            if err:
                raise KafkaWireError(f"ListOffsets error {err}")
            if v == 0:
                n = r.i32()
                return r.i64() if n else 0
            r.i64()  # timestamp
            off = r.i64()
            if v >= 4:
                r.i32()  # leader_epoch
            return off
    raise KafkaWireError("ListOffsets: empty response")


# ------------------------------------------------------------ record (de)coding


def _decode_message_set(data: bytes) -> List[Tuple[int, bytes]]:
    """Message-set v0/v1 → [(offset, value)]; recurses into gzip wrappers."""
    out: List[Tuple[int, bytes]] = []
    r = _Reader(data)
    while r.remaining() >= 12:
        offset = r.i64()
        size = r.i32()
        if r.remaining() < size:
            break  # partial trailing message (normal for fetch)
        msg = _Reader(r._take(size))
        msg.u32()  # crc (not verified on read)
        magic = msg.i8()
        attrs = msg.i8()
        if magic >= 1:
            msg.i64()  # timestamp
        msg.bytes_()  # key
        value = msg.bytes_()
        codec = attrs & 0x07
        if codec == 0:
            if value is not None:
                out.append((offset, value))
        elif codec == 1 and value is not None:
            inner = _decode_message_set(gzip.decompress(value))
            out.extend(inner)
        elif codec == 2 and value is not None:
            try:
                inner = _decode_message_set(snappy_decompress(value))
            except KafkaWireError as e:
                # a corrupt wrapper must not poison the fetch loop: the
                # same offset would refetch the same bytes forever
                _skip_batch(codec, f"undecodable snappy message set ({e});")
                continue
            out.extend(inner)
        elif codec == 3 and value is not None:
            try:
                inner = _decode_message_set(lz4_decompress(value))
            except KafkaWireError as e:
                _skip_batch(codec, f"undecodable lz4 message set ({e});")
                continue
            out.extend(inner)
        elif value is not None:
            _skip_batch(codec)
    return out


def _decode_record_batches(data: bytes) -> List[Tuple[int, bytes]]:
    """Record-batch v2 → [(offset, value)]; gzip handled, others skipped.
    Falls back to message-set decoding when the magic byte is < 2 (brokers
    may return old-format segments on any fetch version)."""
    out: List[Tuple[int, bytes]] = []
    r = _Reader(data)
    while r.remaining() >= 17:
        if r.data[r.pos + 16] < 2:  # magic byte: old message set
            out.extend(_decode_message_set(data[r.pos :]))
            return out
        base_offset = r.i64()
        batch_len = r.i32()
        if r.remaining() < batch_len:
            break  # partial batch
        batch = _Reader(r._take(batch_len))
        batch.i32()  # partition_leader_epoch
        batch.i8()   # magic (2)
        batch.u32()  # crc (not verified on read)
        attrs = batch.i16()
        batch.i32()  # last_offset_delta
        batch.i64()  # base_timestamp
        batch.i64()  # max_timestamp
        batch.i64()  # producer_id
        batch.i16()  # producer_epoch
        batch.i32()  # base_sequence
        n_records = batch.i32()
        if attrs & 0x20:
            # control batch (transaction commit/abort markers): not data —
            # yielding them would hand marker bytes to the command parser
            # (kafka-go filters these out client-side too)
            continue
        payload = batch._take(batch.remaining())
        codec = attrs & 0x07
        if codec == 1:
            payload = gzip.decompress(payload)
        elif codec == 2:
            try:
                payload = snappy_decompress(payload)
            except KafkaWireError as e:
                # corrupt payload: count + skip rather than poisoning the
                # fetch loop (the same offset would refetch it forever)
                _skip_batch(codec, f"undecodable snappy record batch ({e});")
                continue
        elif codec == 3:
            try:
                payload = lz4_decompress(payload)
            except KafkaWireError as e:
                _skip_batch(codec, f"undecodable lz4 record batch ({e});")
                continue
        elif codec:  # zstd (4) stays skip-counted
            _skip_batch(codec)
            continue
        pr = _Reader(payload)
        for _ in range(n_records):
            if pr.remaining() == 0:
                break
            length = pr.varint()
            rec = _Reader(pr._take(length))
            rec.i8()  # attributes
            rec.varint()  # timestamp_delta
            off_delta = rec.varint()
            klen = rec.varint()
            if klen >= 0:
                rec._take(klen)
            vlen = rec.varint()
            value = rec._take(vlen) if vlen >= 0 else None
            n_headers = rec.varint()
            for _ in range(n_headers):
                hk = rec.varint()
                rec._take(max(hk, 0))
                hv = rec.varint()
                if hv > 0:
                    rec._take(hv)
            if value is not None:
                out.append((base_offset + off_delta, value))
    return out


def _encode_message_set_v1(value: bytes, timestamp_ms: int, offset: int = 0) -> bytes:
    body = struct.pack(">bbq", 1, 0, timestamp_ms) + _bytes(None) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    msg = struct.pack(">I", crc) + body
    return struct.pack(">qi", offset, len(msg)) + msg


def _encode_record_batch_v2(value: bytes, timestamp_ms: int, offset: int = 0) -> bytes:
    record_body = (
        struct.pack(">b", 0)        # attributes
        + _varint(0)                # timestamp delta
        + _varint(0)                # offset delta
        + _varint(-1)               # key: null
        + _varint(len(value)) + value
        + _varint(0)                # headers
    )
    record = _varint(len(record_body)) + record_body
    after_crc = (
        struct.pack(">hiqqqhii", 0, 0, timestamp_ms, timestamp_ms,
                    -1, -1, -1, 1)  # attrs, lastOffsetDelta, ts, ts, pid, epoch, seq, n
        + record
    )
    crc = crc32c(after_crc)
    batch = struct.pack(">ibI", -1, 2, crc) + after_crc  # leader_epoch, magic, crc
    return struct.pack(">qi", offset, len(batch)) + batch


# ------------------------------------------------------------ fetch / produce


def fetch(conn: BrokerConn, topic: str, partition: int, offset: int,
          max_wait_ms: int, min_bytes: int, max_bytes: int):
    """→ (records [(offset, value)], error_code)."""
    v = conn.pick_version(_FETCH, [2, 10])
    body = struct.pack(">iii", -1, max_wait_ms, min_bytes)
    if v >= 3:
        body += struct.pack(">i", max_bytes)
    if v >= 4:
        body += struct.pack(">b", 0)  # isolation_level
    if v >= 7:
        body += struct.pack(">ii", 0, -1)  # session_id, session_epoch
    body += struct.pack(">i", 1) + _string(topic) + struct.pack(">i", 1)
    body += struct.pack(">i", partition)
    if v >= 9:
        body += struct.pack(">i", -1)  # current_leader_epoch
    body += struct.pack(">q", offset)
    if v >= 5:
        body += struct.pack(">q", -1)  # log_start_offset
    body += struct.pack(">i", max_bytes)  # partition max bytes
    if v >= 7:
        body += struct.pack(">i", 0)  # forgotten_topics_data
    r = _Reader(conn.request(
        _FETCH, v, body, timeout=max(10.0, max_wait_ms / 1000 + 10)
    ))
    r.i32()  # throttle (v1+)
    if v >= 7:
        top_err = r.i16()
        r.i32()  # session_id
        if top_err:
            raise KafkaWireError(f"fetch error {top_err}")
    records: List[Tuple[int, bytes]] = []
    err = _ERR_NONE
    for _ in range(r.i32()):
        r.string()  # topic
        for _ in range(r.i32()):
            r.i32()  # partition
            err = r.i16()
            r.i64()  # high_watermark
            if v >= 4:
                r.i64()  # last_stable_offset
                if v >= 5:
                    r.i64()  # log_start_offset
                for _ in range(r.i32()):  # aborted transactions
                    r.i64()
                    r.i64()
            record_data = r.bytes_() or b""
            if err == _ERR_NONE and record_data:
                records.extend(_decode_record_batches(record_data))
    return records, err


def produce(conn: BrokerConn, topic: str, partition: int, value: bytes) -> None:
    v = conn.pick_version(_PRODUCE, [2, 7])
    ts = int(time.time() * 1000)
    if v >= 3:
        record_set = _encode_record_batch_v2(value, ts)
        body = _string(None)  # transactional_id
    else:
        record_set = _encode_message_set_v1(value, ts)
        body = b""
    body += struct.pack(">hi", 1, 30_000)  # acks=1, timeout
    body += struct.pack(">i", 1) + _string(topic) + struct.pack(">i", 1)
    body += struct.pack(">i", partition) + _bytes(record_set)
    r = _Reader(conn.request(_PRODUCE, v, body))
    for _ in range(r.i32()):
        r.string()
        for _ in range(r.i32()):
            r.i32()  # partition
            err = r.i16()
            if err:
                raise KafkaWireError(f"produce error {err}")
    # (throttle and later fields ignored)


# ------------------------------------------------------------ the transport


class WireKafkaTransport:
    """KafkaTransport implementation over the wire client.

    read_messages is a generator that yields message values from the pinned
    partition starting at the LATEST offset; any failure raises
    KafkaWireError so KafkaReader's reconnect loop (the shared capped
    jittered backoff, resilience/backoff.reconnect_backoff) takes
    over. send round-robins the report topic's
    partitions with acks=1; failures raise and the message is dropped —
    the reference's drop-don't-block producer semantics."""

    def __init__(self) -> None:
        self._consumer: Optional[BrokerConn] = None
        # one pooled connection per leader broker (multi-broker clusters
        # spread partition leaders; reconnecting per send would mean a full
        # TCP+TLS+ApiVersions handshake per report)
        self._producer_conns: Dict[Tuple[str, int], BrokerConn] = {}
        self._producer_parts: List[int] = []
        self._producer_leaders: Dict[int, Tuple[str, int]] = {}
        self._rr = 0
        self._closed = threading.Event()
        self._lock = threading.Lock()

    # -- connection helpers

    def _connect_any(self, config: Config) -> BrokerConn:
        last: Optional[Exception] = None
        for host, port in _parse_broker_list(config):
            try:
                return BrokerConn(host, port, config)
            except (OSError, KafkaWireError, ssl.SSLError) as e:
                last = e
        raise KafkaWireError(f"no reachable kafka broker: {last}")

    def _leader_conn(self, config: Config, topic: str, partition: int) -> BrokerConn:
        boot = self._connect_any(config)
        try:
            brokers, partitions = get_metadata(boot, topic)
            leader = partitions.get(partition)
            if leader is None or leader < 0:
                raise KafkaWireError(
                    f"no leader for {topic!r}[{partition}] "
                    f"(known partitions: {sorted(partitions)})"
                )
            host, port = brokers[leader]
            if (host, port) == (boot.host, boot.port):
                return boot
            conn = BrokerConn(host, port, config)
            boot.close()
            return conn
        except Exception:
            boot.close()
            raise

    # -- KafkaTransport API

    def read_messages(self, config: Config, topic: str, partition: int) -> Iterator[bytes]:
        # connect + position EAGERLY (not at first next()): LastOffset is
        # "latest as of subscribe time", matching kafka-go's reader
        conn = self._leader_conn(config, topic, partition)
        self._consumer = conn
        max_wait = config.kafka_max_wait_ms or 500
        min_bytes = config.kafka_min_bytes or 1
        max_bytes = config.kafka_max_bytes or (10 << 20)
        try:
            offset = list_latest_offset(conn, topic, partition)
        except Exception:
            conn.close()
            self._consumer = None
            raise
        log.info("KAFKA: consuming %s[%d] from offset %d (%s:%d)",
                 topic, partition, offset, conn.host, conn.port)

        def _iterate() -> Iterator[bytes]:
            nonlocal offset
            try:
                while not self._closed.is_set():
                    records, err = fetch(
                        conn, topic, partition, offset, max_wait, min_bytes,
                        max_bytes,
                    )
                    if err == _ERR_OFFSET_OUT_OF_RANGE:
                        offset = list_latest_offset(conn, topic, partition)
                        continue
                    if err != _ERR_NONE:
                        raise KafkaWireError(f"fetch error {err}")
                    for rec_offset, value in records:
                        if rec_offset < offset:
                            continue  # batches include earlier compacted records
                        offset = rec_offset + 1
                        yield value
            finally:
                conn.close()
                self._consumer = None

        return _iterate()

    def send(self, config: Config, topic: str, value: bytes) -> None:
        with self._lock:
            try:
                self._send_locked(config, topic, value)
            except (KafkaWireError, OSError, ssl.SSLError, KeyError):
                self._teardown_producer()
                raise

    def _send_locked(self, config: Config, topic: str, value: bytes) -> None:
        if not self._producer_parts:
            boot = self._connect_any(config)
            try:
                brokers, partitions = get_metadata(boot, topic)
            except Exception:
                boot.close()
                raise
            # only partitions with a live, known leader are sendable; a
            # partition mid-leader-election must not eat reports
            self._producer_leaders = {
                pid: brokers[node] for pid, node in partitions.items()
                if node >= 0 and node in brokers
            }
            self._producer_parts = sorted(self._producer_leaders)
            if not self._producer_parts:
                boot.close()
                raise KafkaWireError(
                    f"topic {topic!r} has no partition with a live leader"
                )
            self._producer_conns[(boot.host, boot.port)] = boot
        pid = self._producer_parts[self._rr % len(self._producer_parts)]
        self._rr += 1
        addr = self._producer_leaders[pid]
        conn = self._producer_conns.get(addr)
        if conn is None:
            conn = BrokerConn(addr[0], addr[1], config)
            self._producer_conns[addr] = conn
        produce(conn, topic, pid, value)

    def _teardown_producer(self) -> None:
        for conn in self._producer_conns.values():
            conn.close()
        self._producer_conns = {}
        self._producer_parts = []
        self._producer_leaders = {}

    def close(self) -> None:
        self._closed.set()
        if self._consumer is not None:
            self._consumer.close()
        for conn in self._producer_conns.values():
            conn.close()
