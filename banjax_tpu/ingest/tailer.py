"""Log tailer: follow the Nginx access log from EOF and feed the matcher.

Reference behavior: /root/reference/internal/regex_rate_limiter.go:21-78 —
tail the server_log_file with Follow + SeekEnd (retrying every 5 s until the
file exists), then hand each line to consumeLine with the *latest* config
snapshot (so rate-limit rules hot-reload without restarting the tailer).

The reference uses inotify via hpcloud/tail; here a poll-based follower
(50 ms idle sleep) keeps the dependency surface zero and handles truncation
and rotation (size shrink or inode change → drain the old inode to EOF —
bytes appended between the last read and the rotation live only there —
flush the never-terminated trailing line, then reopen from start;
tests/faults/test_tailer_rotation.py pins the no-drop/no-dup contract).

Resilience: the retry-until-exists loop uses capped jittered exponential
backoff instead of the reference's flat 5 s clock, the `tailer.open`
failpoint injects deterministic open failures for the fault suite, and a
health component heartbeats every poll iteration so a wedged tailer
surfaces on /healthz.

Backpressure: reads are bounded (READ_CHUNK_BYTES) so a multi-GB backlog
after a stall arrives as a stream of bounded chunks instead of one giant
string, and `on_lines` is allowed to BLOCK — the pipeline scheduler
(banjax_tpu/pipeline/) uses that to apply bounded backpressure to this
thread when its admission buffer is full.  While on_lines blocks, unread
bytes simply stay in the file, which is the cheapest possible queue.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, List, Optional

from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.backoff import Backoff, reconnect_backoff
from banjax_tpu.resilience.health import ComponentHealth

log = logging.getLogger(__name__)

RETRY_SECONDS = 5  # regex_rate_limiter.go:47 — now the backoff cap
POLL_SECONDS = 0.05
# one read's upper bound: keeps a post-stall backlog from materializing as
# a single unbounded string (and as one unbounded matcher batch)
READ_CHUNK_BYTES = 4 << 20


class LogTailer:
    """Calls `on_lines(batch)` with every read chunk's complete lines.

    Batch delivery is the natural feed for the batched TPU matcher: the
    faster the log grows, the bigger the device batches get, which is
    exactly the load shape the batch path is built for. The serial CPU
    matcher consumes the same batches line by line (Matcher.consume_lines'
    default), preserving the reference's per-line semantics.
    """

    def __init__(self, path: str, on_lines: Callable[[List[str]], None],
                 backoff: Optional[Backoff] = None,
                 health: Optional[ComponentHealth] = None):
        self.path = path
        self.on_lines = on_lines
        # shared reconnect policy (same implementation as kafka + fabric)
        self.backoff = backoff or reconnect_backoff(
            cap=RETRY_SECONDS, base=0.25
        )
        self.health = health
        # set once the log file is open and being followed (readiness
        # signal for tests and supervisors; re-set after each reopen)
        self.opened = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="log-tailer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _open(self, at_end: bool):
        failpoints.check("tailer.open")
        f = open(self.path, "r", encoding="utf-8", errors="replace")
        if at_end:
            f.seek(0, os.SEEK_END)
        return f

    def _deliver(self, buffer: str) -> str:
        """Hand every complete line in `buffer` to on_lines; returns the
        trailing partial line.  One split, not a split-per-line loop: the
        repeated "rest of buffer" copy is O(n^2) on a big burst, which is
        exactly when the tailer must keep up."""
        parts = buffer.split("\n")
        rest = parts.pop()
        batch: List[str] = [line for line in parts if line]
        if batch:
            try:
                self.on_lines(batch)
            except Exception:  # noqa: BLE001 — a bad batch must not kill the tailer
                log.exception("error consuming log line batch")
        return rest

    def _run(self) -> None:
        f = None
        at_end = True  # first open seeks to EOF; rotation reopens from 0
        inode = 0
        buffer = ""
        try:
            while not self._stop.is_set():
                if f is None:
                    # retry-until-open loop (regex_rate_limiter.go:30-51),
                    # shared by first start AND a failed rotation reopen —
                    # an open error can never strand the follow loop on a
                    # closed file handle
                    try:
                        f = self._open(at_end=at_end)
                        inode = os.fstat(f.fileno()).st_ino
                        buffer = ""
                        self.backoff.reset()
                        self.opened.set()
                        log.info("log tailer started on %s", self.path)
                        if self.health is not None:
                            self.health.ok()
                    except OSError as e:
                        log.info("log tailer failed to start. waiting a bit "
                                 "and trying again.")
                        if self.health is not None:
                            self.health.degraded(f"waiting for {self.path}: {e}")
                        if self.backoff.wait(self._stop):
                            return
                        continue

                if self.health is not None:
                    self.health.beat()
                chunk = f.read(READ_CHUNK_BYTES)
                if chunk:
                    buffer = self._deliver(buffer + chunk)
                    continue

                # idle: check rotation/truncation
                try:
                    st = os.stat(self.path)
                    pos = f.tell()
                    if st.st_ino != inode or st.st_size < pos:
                        rotated = st.st_ino != inode
                        # drain the OLD file before closing it: bytes
                        # appended between our last (empty) read and the
                        # rotation live only in the old inode — closing
                        # without this final read drops them (the
                        # log-rotation-mid-burst scenario caught exactly
                        # that loss; tests/faults/test_tailer_rotation.py)
                        while True:
                            tail = f.read(READ_CHUNK_BYTES)
                            if not tail:
                                break
                            buffer = self._deliver(buffer + tail)
                        if rotated and buffer:
                            # the old file is final: a trailing line the
                            # writer never newline-terminated (rotation
                            # raced the write) still reaches the matcher
                            # instead of dying in the parse buffer
                            self._deliver(buffer + "\n")
                        log.info("log file rotated/truncated; reopening")
                        buffer = ""
                        f.close()
                        f = None
                        at_end = False
                        continue
                except OSError:
                    pass
                self._stop.wait(POLL_SECONDS)
        finally:
            if f is not None:
                f.close()
