"""Log tailer: follow the Nginx access log from EOF and feed the matcher.

Reference behavior: /root/reference/internal/regex_rate_limiter.go:21-78 —
tail the server_log_file with Follow + SeekEnd (retrying every 5 s until the
file exists), then hand each line to consumeLine with the *latest* config
snapshot (so rate-limit rules hot-reload without restarting the tailer).

The reference uses inotify via hpcloud/tail; here a poll-based follower
(50 ms idle sleep) keeps the dependency surface zero and handles truncation
and rotation (size shrink or inode change → reopen from start).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)

RETRY_SECONDS = 5  # regex_rate_limiter.go:47
POLL_SECONDS = 0.05


class LogTailer:
    """Calls `on_lines(batch)` with every read chunk's complete lines.

    Batch delivery is the natural feed for the batched TPU matcher: the
    faster the log grows, the bigger the device batches get, which is
    exactly the load shape the batch path is built for. The serial CPU
    matcher consumes the same batches line by line (Matcher.consume_lines'
    default), preserving the reference's per-line semantics.
    """

    def __init__(self, path: str, on_lines: Callable[[List[str]], None]):
        self.path = path
        self.on_lines = on_lines
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="log-tailer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _open_at_end(self):
        f = open(self.path, "r", encoding="utf-8", errors="replace")
        f.seek(0, os.SEEK_END)
        return f

    def _run(self) -> None:
        f = None
        # retry-until-exists loop (regex_rate_limiter.go:30-51)
        while not self._stop.is_set():
            try:
                f = self._open_at_end()
                break
            except OSError:
                log.info("log tailer failed to start. waiting a bit and trying again.")
                if self._stop.wait(RETRY_SECONDS):
                    return

        if f is None:
            return
        log.info("log tailer started on %s", self.path)

        inode = os.fstat(f.fileno()).st_ino
        buffer = ""
        while not self._stop.is_set():
            chunk = f.read()
            if chunk:
                buffer += chunk
                # one split, not a split-per-line loop: the repeated
                # "rest of buffer" copy is O(n^2) on a big burst, which is
                # exactly when the tailer must keep up
                parts = buffer.split("\n")
                buffer = parts.pop()
                batch: List[str] = [line for line in parts if line]
                if batch:
                    try:
                        self.on_lines(batch)
                    except Exception:  # noqa: BLE001 — a bad batch must not kill the tailer
                        log.exception("error consuming log line batch")
                continue

            # idle: check rotation/truncation
            try:
                st = os.stat(self.path)
                pos = f.tell()
                if st.st_ino != inode or st.st_size < pos:
                    log.info("log file rotated/truncated; reopening")
                    f.close()
                    f = open(self.path, "r", encoding="utf-8", errors="replace")
                    inode = os.fstat(f.fileno()).st_ino
                    buffer = ""
                    continue
            except OSError:
                pass
            self._stop.wait(POLL_SECONDS)

        f.close()
