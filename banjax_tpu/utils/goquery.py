"""Go net/url QueryEscape/QueryUnescape equivalents.

The reference's cookie round trip depends on gin's exact behavior: cookie
values are QueryEscape'd when set and QueryUnescape'd when read (which turns
a literal '+' into ' ' — the bug the challenge-cookie parser works around,
challenge_response.go:77-84). Python's urllib quoting differs in error
handling: Go QueryUnescape FAILS on a malformed %-sequence (gin then treats
the cookie as absent), while urllib silently passes it through — so these
ports raise like Go does.
"""

from __future__ import annotations

from urllib.parse import quote_plus

_HEX = "0123456789abcdefABCDEF"

_UNRESERVED = set(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_.~"
)


def go_query_unescape(s: str) -> str:
    """url.QueryUnescape: %XX decoded (error on malformed), '+' → ' '."""
    out = bytearray()
    i = 0
    n = len(s)
    while i < n:
        c = s[i]
        if c == "%":
            if i + 2 >= n:
                raise ValueError(f"invalid URL escape {s[i:i+3]!r}")
            h1, h2 = s[i + 1], s[i + 2]
            if h1 not in _HEX or h2 not in _HEX:
                raise ValueError(f"invalid URL escape {s[i:i+3]!r}")
            out.append(int(h1 + h2, 16))
            i += 3
        elif c == "+":
            out.append(0x20)
            i += 1
        else:
            out.extend(c.encode("utf-8"))
            i += 1
    return out.decode("utf-8", errors="surrogateescape")


def go_query_escape(s: str) -> str:
    """url.QueryEscape: unreserved kept, space → '+', rest %XX.

    urllib's quote_plus over the utf-8 bytes is byte-for-byte identical
    (same always-safe set ALPHA/DIGIT/"-_.~", same '+' for space, same
    uppercase hex) and ~2x faster — differential-tested against the
    explicit loop in tests/unit/test_goquery.py."""
    return quote_plus(s.encode("utf-8", errors="surrogateescape"))


def go_query_escape_ref(s: str) -> str:
    """The explicit reference loop (kept as the differential oracle)."""
    out = []
    for b in s.encode("utf-8", errors="surrogateescape"):
        ch = chr(b)
        if ch in _UNRESERVED:
            out.append(ch)
        elif ch == " ":
            out.append("+")
        else:
            out.append(f"%{b:02X}")
    return "".join(out)
