"""Small Go-stdlib-compatible helpers shared across modules."""

from banjax_tpu.utils.goquery import go_query_escape, go_query_unescape

__all__ = ["go_query_escape", "go_query_unescape"]
