"""Config schema: the single YAML file every subsystem reads.

Reference behavior: /root/reference/internal/config.go:17-131 — a ~60-key YAML
schema whose custom unmarshal step compiles each `regexes_with_rates` entry's
regex and parses its decision string at load time, so a bad rule fails the
whole config load (fail fast, before any traffic is touched).

This port keeps the exact YAML key names. The rule-compile step additionally
feeds the TPU rule compiler (banjax_tpu/matcher/rulec.py) when the TPU matcher
is enabled; unsupported patterns are reported at load time and fall back
per-rule to the CPU path.

Extra keys beyond the reference (all optional, default to reference behavior):
  matcher:              "cpu" (default, Go-semantics reference path) or "tpu"
  matcher_batch_lines:  device batch size for the TPU matcher
  matcher_max_line_len: padded line length for the TPU matcher
"""

from __future__ import annotations

import dataclasses
import re
import socket
import time
from typing import Any, Dict, List

import yaml

from banjax_tpu.decisions.model import Decision, parse_decision
from banjax_tpu.matcher.re2check import check_re2_compatible

NANOS_PER_SECOND = 1_000_000_000


@dataclasses.dataclass
class RegexWithRate:
    """One rate-limit rule (config.go:87-131).

    `interval_ns` mirrors Go's time.Duration (int64 nanoseconds) so the
    fixed-window comparison `ts - start > interval` is bit-identical.
    """

    rule: str
    regex_string: str
    regex: "re.Pattern[str]"
    interval_ns: int
    hits_per_interval: int
    decision: Decision
    hosts_to_skip: Dict[str, bool] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_yaml_dict(cls, d: Dict[str, Any]) -> "RegexWithRate":
        regex_string = d.get("regex", "")
        check_re2_compatible(regex_string)  # reject Python-only constructs RE2 refuses
        try:
            regex = re.compile(regex_string)
        except re.error as e:
            raise ValueError(f"bad regex {regex_string!r}: {e}") from None
        # Go: time.Duration(interval_seconds_float * 1e9) — truncation, not round.
        interval_ns = int(float(d.get("interval", 0)) * NANOS_PER_SECOND)
        return cls(
            rule=d.get("rule", ""),
            regex_string=regex_string,
            regex=regex,
            interval_ns=interval_ns,
            hits_per_interval=int(d.get("hits_per_interval", 0)),
            decision=parse_decision(d.get("decision", "")),
            hosts_to_skip=dict(d.get("hosts_to_skip") or {}),
        )


@dataclasses.dataclass
class Config:
    """Full banjax config (config.go:17-85). YAML keys unchanged."""

    regexes_with_rates: List[RegexWithRate] = dataclasses.field(default_factory=list)
    per_site_regexes_with_rates: Dict[str, List[RegexWithRate]] = dataclasses.field(default_factory=dict)
    server_log_file: str = ""
    banning_log_file: str = ""
    iptables_ban_seconds: int = 0
    iptables_unbanner_seconds: int = 0
    kafka_brokers: List[str] = dataclasses.field(default_factory=list)
    kafka_security_protocol: str = ""
    kafka_ssl_ca: str = ""
    kafka_ssl_cert: str = ""
    kafka_ssl_key: str = ""
    kafka_ssl_key_password: str = ""
    kafka_command_topic: str = ""
    kafka_report_topic: str = ""
    kafka_min_bytes: int = 0
    kafka_max_bytes: int = 0
    kafka_max_wait_ms: int = 0
    kafka_dialer_timeout_seconds: int = 0
    kafka_dialer_keep_alive_seconds: int = 0
    per_site_decision_lists: Dict[str, Dict[str, List[str]]] = dataclasses.field(default_factory=dict)
    global_decision_lists: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    config_version: str = ""
    standalone_testing: bool = False
    challenger_bytes: bytes = b""
    password_page_bytes: bytes = b""
    password_hashes: Dict[str, str] = dataclasses.field(default_factory=dict)
    password_protected_paths: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    password_protected_path_exceptions: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    password_hash_roaming: Dict[str, str] = dataclasses.field(default_factory=dict)
    password_persite_cookie_ttl_seconds: Dict[str, int] = dataclasses.field(default_factory=dict)
    use_user_agent_in_cookie: Dict[str, bool] = dataclasses.field(default_factory=dict)
    expiring_decision_ttl_seconds: int = 0
    block_ip_ttl_seconds: int = 0
    block_session_ttl_seconds: int = 0
    sites_to_block_ip_ttl_seconds: Dict[str, int] = dataclasses.field(default_factory=dict)
    sites_to_block_session_ttl_seconds: Dict[str, int] = dataclasses.field(default_factory=dict)
    too_many_failed_challenges_interval_seconds: int = 0
    too_many_failed_challenges_threshold: int = 0
    password_cookie_ttl_seconds: int = 0
    sha_inv_cookie_ttl_seconds: int = 0
    sha_inv_expected_zero_bits: int = 0
    restart_time: int = 0
    reload_time: int = 0
    hostname: str = ""
    hmac_secret: str = ""
    gin_log_file: str = ""
    sitewide_sha_inv_list: Dict[str, str] = dataclasses.field(default_factory=dict)
    metrics_log_file: str = ""
    sha_inv_challenge_html: str = ""
    password_protected_path_html: str = ""
    debug: bool = False
    profile: bool = False
    disable_logging: Dict[str, bool] = dataclasses.field(default_factory=dict)
    banning_log_file_temp: str = ""
    disable_kafka: bool = False
    disable_kafka_writer: bool = False
    session_cookie_hmac_secret: str = ""
    session_cookie_ttl_seconds: int = 0
    session_cookie_not_verify: bool = False
    sites_to_disable_baskerville: Dict[str, bool] = dataclasses.field(default_factory=dict)
    sha_inv_path_exceptions: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    dnet: str = ""
    dnet_to_partition: Dict[str, int] = dataclasses.field(default_factory=dict)
    per_site_user_agent_decision_lists: Dict[str, Dict[str, List[str]]] = dataclasses.field(default_factory=dict)
    global_user_agent_decision_lists: Dict[str, List[str]] = dataclasses.field(default_factory=dict)

    # --- banjax-tpu extensions (absent from the reference) ---
    matcher: str = "cpu"  # "cpu" | "tpu" — the Matcher seam flag (BASELINE.json)
    matcher_batch_lines: int = 16384
    matcher_max_line_len: int = 256
    # device backend for the TPU matcher: "auto" picks the Pallas kernel on
    # TPU and the XLA scan elsewhere; "pallas-interpret" runs the kernel as
    # plain JAX for CI (SURVEY.md §4 carry-over (f))
    matcher_backend: str = "auto"  # "auto" | "xla" | "pallas" | "pallas-interpret"
    # device-resident fixed-window counters (matcher/windows.py): the batch
    # of match events folds into persistent [capacity, n_rules] arrays on
    # the TPU instead of the host dict. Counters reset on config reload
    # (rule ids reindex); the reference keeps them (keyed by rule name).
    matcher_device_windows: bool = False
    # IP slots for device windows. 0 (the default) = auto-size: start at
    # 16384 and double on observed distinct-IP pressure up to a ~2 GiB
    # device-memory ceiling, so the common case never evicts. A fixed
    # positive count pins the table; beyond it the LRU IP's counters spill
    # losslessly to the host shadow (restored on re-admission) at a
    # throughput cost. DeviceWindows.eviction_count / the metrics line's
    # DeviceWindowsEvictionsPerInterval surface the churn.
    matcher_window_capacity: int = 0  # IP slots; 0 = auto-size
    # two-stage literal prefilter (matcher/prefilter.py): bit-identical
    # output, auto-disabled for rulesets with too few filterable rules.
    # cand_frac sizes the candidate capacity as a fraction of the batch:
    # a batch whose stage-1 hit rate exceeds it falls back to the
    # single-stage matcher (correct but slower) — raise it for rulesets
    # whose factors fire often on benign traffic
    matcher_prefilter: bool = True
    matcher_prefilter_cand_frac: float = 0.125
    # multi-device mesh (parallel/mesh.py): shard the line batch over `dp`
    # devices and the packed NFA word axis over `rp` devices (dp * rp =
    # matcher_mesh_devices). 0 = single-device. matcher_mesh_rp 0 = auto
    # (widest power of two ≤ min(4, devices) that divides the device count).
    matcher_mesh_devices: int = 0
    matcher_mesh_rp: int = 0
    # native C batch parse+encode for the tailer hot path (banjax_tpu/
    # native); auto-disables when no C compiler is present
    matcher_native_parse: bool = True
    # SO_REUSEPORT worker processes for the HTTP request API
    # (httpapi/workers.py). 0 = single process, the reference's layout;
    # N > 0 spawns N workers sharing 127.0.0.1:8081 with the primary,
    # with the failed-challenge limiter in native shared memory and
    # side effects forwarded to the primary; -1 = auto (cores - 1,
    # which is 0 on a single-core host). Needs a C compiler at first
    # start (native/shmstate.c); falls back to 0 without one.
    http_workers: int = 0
    # native asyncio-protocol server for the /auth_request hot path
    # (httpapi/fastserve.py): ~2-3x the aiohttp requests/sec, identical
    # wire contract (cold routes proxied to the aiohttp app over a unix
    # socket). false restores the pure-aiohttp layout.
    http_fast_path: bool = True
    # circuit breaker around the TPU matcher batch path (resilience/
    # breaker.py): this many consecutive device failures (or latency-
    # budget breaches) route batches to the CPU reference matcher until a
    # half-open probe succeeds after breaker_recovery_seconds
    breaker_failure_threshold: int = 3
    breaker_recovery_seconds: float = 30.0
    # per-batch latency budget for the matcher in milliseconds; a batch
    # slower than this counts as a breaker failure. 0 disables the check.
    matcher_latency_budget_ms: float = 0.0
    # optional rolling failure-rate window for the breaker: also trip when
    # breaker_failure_threshold failures land within the last
    # breaker_window_size outcomes even with successes interleaved (the
    # flapping-device mode the consecutive counter misses). 0 = off.
    breaker_window_size: int = 0
    # deterministic fault injection (resilience/failpoints.py): same spec
    # syntax as the BANJAX_FAILPOINTS env var, e.g.
    # "matcher.device=error:5;kafka.read=error" (an optional "@p" suffix
    # fires probabilistically). Empty = nothing armed. Re-applied on
    # SIGHUP when the spec changed, so fault drills need no restart.
    failpoints: str = ""
    # runtime fault-injection admin surface: GET/POST /debug/failpoints
    # lists/arms/disarms failpoints (admin_token-gated off-loopback like
    # the rest of the admin surface; the chaos soak and operators drive
    # failpoints through it without env restarts). false removes the
    # routes' function entirely — defense in depth for deployments that
    # never want runtime fault injection reachable.
    failpoints_admin_enabled: bool = True
    # --- streaming pipeline scheduler (banjax_tpu/pipeline/) ---
    # Overlapped tailer→device→effector batching with adaptive sizing and
    # backpressure; false = the reference-shaped synchronous per-batch
    # consume path.
    pipeline_enabled: bool = False
    # bounded ring of in-flight batches; the encode stage blocks (and the
    # admission buffer absorbs) when it is full
    pipeline_ring_size: int = 4
    # per-batch latency target the adaptive sizer steers toward (encode +
    # device + drain, queueing excluded)
    pipeline_latency_budget_ms: float = 250.0
    # admission buffer bound in lines; beyond it the tailer blocks for
    # pipeline_max_block_ms and then the OLDEST buffered lines are shed
    # (counted in PipelineShedLines — bounded memory, never silent loss)
    pipeline_buffer_lines: int = 131072
    pipeline_max_block_ms: float = 250.0
    # synthetic device probe through the idle pipeline every N seconds so
    # a wedged device trips the breaker before the next burst; 0 = off
    # (the default — standalone tests run without a probe thread)
    matcher_probe_seconds: float = 0.0
    # two-phase fused matcher+windows under the pipeline: program A
    # (stateless match) dispatches ahead on the submit stage, the window
    # commit (program B) runs at drain in admission order — no dense
    # bitmap ever crosses the host boundary. false restores the PR 2
    # classic-bitmap split protocol.
    pipeline_fused: bool = True
    # route KafkaReader command messages through the pipeline's admission
    # buffer (same bounded-block/oldest-first-shed accounting as tailer
    # lines); only meaningful when pipeline_enabled is true
    pipeline_kafka: bool = True
    # --- parallel host path ---
    # sharded encode workers for the pipeline's host stage: each
    # admission batch splits into contiguous row shards parsed/gated on
    # a thread pool (the native parse is GIL-free), then merged back in
    # strict line order — output is byte-identical to single-thread.
    # -1 = auto (min(4, cores); 0 on a single-core host), 0 = the
    # single-thread encode path.
    encode_workers: int = -1
    # native C slot manager for the device-windows ip->slot table
    # (native/slotmgr.c): the whole per-distinct-IP assignment loop runs
    # as one C call per batch, with exact Python-path parity.  Auto-falls
    # back to the Python dict path when no C compiler is present; false
    # forces the dict path (the differential oracle).
    slotmgr_native: bool = True
    # resolve-ahead depth for the fused drain commit: 2 dispatches chunk
    # i+1's window program while chunk i's events decode, overlapping the
    # fixed device->host pull instead of serializing the drain thread;
    # 1 restores the serial drain.  A no-op on the single-kernel path
    # (pallas_single_kernel below), which has no program-B dispatch left
    # to overlap.
    drain_resolve_depth: int = 2
    # single-kernel fused match+window commit (matcher/kernels/
    # fused_match_window.py): collapse the fused path's two device
    # programs (A: stateless match, B: window commit) — and the ~65 ms
    # host-side resolve pull between them — into ONE Pallas-anchored
    # program whose overflow handling is gated in-kernel.  "auto"
    # (default) turns it on when the window-scan kernel lowers for the
    # backend (compiled Mosaic on TPU, interpret-mode on CPU — the CI
    # path); "on" forces it (warns + falls back two-program if it can't
    # lower); "off" pins the two-program path (the differential oracle).
    # Note: on this path the 10 s staleness cutoff is enforced at device
    # commit (submit) time instead of effector drain time.
    pallas_single_kernel: str = "auto"
    # take-size bound for command batches in the pipeline's encode stage:
    # commands carry no device timing for the adaptive sizer, so a Kafka
    # command flood is chopped into batches of at most this many messages
    # instead of riding the (much larger) adaptive line bucket and
    # starving line batching.
    pipeline_command_take_max: int = 1024
    # --- observability (banjax_tpu/obs/trace.py, obs/exposition.py) ---
    # ring-buffered pipeline span recorder: each admission batch gets a
    # trace id carried through encode/submit/collect/drain; /debug/trace
    # dumps the ring as Chrome trace_event JSON (Perfetto-loadable).
    # Off by default — the disabled fast path is a single attribute
    # check per call site (bench.py --trace-overhead banks the measured
    # on/off delta).
    trace_enabled: bool = False
    # span slots in the ring (oldest overwritten); ~120 bytes/slot
    trace_ring_size: int = 4096
    # also enter jax.profiler.TraceAnnotation per span (and a
    # StepTraceAnnotation per batch submit) so host spans line up with
    # the XLA/TPU device timeline when a profiler session is active
    trace_jax_annotations: bool = False
    # bearer token for the admin surface (/healthz, /metrics,
    # /debug/trace).  Enforced (constant-time compare) only when the
    # HTTP listener binds a non-loopback address; loopback stays open
    # by default like the reference's 127.0.0.1:8081 surface.
    admin_token: str = ""
    # listener bind address; empty = the reference's hard-coded
    # 127.0.0.1.  Binding non-loopback without admin_token logs a
    # warning (the whole admin surface would be open to the network).
    http_listen_host: str = ""
    # --- decision provenance / SLO engine / flight recorder (obs/) ---
    # provenance ledger (obs/provenance.py): every Decision insertion
    # (static/ua list hit, fired rate-limit ban, Kafka command,
    # challenge failure, dynamic-list expiry) lands in a per-source
    # ring, queryable via GET /decisions/explain?ip=…  On by default:
    # records fire only on decision events, not per log line (bench.py
    # --provenance-overhead banks the measured on/off delta).
    provenance_enabled: bool = True
    provenance_ring_size: int = 2048
    # SLO burn-rate engine (obs/slo.py): multi-window (5 m / 1 h)
    # error-budget burn from non-destructive counter/histogram peeks,
    # exposed as banjax_slo_burn_rate{slo,window} / banjax_slo_breached
    slo_enabled: bool = True
    slo_sample_seconds: float = 15.0  # 0 = no background sampling thread
    # fraction of matcher batches that must land inside
    # pipeline_latency_budget_ms
    slo_batch_latency_target: float = 0.99
    # max acceptable (shed + drain-error) lines per admitted line
    slo_shed_ratio_max: float = 0.001
    # max acceptable drain-staleness drops per processed line
    slo_stale_ratio_max: float = 0.001
    # max acceptable breaker-OPEN seconds per wall second
    slo_breaker_open_ratio_max: float = 0.01
    # max acceptable matcher latency-budget trips per batch
    slo_budget_trip_ratio_max: float = 0.01
    # incident flight recorder (obs/flightrec.py): on any SLO breach,
    # breaker trip, or shed burst, capture a tar-friendly bundle
    # (trace.json / metrics.prom / provenance.json / meta.json) into
    # this directory; empty = disabled.  GET /debug/incidents lists and
    # serves bundles.
    flightrec_dir: str = ""
    flightrec_min_interval_s: float = 60.0  # capture debounce
    flightrec_keep: int = 16  # newest bundles retained
    flightrec_provenance_records: int = 256  # ledger tail per bundle
    # --- traffic introspection plane (obs/sketch.py; /traffic/top) ---
    # device-resident streaming sketches updated in-stream per matcher
    # chunk: a count-min sketch over client-IP hashes (heavy hitters), a
    # HyperLogLog register array (distinct-source cardinality) and
    # per-rule match-pressure accumulators.  Requires
    # matcher_device_windows (the update keys on the window slot ids the
    # device already holds); read-only telemetry — sketch-on output is
    # differentially proven byte-identical to sketch-off.
    traffic_sketch_enabled: bool = True
    traffic_sketch_depth: int = 4       # count-min rows (1..8)
    traffic_sketch_width: int = 8192    # count-min buckets per row
    traffic_sketch_hll_p: int = 12      # HLL registers = 2^p (~1.6% err)
    # sampling interval for the compact device->host pull every consumer
    # (/traffic/top, /metrics, the 29 s line, incident bundles) shares;
    # the sketch is NEVER pulled per batch
    traffic_sketch_pull_seconds: float = 5.0
    traffic_sketch_topk: int = 32       # heavy-hitter heap size
    traffic_sketch_candidates: int = 8192  # host candidate-IP LRU bound
    # --- mega-state tiering (matcher/windows.py, native/shmstate.c) ---
    # sketch-gated slot admission: an IP with no hot/shadow/warm state
    # only claims a device window slot when the count-min estimate of
    # its cumulative request count (device sketch + an exact host-side
    # mirror of refused rows) says it is plausibly over the cheapest
    # rule threshold.  Refused rows still match and rate-limit through
    # the stateless host path — the gate changes WHERE state lives,
    # never the ban multiset; the sketch never undercounts, so gating
    # delays a ban by at most the admission threshold's worth of rows.
    # Requires traffic_sketch_enabled + matcher_device_windows.
    slot_admission_enabled: bool = False
    # minimum sketch estimate (estimate + current-batch rows) at which
    # an unseen IP is admitted.  <= 0 (default) derives it from the
    # loaded ruleset: min(hits_per_interval) + 1 — the smallest count
    # at which any rule could possibly fire.
    slot_admission_min_estimate: int = 0
    # warm tier: on device-slot eviction the victim's per-rule window
    # vector spills into a shared-memory host table (native/shmstate.c
    # wt_*) instead of living in the unbounded Python shadow dict, and
    # refills into a slot on re-admission.  Sized for 10M+ distinct
    # IPs at ~152 bytes + 24/rule per entry.
    warm_tier_enabled: bool = False
    warm_tier_capacity: int = 1 << 20   # entries (rounded up to 2^n)
    # --- multi-host decision fabric (banjax_tpu/fabric/) ---
    # shard the IP keyspace by consistent hash across N banjax processes
    # on real sockets; lines this process does not own forward to the
    # owning shard, decisions replicate to every peer over the Kafka
    # command path, and a dead shard's range is taken over by its ring
    # successors with journal replay (README "Multi-host decision
    # fabric").
    fabric_enabled: bool = False
    # this shard's stable identity on the ring (must appear in
    # fabric_peers); required when fabric_enabled
    fabric_node_id: str = ""
    # host:port this shard's fabric node listens on; required when
    # fabric_enabled (port 0 = ephemeral, harness use only)
    fabric_listen: str = ""
    # peer table: node id -> "host:port" (this node's own id included)
    fabric_peers: Dict[str, str] = dataclasses.field(default_factory=dict)
    # vnodes per node on the consistent-hash ring: more vnodes = smoother
    # range split + smaller takeover shards, at ring-build cost
    fabric_vnodes: int = 64
    # per-send socket timeout on peer links; a send that cannot complete
    # within it counts as a peer failure (retried on the shared backoff)
    fabric_send_timeout_ms: float = 2000.0
    # drain grace between declaring a peer dead and replaying its line
    # journal to the takeover successors
    fabric_takeover_grace_ms: float = 500.0
    # SWIM gossip membership (banjax_tpu/fabric/membership.py): probe
    # one member per interval; <= 0 disables gossip entirely and the
    # fabric falls back to PR 11's static topology (death discovered
    # only by a failed forward)
    fabric_gossip_interval_ms: float = 1000.0
    # how long a SUSPECT member has to produce liveness evidence (direct
    # or indirect ack, or a refutation digest) before it is confirmed
    # DEAD; must exceed the gossip interval when gossip is enabled
    fabric_suspect_timeout_ms: float = 3000.0
    # indirect ping-req relays fanned out when a direct probe fails
    # (0 = suspect immediately on direct-probe failure)
    fabric_indirect_probes: int = 2
    # budget for the planned-leave drain (stop owning, flush, announce
    # LEFT) before the process departs anyway
    fabric_graceful_leave_ms: float = 5000.0
    # --- fabric wire v2 transport (fabric/peer.py LinePipe) ---
    # frames outstanding per peer on the pipelined data path; 0 = the
    # PR 11 synchronous per-group JSON path (the differential oracle —
    # every forward blocks for its ack)
    fabric_inflight_frames: int = 8
    # binary T_LINES_V2 framing on the data path; false forces the JSON
    # fallback even against v2-capable peers (the version handshake
    # still negotiates down automatically against old peers)
    fabric_wire_v2: bool = True
    # send-side coalescing cap: routed groups pack into one data frame
    # up to this many bytes
    fabric_frame_max_bytes: int = 1 << 20
    # co-located shards (loopback/same-host peer address): exchange data
    # frames through a pair of SPSC shared-memory rings
    # (native/shmring.c) instead of loopback TCP
    fabric_shm_enabled: bool = False
    # per-direction ring capacity in bytes (power of two, and must
    # exceed fabric_frame_max_bytes — a frame is written atomically)
    fabric_shm_ring_bytes: int = 1 << 21
    # --- fleet observability plane (banjax_tpu/obs/fleet.py) ---
    # forwarded chunks carry (origin node id, origin trace id) on the
    # wire and owner-side drains open linked fabric.remote-drain spans +
    # feed the provenance origin resolver — the cross-host trace join.
    # Inert without a live tracer/fabric; adds bytes per data frame.
    fabric_trace_propagation: bool = False
    # /metrics?fleet=1 (admin-gated): fan a metrics pull out to every
    # ALIVE member and serve ONE merged exposition with instance labels
    fleet_metrics_enabled: bool = False
    # per-peer budget for one federated metrics pull; a peer that cannot
    # answer within it is served from its cached snapshot (flagged
    # stale) or flagged unreachable — the scrape itself never fails
    fleet_scrape_timeout_ms: float = 750.0
    # incident capture fan-out: an incident on THIS node also collects
    # trace/metrics/provenance/fabric snapshots from every ALIVE peer
    # into the bundle's peers/<node_id>/ tree
    flightrec_fleet_capture: bool = False
    # --- challenge plane (banjax_tpu/challenge/) ---
    # device-batched PoW verification (challenge/verifier.py + matcher/
    # kernels/pow_verify.py): route the sha-inv leading-zero check through
    # the batched sha256 kernel, with the pure-CPU reference verifier as
    # differential oracle and breaker fallback.  false = CPU-only (the
    # reference layout; expiry+hmac always stay on the CPU wire path).
    challenge_device_verify: bool = False
    # max candidate solutions per device dispatch — the bound on the
    # HTTP-path verification queue; a full queue verifies inline on the
    # CPU oracle instead of blocking the worker
    challenge_verify_batch_max: int = 256
    # per-client failed-challenge state bound (challenge/failures.py):
    # at most this many exact per-IP fixed-window entries are held, LRU
    # beyond it with sketch-gated spill/refill — 1M+ concurrent
    # challengers cannot exhaust the host.  0 = unbounded (the
    # reference's dict semantics, exactly).
    challenge_failure_state_max: int = 0
    # --- compiled serving path (httpapi/fastpath.py) ---
    # consult the native shm decision table before the Python decision
    # chain on /auth_request: a table hit serializes the response from
    # byte templates (differential-tested byte-identical); any miss or
    # table fault falls open to the unchanged chain.  false = every
    # request takes the chain (the reference layout).
    serve_fastpath_enabled: bool = True
    # decision-table slots (native/decisiontable.c); rounded up to a
    # power of two.  A full table refuses inserts (counted in
    # banjax_serve_fastpath_table_dropped_total) — refused IPs simply
    # stay chain-served; live decisions are never evicted.
    serve_decision_table_capacity: int = 65536
    # --- kernel-edge ban batching (effectors/ipset_netlink.py) ---
    # coalesce ipset adds into batched AF_NETLINK sends from a bounded
    # background queue, with the per-entry `ipset` subprocess shim as
    # fallback (netlink failure, non-IPv4 entries, open breaker) and as
    # the admin read path.  false = one subprocess fork per ban (the
    # reference layout).  No effect in standalone testing (no kernel).
    ipset_netlink_enabled: bool = True


# yaml key -> required type; mirrors Go yaml.v2 strictness — a wrong-typed
# value (e.g. a quoted "10" for an int field) fails the whole config load
# rather than crashing later at request time
_SCALAR_KEYS = {
    "server_log_file": str, "banning_log_file": str,
    "iptables_ban_seconds": int, "iptables_unbanner_seconds": int,
    "kafka_security_protocol": str, "kafka_ssl_ca": str,
    "kafka_ssl_cert": str, "kafka_ssl_key": str, "kafka_ssl_key_password": str,
    "kafka_command_topic": str, "kafka_report_topic": str,
    "kafka_min_bytes": int, "kafka_max_bytes": int, "kafka_max_wait_ms": int,
    "kafka_dialer_timeout_seconds": int, "kafka_dialer_keep_alive_seconds": int,
    "config_version": str,
    "expiring_decision_ttl_seconds": int, "block_ip_ttl_seconds": int,
    "block_session_ttl_seconds": int,
    "too_many_failed_challenges_interval_seconds": int,
    "too_many_failed_challenges_threshold": int,
    "password_cookie_ttl_seconds": int, "sha_inv_cookie_ttl_seconds": int,
    "sha_inv_expected_zero_bits": int, "hmac_secret": str,
    "gin_log_file": str, "metrics_log_file": str,
    "sha_inv_challenge_html": str, "password_protected_path_html": str,
    "debug": bool, "profile": bool,
    "banning_log_file_temp": str, "disable_kafka": bool,
    "disable_kafka_writer": bool,
    "session_cookie_hmac_secret": str, "session_cookie_ttl_seconds": int,
    "session_cookie_not_verify": bool, "dnet": str, "standalone_testing": bool,
    "matcher": str, "matcher_batch_lines": int, "matcher_max_line_len": int,
    "matcher_backend": str, "matcher_device_windows": bool,
    "matcher_window_capacity": int, "matcher_prefilter": bool,
    "matcher_prefilter_cand_frac": float,
    "matcher_mesh_devices": int, "matcher_mesh_rp": int,
    "matcher_native_parse": bool, "http_workers": int,
    "http_fast_path": bool,
    "breaker_failure_threshold": int, "breaker_recovery_seconds": float,
    "breaker_window_size": int,
    "matcher_latency_budget_ms": float, "failpoints": str,
    "failpoints_admin_enabled": bool,
    "pipeline_enabled": bool, "pipeline_ring_size": int,
    "pipeline_latency_budget_ms": float, "pipeline_buffer_lines": int,
    "pipeline_max_block_ms": float, "matcher_probe_seconds": float,
    "pipeline_fused": bool, "pipeline_kafka": bool,
    "encode_workers": int, "slotmgr_native": bool,
    "drain_resolve_depth": int, "pallas_single_kernel": str,
    "pipeline_command_take_max": int,
    "trace_enabled": bool, "trace_ring_size": int,
    "trace_jax_annotations": bool, "admin_token": str,
    "http_listen_host": str,
    "provenance_enabled": bool, "provenance_ring_size": int,
    "slo_enabled": bool, "slo_sample_seconds": float,
    "slo_batch_latency_target": float, "slo_shed_ratio_max": float,
    "slo_stale_ratio_max": float, "slo_breaker_open_ratio_max": float,
    "slo_budget_trip_ratio_max": float,
    "flightrec_dir": str, "flightrec_min_interval_s": float,
    "flightrec_keep": int, "flightrec_provenance_records": int,
    "traffic_sketch_enabled": bool, "traffic_sketch_depth": int,
    "traffic_sketch_width": int, "traffic_sketch_hll_p": int,
    "traffic_sketch_pull_seconds": float, "traffic_sketch_topk": int,
    "traffic_sketch_candidates": int,
    "slot_admission_enabled": bool, "slot_admission_min_estimate": int,
    "warm_tier_enabled": bool, "warm_tier_capacity": int,
    "fabric_enabled": bool, "fabric_node_id": str, "fabric_listen": str,
    "fabric_vnodes": int, "fabric_send_timeout_ms": float,
    "fabric_takeover_grace_ms": float,
    "fabric_gossip_interval_ms": float, "fabric_suspect_timeout_ms": float,
    "fabric_indirect_probes": int, "fabric_graceful_leave_ms": float,
    "fabric_inflight_frames": int, "fabric_wire_v2": bool,
    "fabric_frame_max_bytes": int, "fabric_shm_enabled": bool,
    "fabric_shm_ring_bytes": int,
    "fabric_trace_propagation": bool, "fleet_metrics_enabled": bool,
    "fleet_scrape_timeout_ms": float, "flightrec_fleet_capture": bool,
    "challenge_device_verify": bool, "challenge_verify_batch_max": int,
    "challenge_failure_state_max": int,
    "serve_fastpath_enabled": bool, "serve_decision_table_capacity": int,
    "ipset_netlink_enabled": bool,
}

_DICT_OR_LIST_KEYS = {
    "kafka_brokers", "per_site_decision_lists", "global_decision_lists",
    "password_hashes", "password_protected_paths",
    "password_protected_path_exceptions", "password_hash_roaming",
    "password_persite_cookie_ttl_seconds", "use_user_agent_in_cookie",
    "sites_to_block_ip_ttl_seconds", "sites_to_block_session_ttl_seconds",
    "sitewide_sha_inv_list", "disable_logging",
    "sites_to_disable_baskerville", "sha_inv_path_exceptions",
    "dnet_to_partition", "per_site_user_agent_decision_lists",
    "global_user_agent_decision_lists", "fabric_peers",
}


def config_from_yaml_text(text: str, standalone_testing_default: bool = False) -> Config:
    """Parse YAML text into a Config, compiling all rate-limit rules.

    Mirrors the yaml.Unmarshal step of config_holder.go:90 with
    RegexWithRate.UnmarshalYAML (config.go:96-131): any bad regex or bad
    decision string raises, failing the whole load.

    `standalone_testing_default` reproduces config_holder.go:89-90 ordering:
    the CLI flag seeds the field *before* unmarshal, so an explicit YAML
    `standalone_testing:` key wins over the flag.
    """
    raw = yaml.safe_load(text) or {}
    if not isinstance(raw, dict):
        raise ValueError("config root must be a mapping")

    cfg = Config()
    cfg.standalone_testing = standalone_testing_default

    for key, typ in _SCALAR_KEYS.items():
        if key in raw and raw[key] is not None:
            value = raw[key]
            if typ is int:
                if isinstance(value, bool) or not isinstance(value, int):
                    raise ValueError(f"config key {key}: expected int, got {value!r}")
            elif typ is bool:
                if not isinstance(value, bool):
                    raise ValueError(f"config key {key}: expected bool, got {value!r}")
            elif typ is float:
                # YAML parses `1` as int: accept and coerce (bools excluded)
                if isinstance(value, bool) or not isinstance(value, (int, float)):
                    raise ValueError(f"config key {key}: expected float, got {value!r}")
                value = float(value)
            elif not isinstance(value, typ):
                raise ValueError(f"config key {key}: expected {typ.__name__}, got {value!r}")
            setattr(cfg, key, value)
    for key in _DICT_OR_LIST_KEYS:
        if key in raw and raw[key] is not None:
            value = raw[key]
            expected = list if key == "kafka_brokers" else dict
            if not isinstance(value, expected):
                raise ValueError(
                    f"config key {key}: expected {expected.__name__}, got {value!r}"
                )
            setattr(cfg, key, value)

    for entry in raw.get("regexes_with_rates") or []:
        cfg.regexes_with_rates.append(RegexWithRate.from_yaml_dict(entry))
    for site, entries in (raw.get("per_site_regexes_with_rates") or {}).items():
        cfg.per_site_regexes_with_rates[site] = [
            RegexWithRate.from_yaml_dict(e) for e in (entries or [])
        ]

    if cfg.matcher not in ("cpu", "tpu"):
        raise ValueError(f"config key matcher: expected cpu|tpu, got {cfg.matcher!r}")
    if cfg.matcher_backend not in ("auto", "xla", "pallas", "pallas-interpret"):
        raise ValueError(
            "config key matcher_backend: expected "
            f"auto|xla|pallas|pallas-interpret, got {cfg.matcher_backend!r}"
        )
    if cfg.matcher_window_capacity < 0:
        raise ValueError(
            "config key matcher_window_capacity: expected 0 (auto-size) or "
            f"a positive slot count, got {cfg.matcher_window_capacity}"
        )
    if cfg.matcher_mesh_devices < 0 or cfg.matcher_mesh_rp < 0:
        raise ValueError(
            "config keys matcher_mesh_devices/matcher_mesh_rp: expected "
            f"non-negative, got {cfg.matcher_mesh_devices}/{cfg.matcher_mesh_rp}"
        )
    if (
        cfg.matcher_mesh_devices > 0
        and cfg.matcher_mesh_rp > 0
        and cfg.matcher_mesh_devices % cfg.matcher_mesh_rp != 0
    ):
        raise ValueError(
            f"config key matcher_mesh_rp: {cfg.matcher_mesh_rp} does not "
            f"divide matcher_mesh_devices {cfg.matcher_mesh_devices}"
        )
    if cfg.breaker_failure_threshold < 1:
        raise ValueError(
            "config key breaker_failure_threshold: expected >= 1, got "
            f"{cfg.breaker_failure_threshold}"
        )
    if cfg.breaker_recovery_seconds < 0 or cfg.matcher_latency_budget_ms < 0:
        raise ValueError(
            "config keys breaker_recovery_seconds/matcher_latency_budget_ms: "
            f"expected non-negative, got {cfg.breaker_recovery_seconds}/"
            f"{cfg.matcher_latency_budget_ms}"
        )
    if cfg.breaker_window_size != 0 and (
        cfg.breaker_window_size < cfg.breaker_failure_threshold
    ):
        raise ValueError(
            "config key breaker_window_size: expected 0 (off) or >= "
            f"breaker_failure_threshold ({cfg.breaker_failure_threshold}), "
            f"got {cfg.breaker_window_size}"
        )
    if cfg.pipeline_ring_size < 1:
        raise ValueError(
            "config key pipeline_ring_size: expected >= 1, got "
            f"{cfg.pipeline_ring_size}"
        )
    if cfg.pipeline_latency_budget_ms <= 0:
        raise ValueError(
            "config key pipeline_latency_budget_ms: expected positive, got "
            f"{cfg.pipeline_latency_budget_ms}"
        )
    if cfg.pipeline_buffer_lines < 1:
        raise ValueError(
            "config key pipeline_buffer_lines: expected >= 1, got "
            f"{cfg.pipeline_buffer_lines}"
        )
    if cfg.pipeline_max_block_ms < 0 or cfg.matcher_probe_seconds < 0:
        raise ValueError(
            "config keys pipeline_max_block_ms/matcher_probe_seconds: "
            f"expected non-negative, got {cfg.pipeline_max_block_ms}/"
            f"{cfg.matcher_probe_seconds}"
        )
    if cfg.encode_workers < -1:
        raise ValueError(
            "config key encode_workers: expected -1 (auto), 0 (single-"
            f"thread) or a positive worker count, got {cfg.encode_workers}"
        )
    if cfg.drain_resolve_depth < 1:
        raise ValueError(
            "config key drain_resolve_depth: expected >= 1, got "
            f"{cfg.drain_resolve_depth}"
        )
    if cfg.pallas_single_kernel not in ("auto", "on", "off"):
        raise ValueError(
            "config key pallas_single_kernel: expected auto|on|off, got "
            f"{cfg.pallas_single_kernel!r}"
        )
    if cfg.pipeline_command_take_max < 1:
        raise ValueError(
            "config key pipeline_command_take_max: expected >= 1, got "
            f"{cfg.pipeline_command_take_max}"
        )
    if cfg.trace_ring_size < 1:
        raise ValueError(
            "config key trace_ring_size: expected >= 1, got "
            f"{cfg.trace_ring_size}"
        )
    if cfg.provenance_ring_size < 1:
        raise ValueError(
            "config key provenance_ring_size: expected >= 1, got "
            f"{cfg.provenance_ring_size}"
        )
    if not 0.0 < cfg.slo_batch_latency_target < 1.0:
        raise ValueError(
            "config key slo_batch_latency_target: expected a fraction in "
            f"(0, 1), got {cfg.slo_batch_latency_target}"
        )
    for _k in ("slo_shed_ratio_max", "slo_stale_ratio_max",
               "slo_breaker_open_ratio_max", "slo_budget_trip_ratio_max"):
        if getattr(cfg, _k) <= 0:
            raise ValueError(
                f"config key {_k}: expected positive, got {getattr(cfg, _k)}"
            )
    if cfg.slo_sample_seconds < 0 or cfg.flightrec_min_interval_s < 0:
        raise ValueError(
            "config keys slo_sample_seconds/flightrec_min_interval_s: "
            f"expected non-negative, got {cfg.slo_sample_seconds}/"
            f"{cfg.flightrec_min_interval_s}"
        )
    if not 1 <= cfg.traffic_sketch_depth <= 8:
        raise ValueError(
            "config key traffic_sketch_depth: expected 1..8, got "
            f"{cfg.traffic_sketch_depth}"
        )
    if cfg.traffic_sketch_width < 16:
        raise ValueError(
            "config key traffic_sketch_width: expected >= 16, got "
            f"{cfg.traffic_sketch_width}"
        )
    if not 4 <= cfg.traffic_sketch_hll_p <= 16:
        raise ValueError(
            "config key traffic_sketch_hll_p: expected 4..16, got "
            f"{cfg.traffic_sketch_hll_p}"
        )
    if cfg.traffic_sketch_pull_seconds < 0:
        raise ValueError(
            "config key traffic_sketch_pull_seconds: expected "
            f"non-negative, got {cfg.traffic_sketch_pull_seconds}"
        )
    if cfg.traffic_sketch_topk < 1 or cfg.traffic_sketch_candidates < 1:
        raise ValueError(
            "config keys traffic_sketch_topk/traffic_sketch_candidates: "
            f"expected >= 1, got {cfg.traffic_sketch_topk}/"
            f"{cfg.traffic_sketch_candidates}"
        )
    if cfg.slot_admission_enabled and not (
        cfg.traffic_sketch_enabled and cfg.matcher_device_windows
    ):
        raise ValueError(
            "config key slot_admission_enabled: requires "
            "traffic_sketch_enabled and matcher_device_windows"
        )
    if cfg.warm_tier_enabled and not cfg.matcher_device_windows:
        raise ValueError(
            "config key warm_tier_enabled: requires matcher_device_windows"
        )
    if cfg.warm_tier_capacity < 1:
        raise ValueError(
            "config key warm_tier_capacity: expected >= 1, got "
            f"{cfg.warm_tier_capacity}"
        )
    if cfg.fabric_vnodes < 1:
        raise ValueError(
            f"config key fabric_vnodes: expected >= 1, got {cfg.fabric_vnodes}"
        )
    if cfg.fabric_send_timeout_ms <= 0 or cfg.fabric_takeover_grace_ms < 0:
        raise ValueError(
            "config keys fabric_send_timeout_ms/fabric_takeover_grace_ms: "
            f"expected positive/non-negative, got {cfg.fabric_send_timeout_ms}"
            f"/{cfg.fabric_takeover_grace_ms}"
        )
    if cfg.fabric_enabled:
        if not cfg.fabric_node_id or not cfg.fabric_listen:
            raise ValueError(
                "config key fabric_enabled: requires fabric_node_id and "
                "fabric_listen"
            )
        if cfg.fabric_peers and cfg.fabric_node_id not in cfg.fabric_peers:
            raise ValueError(
                f"config key fabric_peers: missing this node's own id "
                f"{cfg.fabric_node_id!r}"
            )
    if (
        cfg.fabric_gossip_interval_ms > 0
        and cfg.fabric_suspect_timeout_ms <= cfg.fabric_gossip_interval_ms
    ):
        raise ValueError(
            "config key fabric_suspect_timeout_ms: must exceed "
            f"fabric_gossip_interval_ms, got {cfg.fabric_suspect_timeout_ms}"
            f" <= {cfg.fabric_gossip_interval_ms}"
        )
    if cfg.fabric_indirect_probes < 0:
        raise ValueError(
            "config key fabric_indirect_probes: expected >= 0, got "
            f"{cfg.fabric_indirect_probes}"
        )
    if cfg.fabric_graceful_leave_ms < 0:
        raise ValueError(
            "config key fabric_graceful_leave_ms: expected >= 0, got "
            f"{cfg.fabric_graceful_leave_ms}"
        )
    if cfg.fabric_inflight_frames < 0:
        raise ValueError(
            "config key fabric_inflight_frames: expected >= 0 (0 = "
            f"synchronous JSON path), got {cfg.fabric_inflight_frames}"
        )
    if cfg.fabric_frame_max_bytes < 4096:
        raise ValueError(
            "config key fabric_frame_max_bytes: expected >= 4096, got "
            f"{cfg.fabric_frame_max_bytes}"
        )
    if cfg.fabric_shm_ring_bytes & (cfg.fabric_shm_ring_bytes - 1) or \
            cfg.fabric_shm_ring_bytes < 4096:
        raise ValueError(
            "config key fabric_shm_ring_bytes: expected a power of two "
            f">= 4096, got {cfg.fabric_shm_ring_bytes}"
        )
    if (
        cfg.fabric_shm_enabled
        and cfg.fabric_shm_ring_bytes <= cfg.fabric_frame_max_bytes
    ):
        raise ValueError(
            "config key fabric_shm_ring_bytes: must exceed "
            "fabric_frame_max_bytes (a frame is ring-written atomically), "
            f"got {cfg.fabric_shm_ring_bytes} <= {cfg.fabric_frame_max_bytes}"
        )
    if cfg.fleet_scrape_timeout_ms <= 0:
        raise ValueError(
            "config key fleet_scrape_timeout_ms: expected positive, got "
            f"{cfg.fleet_scrape_timeout_ms}"
        )
    if cfg.flightrec_keep < 1 or cfg.flightrec_provenance_records < 1:
        raise ValueError(
            "config keys flightrec_keep/flightrec_provenance_records: "
            f"expected >= 1, got {cfg.flightrec_keep}/"
            f"{cfg.flightrec_provenance_records}"
        )
    if cfg.challenge_verify_batch_max < 1:
        raise ValueError(
            "config key challenge_verify_batch_max: expected >= 1, got "
            f"{cfg.challenge_verify_batch_max}"
        )
    if cfg.challenge_failure_state_max < 0:
        raise ValueError(
            "config key challenge_failure_state_max: expected 0 (unbounded) "
            f"or a positive entry count, got {cfg.challenge_failure_state_max}"
        )
    if cfg.serve_decision_table_capacity < 1:
        raise ValueError(
            "config key serve_decision_table_capacity: expected >= 1 "
            "(rounded up to a power of two), got "
            f"{cfg.serve_decision_table_capacity}"
        )

    return cfg


def default_hostname() -> str:
    try:
        return socket.gethostname()
    except OSError:
        return "unknown-hostname"


def now_unix() -> int:
    return int(time.time())
