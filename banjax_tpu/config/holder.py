"""Hot-reloadable config holder.

Reference behavior: /root/reference/internal/config_holder.go:27-161 — an
atomically-swapped immutable Config snapshot. `reload()` re-reads the file
preserving restart_time; load() embeds the two challenge HTML pages (or reads
them from configured paths), validates required keys (server_log_file,
iptables_ban_seconds, kafka_brokers), and applies standalone-testing overrides
(disable Kafka, swap log paths to the testing files).

In CPython an attribute read/write of an object reference is atomic under the
GIL, which gives the same read-mostly snapshot semantics as Go's
atomic.Pointer. Callers must take a local `config = holder.get()` once per
request/line and use only that snapshot, exactly as the Go code does.

When the TPU matcher is enabled a reload also recompiles the rule NFA and
re-uploads the transition tensors (handled by the matcher runner observing the
snapshot generation counter).
"""

from __future__ import annotations

import logging
import threading
import time
from pathlib import Path
from banjax_tpu.config.schema import Config, config_from_yaml_text, default_hostname

log = logging.getLogger(__name__)

_PAGES_DIR = Path(__file__).resolve().parent.parent / "httpapi" / "pages"


def _load_config(
    path: str, restart_time: int, standalone_testing: bool, debug: bool
) -> Config:
    """Port of config_holder.go load() (:68-161)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()

    config = config_from_yaml_text(text, standalone_testing_default=standalone_testing)
    config.restart_time = restart_time
    config.reload_time = int(time.time())
    config.hostname = default_hostname()

    if config.sha_inv_challenge_html:
        log.info("INIT: reading SHA-inverse challenge HTML from %s", config.sha_inv_challenge_html)
        config.challenger_bytes = Path(config.sha_inv_challenge_html).read_bytes()
    else:
        config.challenger_bytes = (_PAGES_DIR / "sha-inverse-challenge.html").read_bytes()

    if config.password_protected_path_html:
        log.info("INIT: reading password page HTML from %s", config.password_protected_path_html)
        config.password_page_bytes = Path(config.password_protected_path_html).read_bytes()
    else:
        config.password_page_bytes = (_PAGES_DIR / "password-protected-path.html").read_bytes()

    if not config.debug and debug:
        config.debug = True

    if config.standalone_testing:
        # config_holder.go:139-145 — make the process self-hosting for tests.
        config.disable_kafka = True
        config.server_log_file = "testing-log-file.txt"
        config.banning_log_file = "banning-log-file.txt"

    if not config.server_log_file:
        raise ValueError("config needs server_log_file")
    if not config.iptables_ban_seconds:
        raise ValueError("config needs iptables_ban_seconds")
    if not config.kafka_brokers:
        raise ValueError("config needs kafka_brokers")

    return config


class ConfigHolder:
    """Snapshot holder; `get()` returns the latest immutable Config."""

    def __init__(self, path: str, standalone_testing: bool = False, debug: bool = False):
        self._path = path
        self._lock = threading.Lock()  # serializes reloads, not reads
        restart_time = int(time.time())
        self._config = _load_config(path, restart_time, standalone_testing, debug)
        self.generation = 0  # bumped on every successful reload

    @property
    def path(self) -> str:
        return self._path

    def get(self) -> Config:
        return self._config

    def reload(self) -> None:
        """Re-read the config file, preserving restart_time (config_holder.go:55-66)."""
        with self._lock:
            old = self._config
            new = _load_config(
                self._path, old.restart_time, old.standalone_testing, old.debug
            )
            self._config = new
            self.generation += 1
