"""Length-prefixed fabric socket frames.

One frame = 4-byte big-endian length, 1-byte type, JSON payload.  The
length covers the type byte + payload, so a reader can pre-allocate
and a torn stream fails loudly (oversized or truncated frames raise
instead of desynchronizing).  Every exchange is a synchronous
request -> response pair on one connection; the client serializes
requests under its own lock, which is what makes the LINES -> ACK
accounting exact (a chunk is acked at most once, and the ack carries
the receiving shard's admitted count).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Tuple

MAX_FRAME_BYTES = 32 << 20  # one scenario chunk is ~32 KiB; 32 MiB is sabotage

_HEADER = struct.Struct("!IB")

# frame types — request/response pairs share a row
T_HELLO = 1        # -> T_HELLO_R     driver/peer handshake, topology push
T_HELLO_R = 2
T_LINES = 3        # -> T_ACK         log lines to route/process
T_ACK = 4
T_STATS = 5        # -> T_STATS_R     scheduler + fabric counters + ban log
T_STATS_R = 6
T_PING = 7         # -> T_PONG        liveness probe
T_PONG = 8
T_SNAPSHOT = 9     # -> T_SNAPSHOT_R  dump expiring decisions (rejoin source)
T_SNAPSHOT_R = 10
T_SYNC = 11        # -> T_ACK         apply a decision snapshot idempotently
T_PEER_DOWN = 12   # -> T_ACK         membership change: mark peer dead
T_PEER_UP = 13     # -> T_ACK         membership change: peer rejoined
T_FLUSH = 14       # -> T_ACK         drain the pipeline to quiescence
T_SHUTDOWN = 15    # -> T_ACK         clean exit
T_ERR = 16         # any request may answer this; payload has "error"
T_GOSSIP_PING = 17     # -> T_GOSSIP_ACK   SWIM direct probe, digest rides
T_GOSSIP_ACK = 18
T_GOSSIP_PING_REQ = 19  # -> T_GOSSIP_ACK  SWIM indirect probe via a relay
T_JOIN = 20        # -> T_JOIN_R      announce + membership/snapshot pull
T_JOIN_R = 21
T_LEAVE = 22       # -> T_ACK         admin: graceful drain, then depart
T_FAILPOINT = 23   # -> T_ACK         harness: arm/disarm a failpoint


class FrameError(OSError):
    """Malformed or oversized frame — the connection is unusable."""


def send_frame(sock: socket.socket, ftype: int, payload: Dict[str, Any]) -> None:
    body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    if 1 + len(body) > MAX_FRAME_BYTES:
        raise FrameError(f"frame too large: {len(body)} bytes")
    sock.sendall(_HEADER.pack(1 + len(body), ftype) + body)


def recv_frame(sock: socket.socket) -> Tuple[int, Dict[str, Any]]:
    header = _recv_exact(sock, _HEADER.size)
    length, ftype = _HEADER.unpack(header)
    if length < 1 or length > MAX_FRAME_BYTES:
        raise FrameError(f"bad frame length {length}")
    body = _recv_exact(sock, length - 1, committed=True)
    try:
        payload = json.loads(body.decode("utf-8")) if length > 1 else {}
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise FrameError(f"undecodable frame payload: {exc}") from exc
    if not isinstance(payload, dict):
        raise FrameError("frame payload must be a JSON object")
    return ftype, payload


def _recv_exact(
    sock: socket.socket, n: int, committed: bool = False
) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except socket.timeout:
            if got or committed:
                # a stall mid-frame would desynchronize the stream if
                # surfaced as an idle timeout — fail the connection
                raise FrameError(
                    f"timeout mid-frame ({got}/{n} bytes)"
                ) from None
            raise
        if not chunk:
            raise FrameError(f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
