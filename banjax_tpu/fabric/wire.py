"""Length-prefixed fabric socket frames.

One frame = 4-byte big-endian length, 1-byte type, then the body.  The
length covers the type byte + body, so a reader can pre-allocate and a
torn stream fails loudly (oversized or truncated frames raise instead
of desynchronizing).

Two body encodings share that header:

  * **JSON** (wire v1) — every control/gossip/membership frame, the
    T_ACK response, and the negotiated fallback for peers that predate
    the binary data path.  A synchronous request -> response exchange
    per frame; the ack accounting is exact because the server answers
    frames in order on one connection.
  * **binary v2** (`T_LINES_V2`) — the data-path hot frame.  Zero JSON
    on the hot path: a `u64` journal sequence, a `u8` flags byte
    (bit 0 = replay, bit 1 = origin section present), a `u32` line
    count, a `(count+1)`-entry `u32` offset table and the raw UTF-8
    line blob.  `decode_lines_v2` validates the offset table strictly
    (monotone, zero-based, last entry == blob length) so a corrupt
    frame raises `FrameError` instead of delivering garbled lines.

    With bit 1 set an **origin section** follows the blob: the sender's
    node id, the tailer-read monotonic timestamp of the oldest line in
    the frame, and a run table of `(trace_id u64, count u32)` pairs
    mapping contiguous line runs back to the admission trace that
    routed them on the origin shard (obs/fleet.py joins a ban on the
    owner back to that trace).  The run counts must sum exactly to the
    line count — a frame that lies about its runs fails decode loudly,
    like every other v2 invariant.

`T_VERSION` is the connect-time handshake: a v2 sender probes with
`{"wire": 2}`; a v2 node answers `T_VERSION_R` with its wire version,
whether it accepts shm-ring attaches, and whether it understands the
origin section (`"trace": true` — senders only set bit 1 against a
peer that advertised it), while an old node answers T_ERR ("unhandled
frame type") — the sender then negotiates down to per-frame JSON
losslessly.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

MAX_FRAME_BYTES = 32 << 20  # one scenario chunk is ~32 KiB; 32 MiB is sabotage
MAX_V2_LINES = 1 << 22      # offset-table sanity bound, far above any frame

WIRE_VERSION = 2

_HEADER = struct.Struct("!IB")
_V2_FIXED = struct.Struct("!QBI")  # seq u64, flags u8, count u32
_V2_REPLAY = 0x01
_V2_TRACE = 0x02   # origin section follows the line blob
# origin section: node_len u16, t_read f64 (monotonic s), run_count u32
_V2_ORIGIN_FIXED = struct.Struct("!HdI")
_V2_RUN = struct.Struct("!QI")     # origin trace_id u64, line count u32
MAX_V2_NODE_LEN = 256

# frame types — request/response pairs share a row
T_HELLO = 1        # -> T_HELLO_R     driver/peer handshake, topology push
T_HELLO_R = 2
T_LINES = 3        # -> T_ACK         log lines to route/process
T_ACK = 4
T_STATS = 5        # -> T_STATS_R     scheduler + fabric counters + ban log
T_STATS_R = 6
T_PING = 7         # -> T_PONG        liveness probe
T_PONG = 8
T_SNAPSHOT = 9     # -> T_SNAPSHOT_R  dump expiring decisions (rejoin source)
T_SNAPSHOT_R = 10
T_SYNC = 11        # -> T_ACK         apply a decision snapshot idempotently
T_PEER_DOWN = 12   # -> T_ACK         membership change: mark peer dead
T_PEER_UP = 13     # -> T_ACK         membership change: peer rejoined
T_FLUSH = 14       # -> T_ACK         drain the pipeline to quiescence
T_SHUTDOWN = 15    # -> T_ACK         clean exit
T_ERR = 16         # any request may answer this; payload has "error"
T_GOSSIP_PING = 17     # -> T_GOSSIP_ACK   SWIM direct probe, digest rides
T_GOSSIP_ACK = 18
T_GOSSIP_PING_REQ = 19  # -> T_GOSSIP_ACK  SWIM indirect probe via a relay
T_JOIN = 20        # -> T_JOIN_R      announce + membership/snapshot pull
T_JOIN_R = 21
T_LEAVE = 22       # -> T_ACK         admin: graceful drain, then depart
T_FAILPOINT = 23   # -> T_ACK         harness: arm/disarm a failpoint
T_LINES_V2 = 24    # -> T_ACK         binary batched line frame (wire v2)
T_VERSION = 26     # -> T_VERSION_R   wire-version handshake at connect
T_VERSION_R = 27
T_RING_ATTACH = 28  # -> T_ACK        co-located peer: switch to shm rings
T_FLIGHTREC = 29   # -> T_FLIGHTREC_R fleet incident capture: obs snapshot
T_FLIGHTREC_R = 30
T_EXPLAIN = 31     # -> T_EXPLAIN_R   cross-shard /decisions/explain proxy
T_EXPLAIN_R = 32


class FrameError(OSError):
    """Malformed or oversized frame — the connection is unusable."""


@dataclasses.dataclass(frozen=True)
class LinesV2:
    """A decoded T_LINES_V2 frame: the journal seq the ack must echo,
    the replay flag, the batched lines, and — when the sender set the
    trace bit — the origin section (which shard tailed these lines,
    when its tailer read them, and which admission trace routed each
    contiguous run)."""

    seq: int
    replay: bool
    lines: Tuple[str, ...]
    origin_node: str = ""
    origin_t_read: float = 0.0
    origin_runs: Tuple[Tuple[int, int], ...] = ()


def encode_lines_v2(
    seq: int,
    lines: Sequence[str],
    replay: bool = False,
    origin_node: str = "",
    origin_t_read: float = 0.0,
    origin_runs: Optional[Sequence[Tuple[int, int]]] = None,
) -> bytes:
    """One complete T_LINES_V2 frame (header included), ready for
    sendall/ring-write.  Many routed groups coalesce into one call —
    the encoder only sees the flattened line list (plus, with
    `origin_node`, the per-run trace table covering it)."""
    blobs = [ln.encode("utf-8") for ln in lines]
    offsets: List[int] = [0]
    for b in blobs:
        offsets.append(offsets[-1] + len(b))
    flags = _V2_REPLAY if replay else 0
    parts = [
        _V2_FIXED.pack(seq, flags, len(blobs)),
        struct.pack(f"!{len(offsets)}I", *offsets),
        b"".join(blobs),
    ]
    if origin_node:
        node_b = origin_node.encode("utf-8")
        if len(node_b) > MAX_V2_NODE_LEN:
            raise FrameError(f"origin node id too long: {len(node_b)} bytes")
        runs = list(origin_runs) if origin_runs else [(0, len(blobs))]
        if sum(c for _t, c in runs) != len(blobs):
            raise FrameError(
                "origin run counts do not cover the line count"
            )
        parts[0] = _V2_FIXED.pack(seq, flags | _V2_TRACE, len(blobs))
        parts.append(_V2_ORIGIN_FIXED.pack(
            len(node_b), float(origin_t_read), len(runs)
        ))
        parts.append(node_b)
        parts.extend(_V2_RUN.pack(int(t), int(c)) for t, c in runs)
    body = b"".join(parts)
    if 1 + len(body) > MAX_FRAME_BYTES:
        raise FrameError(f"frame too large: {len(body)} bytes")
    return _HEADER.pack(1 + len(body), T_LINES_V2) + body


def _decode_origin(
    body: bytes, start: int, count: int
) -> Tuple[str, float, Tuple[Tuple[int, int], ...]]:
    """Strict origin-section decode (trace bit set): exact length, node
    UTF-8, run counts summing to the frame's line count."""
    if len(body) < start + _V2_ORIGIN_FIXED.size:
        raise FrameError("v2 origin section truncated")
    node_len, t_read, run_count = _V2_ORIGIN_FIXED.unpack_from(body, start)
    if node_len > MAX_V2_NODE_LEN:
        raise FrameError(f"v2 origin node length {node_len} oversized")
    if run_count > max(1, count):
        raise FrameError(
            f"v2 origin run count {run_count} exceeds line count {count}"
        )
    pos = start + _V2_ORIGIN_FIXED.size
    end = pos + node_len + run_count * _V2_RUN.size
    if len(body) != end:
        raise FrameError(
            f"v2 origin section length mismatch: need {end - start}, "
            f"have {len(body) - start}"
        )
    try:
        node = body[pos:pos + node_len].decode("utf-8")
    except UnicodeDecodeError as exc:
        raise FrameError(f"v2 origin node not UTF-8: {exc}") from exc
    pos += node_len
    runs = tuple(
        _V2_RUN.unpack_from(body, pos + i * _V2_RUN.size)
        for i in range(run_count)
    )
    if sum(c for _t, c in runs) != count:
        raise FrameError(
            "v2 origin run counts do not cover the line count"
        )
    return node, t_read, runs


def decode_lines_v2(body: bytes) -> LinesV2:
    """Strict decode — any torn/truncated/inconsistent frame raises
    FrameError (the fuzz suite in tests/unit/test_fabric_wire_v2.py
    drives every branch here)."""
    if len(body) < _V2_FIXED.size:
        raise FrameError(f"v2 frame truncated: {len(body)} byte body")
    seq, flags, count = _V2_FIXED.unpack_from(body, 0)
    if count > MAX_V2_LINES:
        raise FrameError(f"v2 frame count {count} exceeds {MAX_V2_LINES}")
    table_end = _V2_FIXED.size + 4 * (count + 1)
    if len(body) < table_end:
        raise FrameError(
            f"v2 offset table truncated: need {table_end}, have {len(body)}"
        )
    offsets = struct.unpack_from(f"!{count + 1}I", body, _V2_FIXED.size)
    if offsets[0] != 0:
        raise FrameError(f"v2 offset table must start at 0, got {offsets[0]}")
    origin_node, origin_t_read = "", 0.0
    origin_runs: Tuple[Tuple[int, int], ...] = ()
    if flags & _V2_TRACE:
        blob = body[table_end:table_end + offsets[-1]]
        if len(blob) != offsets[-1]:
            raise FrameError(
                f"v2 blob truncated: table says {offsets[-1]}, "
                f"have {len(blob)} bytes"
            )
        origin_node, origin_t_read, origin_runs = _decode_origin(
            body, table_end + offsets[-1], count
        )
    else:
        blob = body[table_end:]
        if offsets[-1] != len(blob):
            raise FrameError(
                f"v2 blob length mismatch: table says {offsets[-1]}, "
                f"blob is {len(blob)} bytes"
            )
    prev = 0
    for off in offsets:
        if off < prev:
            raise FrameError("v2 offset table not monotone")
        prev = off
    try:
        lines = tuple(
            blob[offsets[i]:offsets[i + 1]].decode("utf-8")
            for i in range(count)
        )
    except UnicodeDecodeError as exc:
        raise FrameError(f"v2 line blob not UTF-8: {exc}") from exc
    return LinesV2(
        seq=seq, replay=bool(flags & _V2_REPLAY), lines=lines,
        origin_node=origin_node, origin_t_read=origin_t_read,
        origin_runs=origin_runs,
    )


def encode_frame(ftype: int, payload: Dict[str, Any]) -> bytes:
    """One complete JSON frame (header included) — the send_frame body
    without the socket, for transports that write bytes (shm rings)."""
    body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
    if 1 + len(body) > MAX_FRAME_BYTES:
        raise FrameError(f"frame too large: {len(body)} bytes")
    return _HEADER.pack(1 + len(body), ftype) + body


def decode_body(ftype: int, body: bytes) -> Union[Dict[str, Any], LinesV2]:
    """Decode a frame body by type: LinesV2 for the binary data frame,
    a JSON object for everything else."""
    if ftype == T_LINES_V2:
        return decode_lines_v2(body)
    try:
        payload = json.loads(body.decode("utf-8")) if body else {}
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise FrameError(f"undecodable frame payload: {exc}") from exc
    if not isinstance(payload, dict):
        raise FrameError("frame payload must be a JSON object")
    return payload


def send_frame(sock: socket.socket, ftype: int, payload: Dict[str, Any]) -> None:
    sock.sendall(encode_frame(ftype, payload))


def recv_frame(sock: socket.socket) -> Tuple[int, Dict[str, Any]]:
    """Receive one JSON frame.  A binary T_LINES_V2 arriving here is a
    protocol violation (the caller negotiated v1) — FrameError."""
    ftype, payload = recv_frame_any(sock)
    if not isinstance(payload, dict):
        raise FrameError(f"unexpected binary frame type {ftype}")
    return ftype, payload


def recv_frame_any(
    sock: socket.socket,
) -> Tuple[int, Union[Dict[str, Any], LinesV2]]:
    """Receive one frame of either encoding (a v2-aware server's read
    loop)."""
    header = _recv_exact(sock, _HEADER.size)
    length, ftype = _HEADER.unpack(header)
    if length < 1 or length > MAX_FRAME_BYTES:
        raise FrameError(f"bad frame length {length}")
    body = _recv_exact(sock, length - 1, committed=True)
    return ftype, decode_body(ftype, body)


def _recv_exact(
    sock: socket.socket, n: int, committed: bool = False
) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except socket.timeout:
            if got or committed:
                # a stall mid-frame would desynchronize the stream if
                # surfaced as an idle timeout — fail the connection
                raise FrameError(
                    f"timeout mid-frame ({got}/{n} bytes)"
                ) from None
            raise
        if not chunk:
            raise FrameError(f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
