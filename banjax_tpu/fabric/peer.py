"""Client side of a fabric peer link.

One persistent connection per peer, request/response serialized under a
lock.  Every send attempt passes the `fabric.send` failpoint, carries a
per-send socket timeout (`fabric_send_timeout_ms`), and on failure the
connection is torn down and retried on the shared reconnect backoff
(resilience/backoff.py — the same policy as the kafka and tailer
loops).  A per-peer circuit breaker turns repeated failures into a fast
PeerUnavailable so the router can start a takeover instead of timing
out on every chunk for a dead shard.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, Optional, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.backoff import Backoff, reconnect_backoff
from banjax_tpu.resilience.breaker import CircuitBreaker


class PeerUnavailable(OSError):
    """The peer did not answer within the retry budget (or its breaker
    is open) — the caller should treat the shard as dead."""


class PeerClient:
    def __init__(
        self,
        peer_id: str,
        host: str,
        port: int,
        send_timeout_ms: float = 2000.0,
        max_attempts: int = 3,
        backoff: Optional[Backoff] = None,
        breaker: Optional[CircuitBreaker] = None,
        stop: Optional[threading.Event] = None,
    ):
        self.peer_id = peer_id
        self.host = host
        self.port = int(port)
        self.send_timeout_s = float(send_timeout_ms) / 1000.0
        self.max_attempts = int(max_attempts)
        # short cap: a fabric peer link recovers or fails over in
        # hundreds of ms, not the 30 s a kafka broker is allowed
        self.backoff = backoff or reconnect_backoff(cap=1.0, base=0.05)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=max(2, max_attempts),
            recovery_seconds=2.0,
            name=f"fabric.peer.{peer_id}",
        )
        self._stop = stop or threading.Event()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def connect_to(self, host: str, port: int) -> None:
        """Re-point at a rejoined peer's new address."""
        with self._lock:
            self._close_locked()
            self.host = host
            self.port = int(port)

    def request(
        self, ftype: int, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """Send one frame, wait for its response.  Raises
        PeerUnavailable after `max_attempts` failed tries (reconnecting
        on the shared backoff between tries)."""
        if not self.breaker.allow():
            raise PeerUnavailable(
                f"peer {self.peer_id}: breaker {self.breaker.state}"
            )
        last_err: Optional[BaseException] = None
        with self._lock:
            for attempt in range(self.max_attempts):
                if attempt and self.backoff.wait(self._stop):
                    break
                try:
                    failpoints.check("fabric.send")
                    sock = self._ensure_sock_locked()
                    wire.send_frame(sock, ftype, payload)
                    rtype, rpayload = wire.recv_frame(sock)
                except (OSError, socket.timeout) as exc:
                    last_err = exc
                    self._close_locked()
                    self.breaker.record_failure()
                    continue
                if rtype == wire.T_ERR:
                    # the peer is alive and answering: an application
                    # error is not a connectivity failure
                    self.breaker.record_success()
                    self.backoff.reset()
                    raise OSError(
                        f"peer {self.peer_id} error: "
                        f"{rpayload.get('error', '?')}"
                    )
                self.breaker.record_success()
                self.backoff.reset()
                return rtype, rpayload
        raise PeerUnavailable(
            f"peer {self.peer_id} unavailable after "
            f"{self.max_attempts} attempts: {last_err}"
        )

    def _ensure_sock_locked(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.send_timeout_s
            )
            sock.settimeout(self.send_timeout_s)
            self._sock = sock
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()
