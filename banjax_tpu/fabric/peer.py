"""Client side of a fabric peer link.

Two senders share this module:

  * `PeerClient` — the control path: one persistent connection,
    request/response serialized under a lock.  Gossip, membership,
    stats, admin frames.
  * `LinePipe` — the data path (wire v2): a windowed, pipelined frame
    sender.  `submit()` enqueues a routed group and returns
    immediately; a dedicated I/O thread coalesces pending groups into
    binary `T_LINES_V2` frames (up to `fabric_frame_max_bytes`), keeps
    up to `fabric_inflight_frames` frames outstanding, and retires
    them as seq-tagged acks stream back — the router returns to
    matching while forwards are in flight.  The unacked window is the
    retransmit buffer: on reconnect every unacked frame is re-sent in
    seq order (the full journal replay on takeover stays the router's,
    unchanged).  At connect the pipe handshakes the wire version
    (`T_VERSION`) and negotiates down to per-frame JSON `T_LINES`
    against an old peer, losslessly; against a co-located v2 peer with
    `fabric_shm_enabled` it attaches a pair of SPSC shm rings
    (native/shmring.py) and moves frames with zero TCP in the loop.

Every send attempt passes the `fabric.send` failpoint (plus
`fabric.frame.corrupt` / `fabric.ring.stall` on the pipe), carries a
per-send socket timeout (`fabric_send_timeout_ms`), and on failure the
connection is torn down and retried on the shared reconnect backoff
(resilience/backoff.py — the same policy as the kafka and tailer
loops).  A per-peer circuit breaker turns repeated failures into a fast
PeerUnavailable so the router can start a takeover instead of timing
out on every chunk for a dead shard.
"""

from __future__ import annotations

import collections
import logging
import os
import select
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.backoff import Backoff, reconnect_backoff
from banjax_tpu.resilience.breaker import CircuitBreaker

log = logging.getLogger(__name__)


class PeerUnavailable(OSError):
    """The peer did not answer within the retry budget (or its breaker
    is open) — the caller should treat the shard as dead."""


class PeerClient:
    def __init__(
        self,
        peer_id: str,
        host: str,
        port: int,
        send_timeout_ms: float = 2000.0,
        max_attempts: int = 3,
        backoff: Optional[Backoff] = None,
        breaker: Optional[CircuitBreaker] = None,
        stop: Optional[threading.Event] = None,
    ):
        self.peer_id = peer_id
        self.host = host
        self.port = int(port)
        self.send_timeout_s = float(send_timeout_ms) / 1000.0
        self.max_attempts = int(max_attempts)
        # short cap: a fabric peer link recovers or fails over in
        # hundreds of ms, not the 30 s a kafka broker is allowed
        self.backoff = backoff or reconnect_backoff(cap=1.0, base=0.05)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=max(2, max_attempts),
            recovery_seconds=2.0,
            name=f"fabric.peer.{peer_id}",
        )
        self._stop = stop or threading.Event()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def connect_to(self, host: str, port: int) -> None:
        """Re-point at a rejoined peer's new address."""
        with self._lock:
            self._close_locked()
            self.host = host
            self.port = int(port)

    def request(
        self, ftype: int, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """Send one frame, wait for its response.  Raises
        PeerUnavailable after `max_attempts` failed tries (reconnecting
        on the shared backoff between tries)."""
        if not self.breaker.allow():
            raise PeerUnavailable(
                f"peer {self.peer_id}: breaker {self.breaker.state}"
            )
        last_err: Optional[BaseException] = None
        with self._lock:
            for attempt in range(self.max_attempts):
                if attempt and self.backoff.wait(self._stop):
                    break
                try:
                    failpoints.check("fabric.send")
                    sock = self._ensure_sock_locked()
                    wire.send_frame(sock, ftype, payload)
                    rtype, rpayload = wire.recv_frame(sock)
                except (OSError, socket.timeout) as exc:
                    last_err = exc
                    self._close_locked()
                    self.breaker.record_failure()
                    continue
                if rtype == wire.T_ERR:
                    # the peer is alive and answering: an application
                    # error is not a connectivity failure
                    self.breaker.record_success()
                    self.backoff.reset()
                    raise OSError(
                        f"peer {self.peer_id} error: "
                        f"{rpayload.get('error', '?')}"
                    )
                self.breaker.record_success()
                self.backoff.reset()
                return rtype, rpayload
        raise PeerUnavailable(
            f"peer {self.peer_id} unavailable after "
            f"{self.max_attempts} attempts: {last_err}"
        )

    def _ensure_sock_locked(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self.send_timeout_s
            )
            sock.settimeout(self.send_timeout_s)
            self._sock = sock
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()


def _corrupt_frame(frame: bytes) -> bytes:
    """The `fabric.frame.corrupt` fault: flip one body byte where both
    encodings are guaranteed to fail decode loudly (the v2 count field
    / a JSON structural byte), never to deliver silently garbled
    lines."""
    idx = min(wire._HEADER.size + 9, len(frame) - 1)
    return frame[:idx] + bytes([frame[idx] ^ 0xFF]) + frame[idx + 1:]


class _InflightFrame:
    __slots__ = ("seq", "groups", "replay", "n_lines", "sent_at", "t_read")

    def __init__(self, seq, groups, replay, n_lines, sent_at, t_read=None):
        self.seq = seq
        self.groups = groups   # coalesced (lines, origin trace id) groups
        self.replay = replay
        self.n_lines = n_lines
        self.sent_at = sent_at
        self.t_read = t_read   # oldest tailer-read stamp in the frame


class LinePipe:
    """Windowed pipelined data-path sender to one peer (module
    docstring has the architecture).  Thread-safe producer API:
    `submit()` / `flush()` / `close()`; one internal I/O thread owns
    the connection, the version handshake, coalescing, the sliding
    window and retransmits."""

    def __init__(
        self,
        peer_id: str,
        host: str,
        port: int,
        node_id: str = "",
        send_timeout_ms: float = 2000.0,
        max_attempts: int = 3,
        inflight_frames: int = 8,
        frame_max_bytes: int = 1 << 20,
        wire_v2: bool = True,
        shm: bool = False,
        shm_ring_bytes: int = 1 << 20,
        pending_chunks: int = 256,
        backoff: Optional[Backoff] = None,
        breaker: Optional[CircuitBreaker] = None,
        stop: Optional[threading.Event] = None,
        stats=None,
        on_ack: Optional[Callable[[Dict[str, Any]], None]] = None,
        trace_propagation: bool = False,
    ):
        self.peer_id = peer_id
        self.host = host
        self.port = int(port)
        self.node_id = node_id
        self.send_timeout_s = float(send_timeout_ms) / 1000.0
        self.max_attempts = int(max_attempts)
        self.inflight_frames = max(1, int(inflight_frames))
        self.frame_max_bytes = int(frame_max_bytes)
        self.wire_v2 = bool(wire_v2)
        self.shm = bool(shm)
        self.shm_ring_bytes = int(shm_ring_bytes)
        self.pending_chunks = int(pending_chunks)
        self.backoff = backoff or reconnect_backoff(cap=1.0, base=0.05)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=max(2, max_attempts),
            recovery_seconds=2.0,
            name=f"fabric.pipe.{peer_id}",
        )
        self._stop = stop or threading.Event()
        self.stats = stats
        self.on_ack = on_ack
        # cross-host trace propagation (obs/fleet.py): forwarded frames
        # carry (origin node, origin trace id, tailer-read stamp) when
        # on AND the peer advertised origin-section support at handshake
        self.trace_propagation = bool(trace_propagation)
        self._peer_trace = False
        # negotiated per connection; read for introspection/metrics
        self.mode = "v2" if self.wire_v2 else "json"
        self.transport = "tcp"

        self._cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._inflight: "collections.OrderedDict[int, _InflightFrame]" = (
            collections.OrderedDict()
        )
        self._next_seq = 1
        self._dead = False
        self._dead_reason = ""
        self._sock: Optional[socket.socket] = None
        self._ring_out = None  # ShmRing, us -> peer
        self._ring_in = None   # ShmRing, peer -> us
        self._wake_r, self._wake_w = os.pipe()
        self._thread = threading.Thread(
            target=self._io_loop, name=f"fabric-pipe-{peer_id}", daemon=True
        )
        self._thread.start()

    # ---- producer API ----

    def submit(self, lines, replay: bool = False, trace_id: int = 0,
               t_read: Optional[float] = None) -> None:
        """Enqueue one routed group.  Returns as soon as the group is
        in the outbox (backpressure-bounded); raises PeerUnavailable
        when the link is dead or its breaker is open — the router then
        starts the takeover, exactly like a failed synchronous send.

        `trace_id`/`t_read` ride the frame's origin section when trace
        propagation is negotiated; both are free to ignore otherwise."""
        if not self.breaker.allow():
            raise PeerUnavailable(
                f"peer {self.peer_id}: breaker {self.breaker.state}"
            )
        with self._cv:
            while (
                not self._dead
                and len(self._pending) >= self.pending_chunks
                and not self._stop.is_set()
            ):
                self._cv.wait(0.05)
            if self._dead:
                raise PeerUnavailable(
                    f"peer {self.peer_id} pipe dead: {self._dead_reason}"
                )
            self._pending.append(
                (tuple(lines), bool(replay), int(trace_id), t_read)
            )
            was_empty = len(self._pending) == 1
        # wake the I/O thread only on the empty->nonempty transition:
        # in every other sleeping state it is already ack-driven (a
        # full window drains via the socket/ring becoming readable),
        # and the flush/backpressure waiters poll on short timeouts —
        # per-submit syscalls would cap the line rate
        if was_empty:
            self._wake()

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every submitted group is sent AND acked (or the
        pipe dies / the timeout passes).  True iff fully drained."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._pending or self._inflight:
                if self._dead:
                    return False
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
        return True

    def inflight(self) -> int:
        with self._cv:
            return len(self._inflight)

    @property
    def dead(self) -> bool:
        return self._dead

    def connect_to(self, host: str, port: int) -> None:
        """Re-point at a rejoined peer's new address (forces a
        reconnect + retransmit of the unacked window)."""
        with self._cv:
            self.host = host
            self.port = int(port)
        self._teardown_channel()
        self._wake()

    def close(self) -> None:
        with self._cv:
            self._dead = True
            self._dead_reason = self._dead_reason or "closed"
            self._cv.notify_all()
        self._wake()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=2.0)
        self._teardown_channel()
        try:
            os.close(self._wake_r)
            os.close(self._wake_w)
        except OSError:
            pass

    # ---- I/O thread ----

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    def _drain_wake(self) -> None:
        try:
            while True:
                r, _, _ = select.select([self._wake_r], [], [], 0)
                if not r:
                    return
                os.read(self._wake_r, 4096)
        except OSError:
            return

    def _io_loop(self) -> None:
        # consecutive channel failures since the last ACK (a connect
        # alone is not liveness: a wedged peer still accepts TCP)
        self._attempts = 0
        try:
            while not self._dead and not self._stop.is_set():
                try:
                    if self._sock is None:
                        if self._attempts and self.backoff.wait(self._stop):
                            break
                        self._attempts += 1
                        self._connect()
                    self._pump()
                except (OSError, socket.timeout) as exc:
                    self._teardown_channel()
                    self.breaker.record_failure()
                    if self._attempts >= self.max_attempts:
                        self._die(f"{self._attempts} attempts: {exc}")
                        return
        except Exception as exc:  # noqa: BLE001 — a pipe bug must not hang submit()
            log.exception("fabric pipe %s: unexpected error", self.peer_id)
            self._die(f"internal error: {exc!r}")
        finally:
            if self._dead:
                self._teardown_channel()

    def _die(self, reason: str) -> None:
        with self._cv:
            self._dead = True
            self._dead_reason = reason
            self._cv.notify_all()
        if self.stats is not None:
            self.stats.note_inflight(self.peer_id, 0)
        log.warning("fabric pipe %s dead: %s", self.peer_id, reason)

    def _connect(self) -> None:
        """Dial, handshake the wire version, optionally attach shm
        rings, then retransmit the unacked window in seq order."""
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.send_timeout_s
        )
        sock.settimeout(self.send_timeout_s)
        mode, server_ring = "json", False
        self._peer_trace = False
        if self.wire_v2:
            wire.send_frame(sock, wire.T_VERSION, {
                "wire": wire.WIRE_VERSION, "node": self.node_id,
            })
            rtype, rpayload = wire.recv_frame(sock)
            if (
                rtype == wire.T_VERSION_R
                and int(rpayload.get("wire", 1)) >= 2
            ):
                mode = "v2"
                server_ring = bool(rpayload.get("ring"))
                self._peer_trace = bool(rpayload.get("trace"))
            # T_ERR ("unhandled frame type") => a JSON-only peer:
            # negotiate down losslessly
        self._sock = sock
        self.mode = mode
        self.transport = "tcp"
        if self.shm and mode == "v2" and server_ring:
            self._attach_rings(sock)
        # the unacked window rides the new channel first — the peer may
        # or may not have seen these frames (the ack is the only truth)
        with self._cv:
            replays = list(self._inflight.values())
        for fr in replays:
            self._transmit(fr, retransmit=True)

    def _attach_rings(self, sock: socket.socket) -> None:
        from banjax_tpu.native import shmring

        out = shmring.ShmRing(capacity=self.shm_ring_bytes)
        rin = shmring.ShmRing(capacity=self.shm_ring_bytes)
        try:
            wire.send_frame(sock, wire.T_RING_ATTACH, {
                "node": self.node_id,
                "c2s": out.name,
                "s2c": rin.name,
                "bytes": self.shm_ring_bytes,
            })
            rtype, _rp = wire.recv_frame(sock)
        except OSError:
            out.close()
            rin.close()
            raise
        if rtype != wire.T_ACK:
            # peer declined (no shm support on its side): stay on TCP
            out.close()
            rin.close()
            return
        self._ring_out = out
        self._ring_in = rin
        self.transport = "shm"

    def _teardown_channel(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for ring_attr in ("_ring_out", "_ring_in"):
            ring = getattr(self, ring_attr)
            setattr(self, ring_attr, None)
            if ring is not None:
                try:
                    ring.close()
                except OSError:
                    pass

    # ---- the pump: acks first, then sends, then wait for either ----

    def _pump(self) -> None:
        while not self._dead and not self._stop.is_set():
            if self._sock is None:
                return
            progress = self._drain_acks()
            progress |= self._send_ready()
            if not progress:
                self._check_ack_deadline()
                self._wait_io()

    def _check_ack_deadline(self) -> None:
        """A connected-but-wedged peer never errors the socket: bound
        the wait for the window head's ack so the failure path
        (reconnect -> retransmit -> attempts budget -> dead) engages."""
        with self._cv:
            if not self._inflight:
                return
            head = next(iter(self._inflight.values()))
            waited = time.monotonic() - head.sent_at
        if waited > 2.0 * self.send_timeout_s:
            raise OSError(
                f"ack timeout: window head seq {head.seq} unacked "
                f"for {waited:.2f}s"
            )

    def _drain_acks(self) -> bool:
        got = False
        while self._ack_available():
            payload = self._recv_ack()
            self._handle_ack(payload)
            got = True
        return got

    def _ack_available(self) -> bool:
        with self._cv:
            if not self._inflight:
                return False
        if self.transport == "shm":
            return self._ring_in is not None and self._ring_in.readable() > 0
        r, _, _ = select.select([self._sock], [], [], 0)
        return bool(r)

    def _recv_ack(self) -> Dict[str, Any]:
        if self.transport == "shm":
            from banjax_tpu.native import shmring

            fr = shmring.read_frame(self._ring_in, self.send_timeout_s)
            if fr is None:
                raise wire.FrameError("ring ack stalled")
            ftype, body = fr
            payload = wire.decode_body(ftype, body)
        else:
            ftype, payload = wire.recv_frame(self._sock)
        if ftype == wire.T_ERR or not isinstance(payload, dict):
            raise wire.FrameError(
                f"peer {self.peer_id} data-path error: "
                f"{payload.get('error', '?') if isinstance(payload, dict) else payload}"
            )
        return payload

    def _handle_ack(self, payload: Dict[str, Any]) -> None:
        with self._cv:
            if not self._inflight:
                raise wire.FrameError("ack with empty window")
            head_seq, fr = next(iter(self._inflight.items()))
            acked = payload.get("seq", head_seq)
            if acked != head_seq:
                raise wire.FrameError(
                    f"ack seq {acked} != window head {head_seq}"
                )
            self._inflight.popitem(last=False)
            n_inflight = len(self._inflight)
            self._cv.notify_all()
        self._attempts = 0  # an ack is the liveness proof
        if self.stats is not None:
            self.stats.note_ack(max(0.0, time.monotonic() - fr.sent_at))
            self.stats.note_inflight(self.peer_id, n_inflight)
        self.breaker.record_success()
        self.backoff.reset()
        if self.on_ack is not None:
            self.on_ack(payload)

    def _send_ready(self) -> bool:
        fr = self._coalesce()
        if fr is None:
            return False
        self._transmit(fr)
        return True

    def _coalesce(self) -> Optional[_InflightFrame]:
        """Pack pending routed groups (same replay flag) into one frame
        up to frame_max_bytes, claim a seq, and move it into the
        window.  None when the window is full or nothing is pending."""
        with self._cv:
            if self._dead or not self._pending:
                return None
            if len(self._inflight) >= self.inflight_frames:
                return None
            groups: List[tuple] = []
            replay = self._pending[0][1]
            size = 64
            n_lines = 0
            t_read: Optional[float] = None
            while self._pending and self._pending[0][1] == replay:
                lines, _rp, trace_id, grp_t_read = self._pending[0]
                est = sum(len(ln) + 4 for ln in lines)
                if groups and size + est > self.frame_max_bytes:
                    break
                self._pending.popleft()
                groups.append((lines, trace_id))
                size += est
                n_lines += len(lines)
                if grp_t_read is not None and (
                    t_read is None or grp_t_read < t_read
                ):
                    t_read = grp_t_read
            seq = self._next_seq
            self._next_seq += 1
            fr = _InflightFrame(seq, groups, replay, n_lines,
                                time.monotonic(), t_read=t_read)
            self._inflight[seq] = fr
            n_inflight = len(self._inflight)
            self._cv.notify_all()
        if self.stats is not None:
            self.stats.note_inflight(self.peer_id, n_inflight)
        return fr

    def _transmit(self, fr: _InflightFrame, retransmit: bool = False) -> None:
        failpoints.check("fabric.send")
        fr.sent_at = time.monotonic()
        flat: List[str] = []
        runs: List[tuple] = []  # contiguous (origin trace id, count) runs
        for g, trace_id in fr.groups:
            flat.extend(g)
            if runs and runs[-1][0] == trace_id:
                runs[-1] = (trace_id, runs[-1][1] + len(g))
            else:
                runs.append((trace_id, len(g)))
        propagate = self.trace_propagation and self.node_id
        if self.mode == "v2":
            if propagate and self._peer_trace:
                frame = wire.encode_lines_v2(
                    fr.seq, flat, replay=fr.replay,
                    origin_node=self.node_id,
                    origin_t_read=fr.t_read or 0.0,
                    origin_runs=runs,
                )
            else:
                frame = wire.encode_lines_v2(fr.seq, flat, replay=fr.replay)
        else:
            payload = {"lines": flat, "replay": fr.replay, "seq": fr.seq}
            if propagate:
                # the JSON fallback carries the same origin info as a
                # plain key — old receivers ignore unknown keys
                payload["origin"] = {
                    "node": self.node_id,
                    "runs": [[t, c] for t, c in runs],
                    "t_read": fr.t_read,
                }
            frame = wire.encode_frame(wire.T_LINES, payload)
        try:
            failpoints.check("fabric.frame.corrupt")
        except failpoints.FaultInjected:
            frame = _corrupt_frame(frame)
        if self.transport == "shm":
            failpoints.check("fabric.ring.stall")
            from banjax_tpu.native import shmring

            try:
                self._ring_out.write(frame, self.send_timeout_s)
            except shmring.RingTimeout as exc:
                raise OSError(f"shm ring stalled: {exc}") from exc
            if self.stats is not None:
                self.stats.note_ring_occupancy(
                    self.peer_id, self._ring_out.occupancy()
                )
        else:
            self._sock.sendall(frame)
        if self.stats is not None:
            self.stats.note_frame_sent(self.mode, self.transport, len(frame))

    def _wait_io(self) -> None:
        """Idle: wait for an ack byte, a submit() wake, or a timeout
        slice (shm acks can't be select()ed, so ring mode polls)."""
        if self.transport == "shm":
            with self._cv:
                if self._pending and len(self._inflight) < self.inflight_frames:
                    return
            time.sleep(0.0005)
            return
        try:
            select.select([self._sock, self._wake_r], [], [], 0.05)
        except (OSError, ValueError):
            raise OSError("pipe socket vanished mid-select")
        self._drain_wake()
