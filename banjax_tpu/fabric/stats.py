"""Fabric counters — the cross-process half of the accounting contract.

`FabricStats` mirrors the pipeline's PipelineStats shape: note_* under
one lock, `peek()` returns the registry line keys.  The counters close
the fabric-wide ledger the single-process invariant cannot see:

    fed == acked + shed            (driver/router view, per chunk)
    received == local + forwarded + shed   (per shard)

summed with every shard's `admitted == processed + shed + drain_errors`
they prove no line entered the fabric and vanished, even across a
SIGKILL + takeover.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from banjax_tpu.obs.registry import Histogram


class FabricStats:
    """Thread-safe fabric counters + takeover duration histogram."""

    def __init__(self):
        self._lock = threading.Lock()
        self.forwarded_lines = 0       # sent to a peer and acked
        self.received_lines = 0        # arrived over the wire from a peer
        self.local_lines = 0           # owned locally, submitted in-process
        self.shed_lines = 0            # no alive owner — counted, never silent
        self.replayed_lines = 0        # journal replay after a takeover
        self.replicated_decisions = 0  # decisions produced to the command topic
        self.replication_errors = 0    # produce attempts that failed (retried)
        self.duplicate_suppressed = 0  # replicated commands dropped by dedupe
        self.replicated_applied = 0    # replicated commands applied locally
        self.takeovers = 0
        self.takeover_duration = Histogram()
        self.peer_up: Dict[str, bool] = {}
        self.last_takeover: Optional[Dict[str, object]] = None

    def note_forwarded(self, n: int) -> None:
        with self._lock:
            self.forwarded_lines += n

    def note_received(self, n: int) -> None:
        with self._lock:
            self.received_lines += n

    def note_local(self, n: int) -> None:
        with self._lock:
            self.local_lines += n

    def note_shed(self, n: int) -> None:
        with self._lock:
            self.shed_lines += n

    def note_replayed(self, n: int) -> None:
        with self._lock:
            self.replayed_lines += n

    def note_replicated(self, n: int = 1) -> None:
        with self._lock:
            self.replicated_decisions += n

    def note_replication_error(self) -> None:
        with self._lock:
            self.replication_errors += 1

    def note_duplicate_suppressed(self) -> None:
        with self._lock:
            self.duplicate_suppressed += 1

    def note_replicated_applied(self) -> None:
        with self._lock:
            self.replicated_applied += 1

    def note_peer(self, peer_id: str, up: bool) -> None:
        with self._lock:
            self.peer_up[peer_id] = up

    def note_takeover(
        self, peer_id: str, duration_s: float, replayed_lines: int
    ) -> None:
        with self._lock:
            self.takeovers += 1
            self.last_takeover = {
                "peer": peer_id,
                "duration_s": duration_s,
                "replayed_lines": replayed_lines,
            }
        self.takeover_duration.observe(duration_s)

    def peek(self) -> Dict[str, object]:
        """Registry line keys (obs/registry.py `Fabric*` families)."""
        with self._lock:
            return {
                "FabricForwardedLines": self.forwarded_lines,
                "FabricReceivedLines": self.received_lines,
                "FabricLocalLines": self.local_lines,
                "FabricShedLines": self.shed_lines,
                "FabricReplayedLines": self.replayed_lines,
                "FabricReplicatedDecisions": self.replicated_decisions,
                "FabricReplicationErrors": self.replication_errors,
                "FabricDuplicatesSuppressed": self.duplicate_suppressed,
                "FabricReplicatedApplied": self.replicated_applied,
                "FabricTakeovers": self.takeovers,
            }

    def peers_snapshot(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self.peer_up)

    def takeover_snapshot(
        self,
    ) -> Tuple[Tuple[float, ...], list, float, int]:
        return self.takeover_duration.snapshot()
