"""Fabric counters — the cross-process half of the accounting contract.

`FabricStats` mirrors the pipeline's PipelineStats shape: note_* under
one lock, `peek()` returns the registry line keys.  The counters close
the fabric-wide ledger the single-process invariant cannot see:

    fed == acked + shed            (driver/router view, per chunk)
    received + replayed == local + forwarded + shed + replay_skipped
                                   (per shard disposition ledger)

summed with every shard's `admitted == processed + shed + drain_errors`
they prove no line entered the fabric and vanished, even across a
SIGKILL + takeover.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from banjax_tpu.obs.registry import FRAME_BYTES_BUCKETS, Histogram


class FabricStats:
    """Thread-safe fabric counters + takeover duration histogram."""

    def __init__(self):
        self._lock = threading.Lock()
        self.forwarded_lines = 0       # sent to a peer (journaled at submit)
        self.received_lines = 0        # arrived over the wire from a peer
        self.local_lines = 0           # owned locally, submitted in-process
        self.shed_lines = 0            # no alive owner — counted, never silent
        self.replayed_lines = 0        # journal replay after a takeover
        self.replay_skipped_lines = 0  # replayed lines a live owner already saw
        self.replicated_decisions = 0  # decisions produced to the command topic
        self.replication_errors = 0    # produce attempts that failed (retried)
        self.duplicate_suppressed = 0  # replicated commands dropped by dedupe
        self.replicated_applied = 0    # replicated commands applied locally
        self.takeovers = 0
        self.takeover_duration = Histogram()
        self.peer_up: Dict[str, bool] = {}
        self.last_takeover: Optional[Dict[str, object]] = None
        # ---- gossip membership (fabric/membership.py) ----
        self.membership_suspects = 0       # alive -> suspect transitions
        self.membership_confirmed_dead = 0  # suspicion timeouts expired
        self.membership_refuted = 0        # suspect -> alive (incarnation bump)
        self.membership_joined = 0         # new/revived members inserted
        self.membership_left = 0           # graceful departures observed
        self.gossip_bytes = 0              # probe frames + piggyback digests
        self.member_state: Dict[str, str] = {}  # peer -> alive/suspect/dead/left
        self.detection_time = Histogram()  # last liveness evidence -> confirmed dead
        # node -> health bits piggybacked on gossip (obs/fleet.py
        # HEALTH_* encoding: 1 slo_breached, 2 breaker open, 4 half-open)
        self.peer_health: Dict[str, int] = {}
        # ---- wire v2 transport (fabric/peer.py LinePipe) ----
        self.frames_sent: Dict[Tuple[str, str], int] = {}  # (version, transport)
        self.frame_bytes_total = 0
        self.frame_bytes = Histogram(FRAME_BYTES_BUCKETS)
        self.acks_received = 0
        self.ack_rtt = Histogram()                 # seconds, shared buckets
        self.inflight: Dict[str, int] = {}         # peer -> frames outstanding
        self.ring_occupancy: Dict[str, float] = {}  # peer -> fill fraction

    def note_frame_sent(
        self, version: str, transport: str, nbytes: int
    ) -> None:
        with self._lock:
            key = (version, transport)
            self.frames_sent[key] = self.frames_sent.get(key, 0) + 1
            self.frame_bytes_total += nbytes
        self.frame_bytes.observe(float(nbytes))

    def note_ack(self, rtt_s: float) -> None:
        with self._lock:
            self.acks_received += 1
        self.ack_rtt.observe(rtt_s)

    def note_inflight(self, peer_id: str, n: int) -> None:
        with self._lock:
            self.inflight[peer_id] = n

    def note_ring_occupancy(self, peer_id: str, frac: float) -> None:
        with self._lock:
            self.ring_occupancy[peer_id] = frac

    def note_replay_skipped(self, n: int) -> None:
        with self._lock:
            self.replay_skipped_lines += n

    def note_forwarded(self, n: int) -> None:
        with self._lock:
            self.forwarded_lines += n

    def note_received(self, n: int) -> None:
        with self._lock:
            self.received_lines += n

    def note_local(self, n: int) -> None:
        with self._lock:
            self.local_lines += n

    def note_shed(self, n: int) -> None:
        with self._lock:
            self.shed_lines += n

    def note_replayed(self, n: int) -> None:
        with self._lock:
            self.replayed_lines += n

    def note_replicated(self, n: int = 1) -> None:
        with self._lock:
            self.replicated_decisions += n

    def note_replication_error(self) -> None:
        with self._lock:
            self.replication_errors += 1

    def note_duplicate_suppressed(self) -> None:
        with self._lock:
            self.duplicate_suppressed += 1

    def note_replicated_applied(self) -> None:
        with self._lock:
            self.replicated_applied += 1

    def note_peer(self, peer_id: str, up: bool) -> None:
        with self._lock:
            self.peer_up[peer_id] = up

    def note_membership_event(self, event: str) -> None:
        """Count one membership transition (membership.py event names)."""
        with self._lock:
            if event == "suspect":
                self.membership_suspects += 1
            elif event == "confirmed_dead":
                self.membership_confirmed_dead += 1
            elif event == "refuted":
                self.membership_refuted += 1
            elif event == "joined":
                self.membership_joined += 1
            elif event == "left":
                self.membership_left += 1

    def note_member_state(self, peer_id: str, state: str) -> None:
        with self._lock:
            self.member_state[peer_id] = state

    def note_gossip_bytes(self, n: int) -> None:
        with self._lock:
            self.gossip_bytes += n

    def note_peer_health(self, peer_id: str, bits: int) -> None:
        with self._lock:
            self.peer_health[peer_id] = int(bits)

    def peer_health_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.peer_health)

    def note_detection(self, duration_s: float) -> None:
        """Failure-detection latency: last liveness evidence for the
        member -> its death confirmed in this node's view."""
        self.detection_time.observe(duration_s)

    def note_takeover(
        self, peer_id: str, duration_s: float, replayed_lines: int
    ) -> None:
        with self._lock:
            self.takeovers += 1
            self.last_takeover = {
                "peer": peer_id,
                "duration_s": duration_s,
                "replayed_lines": replayed_lines,
            }
        self.takeover_duration.observe(duration_s)

    def peek(self) -> Dict[str, object]:
        """Registry line keys (obs/registry.py `Fabric*` families)."""
        with self._lock:
            return {
                "FabricForwardedLines": self.forwarded_lines,
                "FabricReceivedLines": self.received_lines,
                "FabricLocalLines": self.local_lines,
                "FabricShedLines": self.shed_lines,
                "FabricReplayedLines": self.replayed_lines,
                "FabricReplaySkippedLines": self.replay_skipped_lines,
                "FabricFramesSent": sum(self.frames_sent.values()),
                "FabricFrameBytes": self.frame_bytes_total,
                "FabricAcksReceived": self.acks_received,
                "FabricInflightFrames": sum(self.inflight.values()),
                "FabricRingOccupancy": round(
                    max(self.ring_occupancy.values(), default=0.0), 4
                ),
                "FabricReplicatedDecisions": self.replicated_decisions,
                "FabricReplicationErrors": self.replication_errors,
                "FabricDuplicatesSuppressed": self.duplicate_suppressed,
                "FabricReplicatedApplied": self.replicated_applied,
                "FabricTakeovers": self.takeovers,
                "FabricMembershipSuspects": self.membership_suspects,
                "FabricMembershipConfirmedDead":
                    self.membership_confirmed_dead,
                "FabricMembershipRefuted": self.membership_refuted,
                "FabricMembershipJoined": self.membership_joined,
                "FabricMembershipLeft": self.membership_left,
                "FabricGossipBytes": self.gossip_bytes,
            }

    def peers_snapshot(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self.peer_up)

    def frames_snapshot(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self.frames_sent)

    def ring_occupancy_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.ring_occupancy)

    def member_states_snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self.member_state)

    def takeover_snapshot(
        self,
    ) -> Tuple[Tuple[float, ...], list, float, int]:
        return self.takeover_duration.snapshot()

    def detection_snapshot(
        self,
    ) -> Tuple[Tuple[float, ...], list, float, int]:
        return self.detection_time.snapshot()
