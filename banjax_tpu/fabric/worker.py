"""One fabric shard as a real OS process.

`python -m banjax_tpu.fabric.worker --node-id w0 ...` builds the full
single-process engine (the same `build_engine` assembly the scenario
harness drives), wraps its banner with the decision replicator, attaches
a REAL KafkaReader to the command topic for peer decisions, and serves
the fabric wire protocol on a socket.  The dryrun harness spawns N of
these, kills one mid-flood, and audits the survivors.

Startup protocol (stdout, one JSON line):  the worker prints
`{"ready": true, "node_id": ..., "port": ...}` only after the engine is
warmed (device compile done) and the kafka reader has proven attached
(its own `fabric_ping` round-tripped), so a SIGKILL any time after
READY lands on a fully live shard.

Two ways into the ring:

  * **HELLO** (driver-pushed topology): the harness sends T_HELLO with
    the full peer map; gossip membership starts from it as a seed when
    the payload carries `gossip_interval_ms > 0`.
  * **--join host:port** (automatic join, no driver involvement): the
    worker announces itself to one live seed with T_JOIN, builds its
    router from the returned membership digest, pulls the seed's
    decision snapshot (T_SNAPSHOT -> local T_SYNC application), starts
    gossiping, and only then prints READY — the surviving fleet learns
    of it purely through gossip, no restarts, no broadcast.

Planned leave (T_LEAVE): stop owning (router.mark_left on self — every
subsequent line forwards to its new owner), flush the pipeline to
quiescence, announce LEFT via a final gossip digest to every alive
member, then depart.  Crash takeover replays the victim's journal;
graceful leave hands ranges back with the journal untouched-by-replay
because nothing was lost.
"""

from __future__ import annotations

import argparse
import json
import os
import socket as _socket
import sys
import threading
import time


def _pin_cpu_backend() -> None:
    # mirror __graft_entry__._backend_guard: a worker must never grab a
    # real accelerator out from under the host process
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    _pin_cpu_backend()
    ap = argparse.ArgumentParser(description="banjax fabric shard worker")
    ap.add_argument("--node-id", required=True)
    ap.add_argument("--listen-port", type=int, default=0)
    ap.add_argument("--broker-port", type=int, default=0,
                    help="kafka broker port for decision replication "
                         "(0 = replication off)")
    ap.add_argument("--send-timeout-ms", type=float, default=800.0)
    ap.add_argument("--grace-ms", type=float, default=200.0)
    ap.add_argument("--vnodes", type=int, default=64)
    ap.add_argument("--gossip-interval-ms", type=float, default=0.0,
                    help="SWIM probe cadence; 0 = gossip off (HELLO "
                         "payload may still enable it)")
    ap.add_argument("--suspect-timeout-ms", type=float, default=1200.0)
    ap.add_argument("--indirect-probes", type=int, default=2)
    ap.add_argument("--graceful-leave-ms", type=float, default=5000.0)
    ap.add_argument("--inflight-frames", type=int, default=8,
                    help="pipelined data-path window per peer "
                         "(0 = synchronous JSON forwards, the PR 11 "
                         "differential oracle)")
    ap.add_argument("--frame-max-bytes", type=int, default=1 << 20)
    ap.add_argument("--wire-v2", type=int, default=1)
    ap.add_argument("--shm", type=int, default=0,
                    help="1 = co-located peers exchange frames over "
                         "shm rings instead of loopback TCP")
    ap.add_argument("--shm-ring-bytes", type=int, default=1 << 21)
    ap.add_argument("--trace-propagation", type=int, default=0,
                    help="1 = forwarded chunks carry (origin node, "
                         "origin trace id) and owner-side drains open "
                         "linked fabric.remote-drain spans")
    ap.add_argument("--join", default="",
                    help="host:port of one live member — join its ring "
                         "via gossip announce + snapshot sync instead of "
                         "waiting for a driver HELLO")
    args = ap.parse_args(argv)

    # heavy imports AFTER the backend pin
    from banjax_tpu.decisions.model import Decision
    from banjax_tpu.fabric import membership as swim
    from banjax_tpu.fabric import wire
    from banjax_tpu.fabric.node import FabricNode
    from banjax_tpu.fabric.peer import LinePipe, PeerClient
    from banjax_tpu.fabric.replication import (
        DecisionReplicator,
        FabricDeduper,
        ReplicatingBanner,
    )
    from banjax_tpu.fabric.router import FabricRouter
    from banjax_tpu.fabric.hashring import ConsistentHashRing
    from banjax_tpu.fabric.stats import FabricStats
    from banjax_tpu.fabric.router import ip_of_line
    from banjax_tpu.ingest.kafka_io import handle_command
    from banjax_tpu.obs import fleet, provenance, trace
    from banjax_tpu.obs.exposition import render_prometheus
    from banjax_tpu.resilience import failpoints
    from banjax_tpu.resilience.health import HealthRegistry
    from banjax_tpu.scenarios.runtime import (
        RecordingBanner,
        _WARM_IP,
        build_engine,
    )
    from banjax_tpu.scenarios.shapes import RULES_YAML, T0

    node_id = args.node_id
    fstats = FabricStats()
    health = HealthRegistry()
    inner_banner = RecordingBanner()
    replicator = None
    banner = inner_banner
    if args.broker_port:
        from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

        replicator = DecisionReplicator(
            origin=node_id,
            transport=WireKafkaTransport(),
            topic="fabric.commands",
            stats=fstats,
        )
        banner = ReplicatingBanner(inner_banner, replicator)

    parts = build_engine(
        RULES_YAML,
        banner=banner,
        kafka_broker_port=args.broker_port or None,
        kafka_command_topic="fabric.commands",
        kafka_report_topic="fabric.reports",
        cfg_overrides={
            "fabric_enabled": True,
            "fabric_node_id": node_id,
            "fabric_listen": "127.0.0.1:0",
            "fabric_vnodes": args.vnodes,
            "fabric_send_timeout_ms": args.send_timeout_ms,
            "fabric_takeover_grace_ms": args.grace_ms,
            "fabric_gossip_interval_ms": args.gossip_interval_ms,
            "fabric_suspect_timeout_ms": max(
                args.suspect_timeout_ms, args.gossip_interval_ms * 2 + 1
            ),
            "fabric_indirect_probes": args.indirect_probes,
            "fabric_graceful_leave_ms": args.graceful_leave_ms,
        },
    )
    cfg, sched, dynamic_lists = parts.cfg, parts.sched, parts.dynamic_lists
    # owner half of the cross-host trace join: forwarded-line bans
    # resolve (origin_node, origin_trace) through the fleet index —
    # inert until a propagating sender actually feeds it
    provenance.set_origin_resolver(fleet.get_origin_index().resolve)
    if args.trace_propagation:
        # origin half: router-allocated trace ids need a live span ring
        trace.configure(enabled=True)
    if replicator is not None:
        replicator.configure(cfg)
        # the origin's own kafka echo is suppressed by the deduper, so
        # its decisions land in its dynamic lists here, at publish time
        replicator.local_apply = lambda cmd: handle_command(
            cfg, cmd, dynamic_lists
        )
    sched.start()

    # ---- kafka replication consumer (real reader, real wire) ----
    reader = None
    kafka_ready = threading.Event()
    if args.broker_port:
        from banjax_tpu.ingest.kafka_io import KafkaReader
        from banjax_tpu.ingest.kafka_wire import WireKafkaTransport
        from banjax_tpu.resilience.backoff import reconnect_backoff

        deduper = FabricDeduper(
            origin=node_id,
            apply_command=lambda cmd: handle_command(
                cfg, cmd, dynamic_lists
            ),
            stats=fstats,
        )

        def _dispatch(raw) -> None:
            data = raw if isinstance(raw, bytes) else raw.encode()
            if b"fabric_ping" in data:
                try:
                    ping = json.loads(data)
                except ValueError:
                    return
                if ping.get("fabric_origin") == node_id:
                    kafka_ready.set()
                return
            deduper.dispatch(raw)

        class _Holder:
            def get(self):
                return cfg

        reader = KafkaReader(
            _Holder(), dynamic_lists, transport=WireKafkaTransport(),
            backoff=reconnect_backoff(cap=0.2, base=0.05),
            pipeline=sched,
        )
        reader.dispatch_raw = _dispatch
        reader.start()

    # ---- warmup (compile outside the measured window) ----
    warm = [
        f"{T0:.6f} {_WARM_IP} GET warm.example GET /about HTTP/1.1 warm -"
        for _ in range(48)
    ]
    for _ in range(2):
        sched.submit(list(warm))
        if not sched.flush(600):
            print(json.dumps({"ready": False, "error": "warmup hang"}),
                  flush=True)
            return 2

    # the reader attaches at the log tail at an unobservable moment:
    # keep producing pings until our own round-trips (same handshake as
    # the scenario harness's kafka mode)
    if reader is not None and replicator is not None:
        ping = json.dumps(
            {"Name": "fabric_ping", "fabric_origin": node_id}
        ).encode()
        deadline = time.monotonic() + 30
        while not kafka_ready.wait(0.05):
            if time.monotonic() > deadline:
                print(json.dumps(
                    {"ready": False, "error": "kafka never attached"}
                ), flush=True)
                return 2
            try:
                replicator.transport.send(cfg, "fabric.commands", ping)
            except OSError:
                pass

    # ---- fabric server ----
    shutdown = threading.Event()
    state = {"router": None, "membership": None}

    def _local_submit(lines, t_read=None, hop="local") -> int:
        sched.submit(list(lines), t_read=t_read, hop=hop)
        return len(lines)

    def _metrics_text() -> str:
        return render_prometheus(
            dynamic_lists, {}, {}, matcher=parts.matcher,
            pipeline=sched, fabric=fstats,
        )

    def _health_bits() -> int:
        return fleet.compute_health_bits(matcher=parts.matcher)

    def _drain_forwarded(lines, origin_node="", origin_runs=(),
                         origin_t_read=None):
        """Owner-side drain of a forwarded chunk (mirrors
        fabric/service.py): feed the OriginIndex, open linked
        fabric.remote-drain spans under the ORIGIN trace ids, stamp the
        submit hop=fabric with the sender's read time."""
        spans = []
        if origin_node:
            runs = [(int(t), int(c)) for t, c in (origin_runs or ())]
            if not runs:
                runs = [(0, len(lines))]
            idx = fleet.get_origin_index()
            pos = 0
            for tid, count in runs:
                for ln in lines[pos:pos + count]:
                    idx.note(ip_of_line(ln), origin_node, tid)
                if tid:
                    spans.append(trace.begin(
                        "fabric.remote-drain", tid,
                        args={"origin_node": origin_node, "lines": count},
                    ))
                pos += count
        try:
            t_read = float(origin_t_read) if origin_t_read else None
            _local_submit(lines, t_read=t_read, hop="fabric")
        finally:
            for sp in spans:
                trace.end(sp)

    def _make_client(pid, host, port, timeout_ms=None):
        return PeerClient(
            pid, host, int(port),
            send_timeout_ms=float(timeout_ms or args.send_timeout_ms),
        )

    def _pipe_factory_from(payload):
        """Build the router's LinePipe factory from transport knobs in
        the HELLO payload (driver-pushed) falling back to the CLI args
        (join path).  inflight 0 disables the pipelined data path —
        forwards stay on the synchronous JSON oracle."""
        inflight = int(payload.get("inflight_frames", args.inflight_frames))
        if inflight <= 0:
            return None
        v2 = bool(payload.get("wire_v2", args.wire_v2))
        frame_max = int(payload.get("frame_max_bytes", args.frame_max_bytes))
        shm = bool(payload.get("shm", args.shm))
        ring_bytes = int(payload.get("shm_ring_bytes", args.shm_ring_bytes))
        timeout_ms = float(
            payload.get("send_timeout_ms", args.send_timeout_ms)
        )
        trace_prop = bool(
            payload.get("trace_propagation", args.trace_propagation)
        )

        def factory(pid, host, port, on_ack):
            return LinePipe(
                pid, host, int(port), node_id=node_id,
                send_timeout_ms=timeout_ms,
                inflight_frames=inflight,
                frame_max_bytes=frame_max,
                wire_v2=v2, shm=shm, shm_ring_bytes=ring_bytes,
                stats=fstats, on_ack=on_ack,
                trace_propagation=trace_prop,
            )
        return factory

    def _start_membership(router, seeds, gossip_ms, suspect_ms,
                          indirect, listen_port):
        ms = swim.SwimMembership(
            node_id, "127.0.0.1", listen_port,
            router=router, stats=fstats,
            gossip_interval_ms=gossip_ms,
            suspect_timeout_ms=suspect_ms,
            indirect_probes=indirect,
            peer_factory=_make_client,
            health_provider=_health_bits,
        )
        if seeds:
            ms.seed(seeds)
        router.gossip_merge = ms.merge
        state["membership"] = ms
        ms.start()
        return ms

    def h_hello(payload):
        peers_map = payload.get("peers", {})
        ring = ConsistentHashRing(
            peers_map.keys(), vnodes=int(payload.get("vnodes", args.vnodes))
        )
        clients = {}
        for pid, addr in peers_map.items():
            if pid == node_id:
                clients[pid] = None
                continue
            clients[pid] = _make_client(
                pid, addr[0], addr[1],
                payload.get("send_timeout_ms", args.send_timeout_ms),
            )
        router = FabricRouter(
            node_id, ring, clients, _local_submit, stats=fstats,
            health=health,
            takeover_grace_ms=float(
                payload.get("grace_ms", args.grace_ms)
            ),
            pipe_factory=_pipe_factory_from(payload),
            trace_propagation=bool(payload.get(
                "trace_propagation", args.trace_propagation
            )),
        )
        state["router"] = router
        gossip_ms = float(
            payload.get("gossip_interval_ms", args.gossip_interval_ms)
        )
        if gossip_ms > 0:
            _start_membership(
                router,
                {pid: (addr[0], int(addr[1]))
                 for pid, addr in peers_map.items()},
                gossip_ms,
                float(payload.get(
                    "suspect_timeout_ms", args.suspect_timeout_ms
                )),
                int(payload.get("indirect_probes", args.indirect_probes)),
                node.port,
            )
        return wire.T_HELLO_R, {"node_id": node_id}

    def h_lines(payload):
        lines = payload.get("lines", [])
        fstats.note_received(len(lines))
        router = state["router"]
        ms = state["membership"]
        piggy = {"gossip": ms.digest()} if ms is not None else {}
        if "seq" in payload:
            # a pipelined JSON-mode sender matches acks FIFO by seq
            piggy["seq"] = payload["seq"]
        if payload.get("route") and router is not None:
            out = router.route(
                lines, replay=bool(payload.get("replay"))
            )
            if out["forwarded"]:
                # our ack upstream must mean LANDED, not in-window: a
                # SIGKILL here would otherwise take acked-but-unflushed
                # survivor-owned lines down with us, and the replay
                # dedupe filter would (rightly) refuse to re-run them
                router.flush(15.0)
            return wire.T_ACK, {"n": len(lines), **out, **piggy}
        origin = payload.get("origin")
        origin = origin if isinstance(origin, dict) else {}
        _drain_forwarded(
            lines,
            str(origin.get("node", "")),
            origin.get("runs") or (),
            origin.get("t_read"),
        )
        fstats.note_local(len(lines))
        return wire.T_ACK, {
            "n": len(lines), "local": len(lines), **piggy
        }

    def h_lines_v2(fr):
        # binary data frame (wire.LinesV2): a peer's pipelined forward —
        # ownership was already computed by the sender, so the lines go
        # straight down the local pipeline
        lines = list(fr.lines)
        fstats.note_received(len(lines))
        _drain_forwarded(
            lines, fr.origin_node, fr.origin_runs, fr.origin_t_read
        )
        fstats.note_local(len(lines))
        ms = state["membership"]
        ack = {"seq": fr.seq, "n": len(lines), "local": len(lines)}
        if ms is not None:
            ack["gossip"] = ms.digest()
        return wire.T_ACK, ack

    def h_peer_down(payload):
        pid = str(payload.get("peer", ""))
        ms = state["membership"]
        router = state["router"]
        if ms is not None:
            ms.note_peer_down(pid)
        elif router is not None:
            router.mark_dead(pid, reason="driver broadcast")
        return wire.T_ACK, {}

    def h_peer_up(payload):
        pid = str(payload.get("peer", ""))
        ms = state["membership"]
        router = state["router"]
        if ms is not None:
            # exactly-once funnel: a duplicate notification (driver
            # handshake racing gossip discovery) is a no-op here
            ms.note_peer_up(
                pid, host=payload.get("host"), port=payload.get("port")
            )
        elif router is not None:
            router.mark_alive(
                pid, host=payload.get("host"), port=payload.get("port")
            )
        return wire.T_ACK, {}

    def h_gossip_ping(payload):
        ms = state["membership"]
        if ms is None:
            return wire.T_ERR, {"error": "gossip disabled"}
        return ms.handle_ping(payload)

    def h_gossip_ping_req(payload):
        ms = state["membership"]
        if ms is None:
            return wire.T_ERR, {"error": "gossip disabled"}
        return ms.handle_ping_req(payload)

    def h_join(payload):
        ms = state["membership"]
        if ms is None:
            return wire.T_ERR, {"error": "gossip disabled"}
        return ms.handle_join(payload)

    def h_leave(payload):
        """Planned leave: drain, hand back, announce, depart."""
        t0 = time.monotonic()
        ms = state["membership"]
        router = state["router"]
        if router is not None:
            # stop owning FIRST: every line arriving after this forwards
            # to its new owner, so nothing new lands in our pipeline
            router.mark_left(node_id)
        budget_s = float(
            payload.get("timeout", args.graceful_leave_ms / 1000.0)
        )
        drained = True
        if router is not None:
            # land every in-flight forward before draining the local
            # pipeline: a departing shard leaves no frame on the wire
            drained = router.flush(max(budget_s, 1.0))
        flushed = sched.flush(max(budget_s, 1.0)) and drained
        announced = 0
        if ms is not None:
            digest = ms.begin_leave()
            for row in digest:
                rid, status, _inc, host, port = row
                if rid == node_id or status != swim.ALIVE:
                    continue
                if ms._send(
                    host, int(port), wire.T_GOSSIP_PING,
                    {"from": node_id, "digest": digest},
                ) is not None:
                    announced += 1
            ms.stop()
        # depart shortly after the ack flushes to the admin socket
        threading.Timer(0.3, shutdown.set).start()
        return wire.T_ACK, {
            "flushed": bool(flushed),
            "announced": announced,
            "drain_ms": (time.monotonic() - t0) * 1000.0,
            # final ledger: the driver audits the leaver's zero-shed /
            # zero-replay claim after the process is gone
            "sched": sched.stats.peek(),
            "fabric": fstats.peek(),
            "bans": list(inner_banner.regex_ban_logs),
        }

    def h_failpoint(payload):
        """Harness chaos surface: arm/disarm a named failpoint in THIS
        process (the slow-node suspect/refute cycle arms
        fabric.gossip.ack with mode=sleep here)."""
        name = str(payload.get("name", ""))
        if name not in failpoints.KNOWN_SITES:
            return wire.T_ERR, {"error": f"unknown failpoint {name!r}"}
        if payload.get("disarm"):
            failpoints.disarm(name)
            return wire.T_ACK, {"disarmed": name}
        failpoints.arm(
            name,
            mode=str(payload.get("mode", "error")),
            count=payload.get("count"),
            delay_s=float(payload.get("delay_s", 0.0)),
            probability=float(payload.get("probability", 1.0)),
        )
        return wire.T_ACK, {"armed": name}

    def h_stats(payload):
        router = state["router"]
        ms = state["membership"]
        out = {
            "node_id": node_id,
            "sched": sched.stats.peek(),
            "fabric": fstats.peek(),
            "bans": list(inner_banner.regex_ban_logs),
            "decisions": list(inner_banner.decisions),
            "dynamic": list(dynamic_lists.metrics()),
            "router": router.describe() if router is not None else None,
            "membership": ms.describe() if ms is not None else None,
            "detection": fstats.detection_snapshot()[1],
        }
        if payload.get("metrics"):
            # federated scrape pull (obs/fleet.py FleetScraper)
            try:
                out["metrics_text"] = _metrics_text()
            except Exception as e:  # noqa: BLE001 — a render bug must not kill the link
                out["metrics_error"] = str(e)
        return wire.T_STATS_R, out

    def h_explain(payload):
        # cross-shard /decisions/explain: answer from THIS shard's
        # ledger, tagged with our id so the asker can attribute it
        ip = str(payload.get("ip", ""))
        ed = dynamic_lists.format_ip_entries().get(ip)
        return wire.T_EXPLAIN_R, {
            "node_id": node_id,
            "ip": ip,
            "ledger_enabled": provenance.enabled(),
            "records": provenance.get_ledger().explain(ip),
            "active_decision": ed.decision.name if ed is not None else None,
        }

    def h_flightrec(payload):
        # a peer's incident fan-out: contribute THIS node's snapshot
        # (never re-fan-out — the origin owns the incident)
        router = state["router"]
        return wire.T_FLIGHTREC_R, {
            "node_id": node_id,
            "incident": str(payload.get("incident", "")),
            "files": fleet.local_capture_files(
                metrics_text_fn=_metrics_text,
                fabric_fn=(
                    router.describe if router is not None
                    else lambda: {"enabled": False}
                ),
            ),
        }

    def h_snapshot(payload):
        entries = []
        for ip, ed in dynamic_lists.format_ip_entries().items():
            entries.append([
                ip, ed.decision.name, ed.expires,
                getattr(ed, "domain", "") or "",
            ])
        return wire.T_SNAPSHOT_R, {"decisions": entries}

    def h_sync(payload):
        applied = 0
        for ip, dec_name, expires, domain in payload.get("decisions", []):
            dynamic_lists.update(
                ip, float(expires), Decision[dec_name], True, domain
            )
            applied += 1
        return wire.T_ACK, {"applied": applied}

    def h_flush(payload):
        t = float(payload.get("timeout", 120))
        router = state["router"]
        routed = router.flush(t) if router is not None else True
        ok = sched.flush(t)
        return wire.T_ACK, {"flushed": bool(ok and routed)}

    def h_ping(payload):
        return wire.T_PONG, {"node_id": node_id}

    def h_shutdown(payload):
        shutdown.set()
        return wire.T_ACK, {}

    node = FabricNode(
        "127.0.0.1", args.listen_port,
        handlers={
            wire.T_HELLO: h_hello,
            wire.T_LINES: h_lines,
            wire.T_LINES_V2: h_lines_v2,
            wire.T_PEER_DOWN: h_peer_down,
            wire.T_PEER_UP: h_peer_up,
            wire.T_GOSSIP_PING: h_gossip_ping,
            wire.T_GOSSIP_PING_REQ: h_gossip_ping_req,
            wire.T_JOIN: h_join,
            wire.T_LEAVE: h_leave,
            wire.T_FAILPOINT: h_failpoint,
            wire.T_STATS: h_stats,
            wire.T_EXPLAIN: h_explain,
            wire.T_FLIGHTREC: h_flightrec,
            wire.T_SNAPSHOT: h_snapshot,
            wire.T_SYNC: h_sync,
            wire.T_FLUSH: h_flush,
            wire.T_PING: h_ping,
            wire.T_SHUTDOWN: h_shutdown,
        },
    ).start()

    if args.join:
        # ---- automatic join: announce -> snapshot sync -> gossip ----
        jhost, _, jport = args.join.rpartition(":")
        jhost = jhost or "127.0.0.1"

        def _rpc(ftype, payload, timeout=10.0):
            with _socket.create_connection(
                (jhost, int(jport)), timeout=timeout
            ) as sock:
                sock.settimeout(timeout)
                wire.send_frame(sock, ftype, payload)
                return wire.recv_frame(sock)

        try:
            rtype, joined = _rpc(wire.T_JOIN, {
                "node_id": node_id, "host": "127.0.0.1", "port": node.port,
            })
            if rtype != wire.T_JOIN_R:
                raise OSError(f"join refused: {joined}")
            members = joined.get("members", [])
            ring_ids = sorted(
                str(row[0]) for row in members
                if row[1] in (swim.ALIVE, swim.SUSPECT)
            )
            clients = {
                str(row[0]): (
                    None if str(row[0]) == node_id
                    else _make_client(str(row[0]), row[3], row[4])
                )
                for row in members if str(row[0]) in ring_ids
            }
            router = FabricRouter(
                node_id,
                ConsistentHashRing(ring_ids, vnodes=args.vnodes),
                clients, _local_submit, stats=fstats, health=health,
                takeover_grace_ms=args.grace_ms,
                pipe_factory=_pipe_factory_from({}),
                trace_propagation=bool(args.trace_propagation),
            )
            state["router"] = router
            ms = _start_membership(
                router, None,
                args.gossip_interval_ms or 250.0,
                args.suspect_timeout_ms,
                args.indirect_probes,
                node.port,
            )
            ms.merge(members, via="join")
            # warm start: the fleet's decisions, idempotently applied
            rtype, snap = _rpc(wire.T_SNAPSHOT, {})
            synced = 0
            if rtype == wire.T_SNAPSHOT_R:
                for ip, dec_name, expires, domain in snap.get(
                    "decisions", []
                ):
                    dynamic_lists.update(
                        ip, float(expires), Decision[dec_name], True, domain
                    )
                    synced += 1
        except (OSError, ValueError, KeyError) as exc:
            print(json.dumps(
                {"ready": False, "error": f"join failed: {exc}"}
            ), flush=True)
            return 2
        print(json.dumps({
            "ready": True, "node_id": node_id, "port": node.port,
            "joined": True, "synced": synced,
            "members": len(members),
        }), flush=True)
    else:
        print(json.dumps(
            {"ready": True, "node_id": node_id, "port": node.port}
        ), flush=True)

    try:
        while not shutdown.wait(0.2):
            pass
    finally:
        ms = state["membership"]
        if ms is not None:
            ms.stop()
        router = state["router"]
        if router is not None:
            router.close()
        if reader is not None:
            reader.stop()
        sched.stop()
        parts.matcher.close()
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
