"""One fabric shard as a real OS process.

`python -m banjax_tpu.fabric.worker --node-id w0 ...` builds the full
single-process engine (the same `build_engine` assembly the scenario
harness drives), wraps its banner with the decision replicator, attaches
a REAL KafkaReader to the command topic for peer decisions, and serves
the fabric wire protocol on a socket.  The dryrun harness spawns N of
these, kills one mid-flood, and audits the survivors.

Startup protocol (stdout, one JSON line):  the worker prints
`{"ready": true, "node_id": ..., "port": ...}` only after the engine is
warmed (device compile done) and the kafka reader has proven attached
(its own `fabric_ping` round-tripped), so a SIGKILL any time after
READY lands on a fully live shard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def _pin_cpu_backend() -> None:
    # mirror __graft_entry__._backend_guard: a worker must never grab a
    # real accelerator out from under the host process
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    _pin_cpu_backend()
    ap = argparse.ArgumentParser(description="banjax fabric shard worker")
    ap.add_argument("--node-id", required=True)
    ap.add_argument("--listen-port", type=int, default=0)
    ap.add_argument("--broker-port", type=int, default=0,
                    help="kafka broker port for decision replication "
                         "(0 = replication off)")
    ap.add_argument("--send-timeout-ms", type=float, default=800.0)
    ap.add_argument("--grace-ms", type=float, default=200.0)
    ap.add_argument("--vnodes", type=int, default=64)
    args = ap.parse_args(argv)

    # heavy imports AFTER the backend pin
    from banjax_tpu.decisions.model import Decision
    from banjax_tpu.fabric import wire
    from banjax_tpu.fabric.node import FabricNode
    from banjax_tpu.fabric.peer import PeerClient
    from banjax_tpu.fabric.replication import (
        DecisionReplicator,
        FabricDeduper,
        ReplicatingBanner,
    )
    from banjax_tpu.fabric.router import FabricRouter
    from banjax_tpu.fabric.hashring import ConsistentHashRing
    from banjax_tpu.fabric.stats import FabricStats
    from banjax_tpu.ingest.kafka_io import handle_command
    from banjax_tpu.resilience.health import HealthRegistry
    from banjax_tpu.scenarios.runtime import (
        RecordingBanner,
        _WARM_IP,
        build_engine,
    )
    from banjax_tpu.scenarios.shapes import RULES_YAML, T0

    node_id = args.node_id
    fstats = FabricStats()
    health = HealthRegistry()
    inner_banner = RecordingBanner()
    replicator = None
    banner = inner_banner
    if args.broker_port:
        from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

        replicator = DecisionReplicator(
            origin=node_id,
            transport=WireKafkaTransport(),
            topic="fabric.commands",
            stats=fstats,
        )
        banner = ReplicatingBanner(inner_banner, replicator)

    parts = build_engine(
        RULES_YAML,
        banner=banner,
        kafka_broker_port=args.broker_port or None,
        kafka_command_topic="fabric.commands",
        kafka_report_topic="fabric.reports",
        cfg_overrides={
            "fabric_enabled": True,
            "fabric_node_id": node_id,
            "fabric_listen": "127.0.0.1:0",
            "fabric_vnodes": args.vnodes,
            "fabric_send_timeout_ms": args.send_timeout_ms,
            "fabric_takeover_grace_ms": args.grace_ms,
        },
    )
    cfg, sched, dynamic_lists = parts.cfg, parts.sched, parts.dynamic_lists
    if replicator is not None:
        replicator.configure(cfg)
        # the origin's own kafka echo is suppressed by the deduper, so
        # its decisions land in its dynamic lists here, at publish time
        replicator.local_apply = lambda cmd: handle_command(
            cfg, cmd, dynamic_lists
        )
    sched.start()

    # ---- kafka replication consumer (real reader, real wire) ----
    reader = None
    kafka_ready = threading.Event()
    if args.broker_port:
        from banjax_tpu.ingest.kafka_io import KafkaReader
        from banjax_tpu.ingest.kafka_wire import WireKafkaTransport
        from banjax_tpu.resilience.backoff import reconnect_backoff

        deduper = FabricDeduper(
            origin=node_id,
            apply_command=lambda cmd: handle_command(
                cfg, cmd, dynamic_lists
            ),
            stats=fstats,
        )

        def _dispatch(raw) -> None:
            data = raw if isinstance(raw, bytes) else raw.encode()
            if b"fabric_ping" in data:
                try:
                    ping = json.loads(data)
                except ValueError:
                    return
                if ping.get("fabric_origin") == node_id:
                    kafka_ready.set()
                return
            deduper.dispatch(raw)

        class _Holder:
            def get(self):
                return cfg

        reader = KafkaReader(
            _Holder(), dynamic_lists, transport=WireKafkaTransport(),
            backoff=reconnect_backoff(cap=0.2, base=0.05),
            pipeline=sched,
        )
        reader.dispatch_raw = _dispatch
        reader.start()

    # ---- warmup (compile outside the measured window) ----
    warm = [
        f"{T0:.6f} {_WARM_IP} GET warm.example GET /about HTTP/1.1 warm -"
        for _ in range(48)
    ]
    for _ in range(2):
        sched.submit(list(warm))
        if not sched.flush(600):
            print(json.dumps({"ready": False, "error": "warmup hang"}),
                  flush=True)
            return 2

    # the reader attaches at the log tail at an unobservable moment:
    # keep producing pings until our own round-trips (same handshake as
    # the scenario harness's kafka mode)
    if reader is not None and replicator is not None:
        ping = json.dumps(
            {"Name": "fabric_ping", "fabric_origin": node_id}
        ).encode()
        deadline = time.monotonic() + 30
        while not kafka_ready.wait(0.05):
            if time.monotonic() > deadline:
                print(json.dumps(
                    {"ready": False, "error": "kafka never attached"}
                ), flush=True)
                return 2
            try:
                replicator.transport.send(cfg, "fabric.commands", ping)
            except OSError:
                pass

    # ---- fabric server ----
    shutdown = threading.Event()
    state = {"router": None}

    def _local_submit(lines) -> int:
        sched.submit(list(lines))
        return len(lines)

    def h_hello(payload):
        peers_map = payload.get("peers", {})
        ring = ConsistentHashRing(
            peers_map.keys(), vnodes=int(payload.get("vnodes", args.vnodes))
        )
        clients = {}
        for pid, addr in peers_map.items():
            if pid == node_id:
                clients[pid] = None
                continue
            clients[pid] = PeerClient(
                pid, addr[0], int(addr[1]),
                send_timeout_ms=float(
                    payload.get("send_timeout_ms", args.send_timeout_ms)
                ),
            )
        state["router"] = FabricRouter(
            node_id, ring, clients, _local_submit, stats=fstats,
            health=health,
            takeover_grace_ms=float(
                payload.get("grace_ms", args.grace_ms)
            ),
        )
        return wire.T_HELLO_R, {"node_id": node_id}

    def h_lines(payload):
        lines = payload.get("lines", [])
        fstats.note_received(len(lines))
        router = state["router"]
        if payload.get("route") and router is not None:
            out = router.route(lines)
            return wire.T_ACK, {"n": len(lines), **out}
        _local_submit(lines)
        fstats.note_local(len(lines))
        return wire.T_ACK, {"n": len(lines), "local": len(lines)}

    def h_peer_down(payload):
        router = state["router"]
        if router is not None:
            router.mark_dead(
                str(payload.get("peer", "")), reason="driver broadcast"
            )
        return wire.T_ACK, {}

    def h_peer_up(payload):
        router = state["router"]
        if router is not None:
            router.mark_alive(
                str(payload.get("peer", "")),
                host=payload.get("host"),
                port=payload.get("port"),
            )
        return wire.T_ACK, {}

    def h_stats(payload):
        router = state["router"]
        return wire.T_STATS_R, {
            "node_id": node_id,
            "sched": sched.stats.peek(),
            "fabric": fstats.peek(),
            "bans": list(inner_banner.regex_ban_logs),
            "decisions": list(inner_banner.decisions),
            "dynamic": list(dynamic_lists.metrics()),
            "router": router.describe() if router is not None else None,
        }

    def h_snapshot(payload):
        entries = []
        for ip, ed in dynamic_lists.format_ip_entries().items():
            entries.append([
                ip, ed.decision.name, ed.expires,
                getattr(ed, "domain", "") or "",
            ])
        return wire.T_SNAPSHOT_R, {"decisions": entries}

    def h_sync(payload):
        applied = 0
        for ip, dec_name, expires, domain in payload.get("decisions", []):
            dynamic_lists.update(
                ip, float(expires), Decision[dec_name], True, domain
            )
            applied += 1
        return wire.T_ACK, {"applied": applied}

    def h_flush(payload):
        ok = sched.flush(float(payload.get("timeout", 120)))
        return wire.T_ACK, {"flushed": bool(ok)}

    def h_ping(payload):
        return wire.T_PONG, {"node_id": node_id}

    def h_shutdown(payload):
        shutdown.set()
        return wire.T_ACK, {}

    node = FabricNode(
        "127.0.0.1", args.listen_port,
        handlers={
            wire.T_HELLO: h_hello,
            wire.T_LINES: h_lines,
            wire.T_PEER_DOWN: h_peer_down,
            wire.T_PEER_UP: h_peer_up,
            wire.T_STATS: h_stats,
            wire.T_SNAPSHOT: h_snapshot,
            wire.T_SYNC: h_sync,
            wire.T_FLUSH: h_flush,
            wire.T_PING: h_ping,
            wire.T_SHUTDOWN: h_shutdown,
        },
    ).start()

    print(json.dumps(
        {"ready": True, "node_id": node_id, "port": node.port}
    ), flush=True)

    try:
        while not shutdown.wait(0.2):
            pass
    finally:
        if reader is not None:
            reader.stop()
        sched.stop()
        parts.matcher.close()
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
