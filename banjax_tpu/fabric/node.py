"""Server side of a fabric shard: accepts peer/driver connections and
dispatches frames to registered handlers.

Thread-per-connection (peer counts are single digits), one synchronous
response per request — which is also what makes the client's pipelined
window work with FIFO ack matching: frames on one channel are answered
in order.  The frame-read path accepts both encodings
(`wire.recv_frame_any`): JSON control frames and the binary
`T_LINES_V2` data frame, whose decoded `wire.LinesV2` is passed to the
handler in place of a payload dict.  Two frame types are answered by
the node itself:

  * `T_VERSION` — the wire handshake: answers the node's wire version
    and whether it accepts shm-ring attaches.
  * `T_RING_ATTACH` — a co-located peer created a pair of SPSC shm
    rings (native/shmring.py); the node attaches and serves frames
    from the ring on a dedicated thread, same dispatch table, no TCP
    in the data path.

The frame-read path passes the `fabric.recv` failpoint; an injected
fault drops the connection exactly like a torn network would, so the
client exercises its reconnect backoff.  A malformed frame
(FrameError: torn, oversized, corrupt offset table) is logged loudly
and drops the connection — the client reconnects and retransmits its
unacked window.  Handler exceptions answer T_ERR and keep the
connection — an application error must not masquerade as a dead shard.
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints

log = logging.getLogger(__name__)

Handler = Callable[[Dict[str, Any]], Tuple[int, Dict[str, Any]]]


class FabricNode:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        handlers: Optional[Dict[int, Handler]] = None,
        allow_rings: bool = True,
    ):
        self.handlers: Dict[int, Handler] = dict(handlers or {})
        self.allow_rings = bool(allow_rings)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: list = []
        self._rings: list = []  # (ring_in, ring_out, thread)
        self._rings_lock = threading.Lock()

    def on(self, ftype: int, handler: Handler) -> None:
        self.handlers[ftype] = handler

    def start(self) -> "FabricNode":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fabric-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        try:
            self._sock.settimeout(0.25)
        except OSError:
            return  # stop() closed the socket before the thread ran
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="fabric-conn", daemon=True,
            )
            t.start()
            self._conn_threads.append(t)

    def _dispatch(self, ftype: int, payload) -> Tuple[int, Dict[str, Any]]:
        """Shared by the TCP and ring read loops.  `payload` is a dict
        for JSON frames, a wire.LinesV2 for the binary data frame."""
        if ftype == wire.T_VERSION:
            return wire.T_VERSION_R, {
                "wire": wire.WIRE_VERSION, "ring": self.allow_rings,
                # origin-section support (wire._V2_TRACE): senders only
                # set the trace bit against a peer that advertised it
                "trace": True,
            }
        handler = self.handlers.get(ftype)
        if handler is None:
            return wire.T_ERR, {"error": f"unhandled frame type {ftype}"}
        try:
            return handler(payload)
        except Exception as exc:  # answer, don't die
            return wire.T_ERR, {"error": repr(exc)}

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    ftype, payload = wire.recv_frame_any(conn)
                except socket.timeout:
                    continue
                except wire.FrameError as exc:
                    # corrupt/torn frame: loud error, drop the
                    # connection — the sender reconnects on the shared
                    # backoff and retransmits its unacked window
                    log.error("fabric node %s:%s: dropping connection on "
                              "malformed frame: %s", self.host, self.port, exc)
                    return
                except OSError:
                    return
                try:
                    failpoints.check("fabric.recv")
                except failpoints.FaultInjected:
                    return  # injected torn network: drop the connection
                if ftype == wire.T_RING_ATTACH:
                    rtype, rpayload = self._ring_attach(payload)
                else:
                    rtype, rpayload = self._dispatch(ftype, payload)
                try:
                    wire.send_frame(conn, rtype, rpayload)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ---- shm ring serving ----

    def _ring_attach(self, payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        if not self.allow_rings:
            return wire.T_ERR, {"error": "shm rings disabled on this node"}
        try:
            from banjax_tpu.native import shmring

            # the client's c2s ring is OUR inbound side
            ring_in = shmring.ShmRing(name=payload["c2s"])
            ring_out = shmring.ShmRing(name=payload["s2c"])
        except Exception as exc:  # noqa: BLE001 — decline, stay on TCP
            return wire.T_ERR, {"error": f"ring attach failed: {exc!r}"}
        t = threading.Thread(
            target=self._serve_ring, args=(ring_in, ring_out),
            name="fabric-ring", daemon=True,
        )
        with self._rings_lock:
            self._rings.append((ring_in, ring_out, t))
        t.start()
        return wire.T_ACK, {"attached": True}

    def _serve_ring(self, ring_in, ring_out) -> None:
        from banjax_tpu.native import shmring

        try:
            while not self._stop.is_set():
                try:
                    fr = shmring.read_frame(ring_in, idle_timeout_s=0.25)
                except wire.FrameError as exc:
                    log.error("fabric node %s:%s: shm ring torn: %s",
                              self.host, self.port, exc)
                    return
                if fr is None:
                    continue
                ftype, body = fr
                try:
                    payload = wire.decode_body(ftype, body)
                except wire.FrameError as exc:
                    log.error("fabric node %s:%s: malformed ring frame: %s",
                              self.host, self.port, exc)
                    return
                rtype, rpayload = self._dispatch(ftype, payload)
                try:
                    ring_out.write(
                        wire.encode_frame(rtype, rpayload), 2.0
                    )
                except OSError as exc:
                    log.error("fabric node %s:%s: ring ack write failed: %s",
                              self.host, self.port, exc)
                    return
        finally:
            try:
                ring_in.close()
                ring_out.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        with self._rings_lock:
            rings = list(self._rings)
            self._rings.clear()
        for ring_in, ring_out, t in rings:
            t.join(timeout=2.0)
