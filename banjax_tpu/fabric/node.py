"""Server side of a fabric shard: accepts peer/driver connections and
dispatches frames to registered handlers.

Thread-per-connection (peer counts are single digits), one synchronous
response per request.  The frame-read path passes the `fabric.recv`
failpoint; an injected fault drops the connection exactly like a torn
network would, so the client exercises its reconnect backoff.  Handler
exceptions answer T_ERR and keep the connection — an application error
must not masquerade as a dead shard.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints

Handler = Callable[[Dict[str, Any]], Tuple[int, Dict[str, Any]]]


class FabricNode:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        handlers: Optional[Dict[int, Handler]] = None,
    ):
        self.handlers: Dict[int, Handler] = dict(handlers or {})
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: list = []

    def on(self, ftype: int, handler: Handler) -> None:
        self.handlers[ftype] = handler

    def start(self) -> "FabricNode":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fabric-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        try:
            self._sock.settimeout(0.25)
        except OSError:
            return  # stop() closed the socket before the thread ran
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="fabric-conn", daemon=True,
            )
            t.start()
            self._conn_threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    ftype, payload = wire.recv_frame(conn)
                except socket.timeout:
                    continue
                except OSError:
                    return
                try:
                    failpoints.check("fabric.recv")
                except failpoints.FaultInjected:
                    return  # injected torn network: drop the connection
                handler = self.handlers.get(ftype)
                if handler is None:
                    rtype, rpayload = wire.T_ERR, {
                        "error": f"unhandled frame type {ftype}"
                    }
                else:
                    try:
                        rtype, rpayload = handler(payload)
                    except Exception as exc:  # answer, don't die
                        rtype, rpayload = wire.T_ERR, {"error": repr(exc)}
                try:
                    wire.send_frame(conn, rtype, rpayload)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
