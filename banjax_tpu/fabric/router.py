"""Ownership routing, the per-peer line journal, and takeover.

The router is the zero-lost-ban mechanism.  Every chunk successfully
forwarded to a peer is also appended to that peer's journal (bounded
deque of recent chunks).  When a peer is declared dead — a send
exhausted its retry budget, its breaker opened, a membership frame
said so, or gossip confirmed a suspicion (fabric/membership.py) — the
router:

  1. passes the `fabric.takeover` failpoint (armable chaos),
  2. removes the peer from the alive set (the consistent-hash ring
     then hands its ranges to the next alive points automatically),
  3. schedules the journal replay for `fabric_takeover_grace_ms`
     later — the grace is a DEADLINE, not a sleep: `mark_dead`
     returns immediately, so a death event mid-flood never stalls the
     routing caller.  The replay fires from whichever comes first of
     a `route()` call observing the deadline passed, a `poll()` tick
     (the gossip loop calls it every interval), or the dedicated
     grace timer thread,
  4. replays the dead peer's entire journal through normal routing, so
     the successor re-derives every window state the dead shard held.

Replayed lines are counted (`FabricReplayedLines`) and re-journaled
against their new owners (cascading failures still replay).  A replay
is also the one place double-processing used to leak in: a replayed
chunk can contain lines whose owner never died (the driver replays
whole direct-feed chunks).  Re-routing those would double-count their
rate-limit hits on a live shard and mint a duplicate ban (the banked
n2 precision 0.969697 bug) — so replay recomputes ownership under the
pre-death view (alive ∪ crashed) and SKIPS lines whose pre-death owner
is still alive (`FabricReplaySkippedLines`): they were delivered once
on the normal path and their window state never died.  Lines with no
alive owner are counted shed, never silently dropped.

When a pipe factory is installed (wire v2), forwards ride per-peer
pipelined windows (`fabric/peer.py` LinePipe): the group is journaled
at submit, `route()` returns to matching while frames are in flight,
and acks stream back on the pipe's I/O thread (which must never take
the router lock — gossip piggybacks are queued and merged by poll()).
Without a factory the synchronous per-group JSON path is preserved
verbatim as the negotiated fallback and differential oracle.

Dynamic membership adds two transitions the static fabric never
needed: `add_node` (a gossip-discovered joiner — the ring is rebuilt
to include it, which steals keys only from the joiner's ring
successors) and `mark_left` (a graceful leaver — removed from the
alive set with its journal CLEARED, no replay: the leaver drained its
pipeline and replicated its decisions before departing, so a replay
could only double-process).
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.peer import LinePipe, PeerClient, PeerUnavailable
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.fabric import wire
from banjax_tpu.obs import trace
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.health import HealthRegistry

# pipe_factory(peer_id, host, port, on_ack) -> LinePipe: installed by
# service/worker wiring when the pipelined data path is configured
# (fabric_inflight_frames > 0); absent => the synchronous per-group
# JSON path below, byte-for-byte the PR 11 behavior (the differential
# oracle for the transport rewrite)
PipeFactory = Callable[[str, str, int, Callable], LinePipe]


def ip_of_line(line: str) -> str:
    """The reference log format's client address (field 2)."""
    parts = line.split(" ", 2)
    return parts[1] if len(parts) > 2 else line


class FabricRouter:
    def __init__(
        self,
        node_id: str,
        ring: ConsistentHashRing,
        peers: Dict[str, PeerClient],
        local_submit: Callable[[Sequence[str]], int],
        stats: Optional[FabricStats] = None,
        health: Optional[HealthRegistry] = None,
        takeover_grace_ms: float = 500.0,
        journal_chunks: int = 4096,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        pipe_factory: Optional[PipeFactory] = None,
        trace_propagation: bool = False,
    ):
        self.node_id = node_id
        self.ring = ring
        self.peers = peers
        self.local_submit = local_submit
        # origin trace ids ride forwarded chunks only when configured
        # (fabric_trace_propagation) — inert with the tracer off, since
        # new_trace() then returns 0 and the wire omits the section
        self.trace_propagation = bool(trace_propagation)
        # whether local_submit accepts the (t_read, hop) latency-stamp
        # keywords — probed once so plain `lambda lines: n` callables
        # (tests, simple drivers) keep working unchanged
        try:
            params = inspect.signature(local_submit).parameters
            self._local_kw = "t_read" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            self._local_kw = False
        self.stats = stats or FabricStats()
        self.health = health
        self.takeover_grace_s = float(takeover_grace_ms) / 1000.0
        self._journal_chunks = int(journal_chunks)
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.RLock()
        self.alive = set(ring.node_ids)
        # peers that crashed (mark_dead) and have not come back: the
        # pre-death ownership view for replay dedupe is alive ∪ crashed
        self._crashed: set = set()
        self._pipe_factory = pipe_factory
        self._pipes: Dict[str, LinePipe] = {}
        # gossip digests from pipelined acks, drained by poll() — the
        # pipe's I/O thread must never take the router lock (it could
        # be the thread route() is waiting on for window space)
        self._gossip_inbox: deque = deque(maxlen=256)
        # graceful-membership hook: a merge callable installed by
        # SwimMembership so digests piggybacked on T_LINES acks feed
        # the membership table (convergence rides the data path)
        self.gossip_merge: Optional[Callable[[list], None]] = None
        # peer -> (declared_dead_at, replay_deadline): takeovers whose
        # grace window is still open (deadline-polled, never slept-on)
        self._pending_takeover: Dict[str, tuple] = {}
        self._journal: Dict[str, deque] = {
            p: deque(maxlen=journal_chunks) for p in ring.node_ids
        }
        for pid in ring.node_ids:
            self.stats.note_peer(pid, True)
            if self.health is not None and pid != node_id:
                self.health.register(f"fabric.peer.{pid}").ok()

    # ---- routing ----

    def route(
        self, lines: Sequence[str], replay: bool = False,
        t_read: Optional[float] = None,
    ) -> Dict[str, int]:
        """Deliver every line to its owner.  Returns the disposition
        ledger {local, forwarded, shed, skipped} — their sum is always
        len(lines).  `skipped` is only ever nonzero on a replay: lines
        whose pre-death owner is still alive were already processed
        once, and replaying them would double-count rate-limit hits
        (the n2 duplicate-ban bug).  `t_read` is the tailer-read
        monotonic stamp of the chunk (e2e latency; rides the wire with
        forwarded groups)."""
        self.poll()  # complete any takeover whose grace deadline passed
        out = {"local": 0, "forwarded": 0, "shed": 0, "skipped": 0}
        # the origin trace: allocated HERE, before ownership fans the
        # chunk out, so a ban minted on any owner shard joins back to
        # this admission batch (0 = tracer off: the wire section and
        # every span call no-op)
        tid = trace.new_trace() if self.trace_propagation else 0
        span = trace.begin("fabric.route", tid, args={"lines": len(lines)})
        try:
            with self._lock:
                self._route_locked(list(lines), out, replay, tid, t_read)
        finally:
            span.note("disposition", dict(out))
            trace.end(span)
        return out

    def _local_call(
        self, group: List[str], t_read: Optional[float], hop: str
    ) -> None:
        if self._local_kw:
            self.local_submit(group, t_read=t_read, hop=hop)
        else:
            self.local_submit(group)

    def _route_locked(
        self, lines: List[str], out: Dict[str, int], replay: bool,
        trace_id: int = 0, t_read: Optional[float] = None,
    ) -> None:
        if not lines:
            return
        if not self.alive:
            self.stats.note_shed(len(lines))
            out["shed"] += len(lines)
            return
        if replay:
            lines = self._filter_replay_locked(lines, out)
            if not lines:
                return
        by_owner = self.ring.partition(
            [ip_of_line(ln) for ln in lines], self.alive
        )
        for owner, idxs in by_owner.items():
            group = [lines[i] for i in idxs]
            if owner == self.node_id or self.peers.get(owner) is None:
                self._local_call(group, t_read, "local")
                self.stats.note_local(len(group))
                out["local"] += len(group)
                continue
            pipe = self._pipe_for_locked(owner)
            if pipe is not None:
                self._forward_pipelined_locked(
                    owner, pipe, group, out, replay, trace_id, t_read
                )
            else:
                self._forward_sync_locked(
                    owner, group, out, replay, trace_id, t_read
                )

    def _filter_replay_locked(
        self, lines: List[str], out: Dict[str, int]
    ) -> List[str]:
        """Replay dedupe: recompute ownership under the pre-death view
        (alive ∪ crashed).  A replayed line whose pre-death owner is
        still alive was delivered to that owner on the normal path
        before the crash — it is skipped, not re-processed.  Lines the
        crashed peers owned are kept: those window states died with
        their shard and MUST be re-derived (zero-lost-ban)."""
        if not self._crashed:
            return lines
        view = self.alive | self._crashed
        pre = self.ring.partition([ip_of_line(ln) for ln in lines], view)
        keep: List[str] = []
        skipped = 0
        for owner, idxs in pre.items():
            if owner in self._crashed:
                keep.extend(lines[i] for i in idxs)
            else:
                skipped += len(idxs)
        if skipped:
            self.stats.note_replay_skipped(skipped)
            out["skipped"] += skipped
        return keep

    def _forward_pipelined_locked(
        self, owner: str, pipe: LinePipe, group: List[str],
        out: Dict[str, int], replay: bool,
        trace_id: int = 0, t_read: Optional[float] = None,
    ) -> None:
        """Wire v2 data path: journal at submit (the takeover replay
        source), hand the group to the peer's pipelined window, return
        to matching — acks stream back on the pipe's I/O thread."""
        entry = tuple(group)
        self._journal[owner].append(entry)
        try:
            pipe.submit(group, replay=replay, trace_id=trace_id,
                        t_read=t_read)
        except PeerUnavailable:
            # the group never entered the window: pull it back out of
            # the journal (first equal chunk — same multiset) and
            # reroute it NOW; the takeover replay covers the rest
            try:
                self._journal[owner].remove(entry)
            except ValueError:
                pass
            self.mark_dead(owner, reason="pipe dead")
            self._route_locked(group, out, replay, trace_id, t_read)
            return
        self.stats.note_forwarded(len(group))
        out["forwarded"] += len(group)

    def _forward_sync_locked(
        self, owner: str, group: List[str],
        out: Dict[str, int], replay: bool,
        trace_id: int = 0, t_read: Optional[float] = None,
    ) -> None:
        """The PR 11 synchronous JSON path — kept verbatim as the
        negotiated fallback and the differential oracle
        (fabric_inflight_frames = 0)."""
        payload: Dict[str, object] = {"lines": group, "replay": replay}
        if self.trace_propagation and self.node_id:
            # same origin section the v2 binary frame carries; old
            # receivers ignore the unknown key
            payload["origin"] = {
                "node": self.node_id,
                "runs": [[trace_id, len(group)]],
                "t_read": t_read,
            }
        try:
            _rt, rpayload = self.peers[owner].request(wire.T_LINES, payload)
        except PeerUnavailable:
            self.mark_dead(owner, reason="send failed")
            self._route_locked(group, out, replay, trace_id, t_read)
            return
        self.stats.note_forwarded(len(group))
        out["forwarded"] += len(group)
        self._journal[owner].append(tuple(group))
        if self.health is not None:
            comp = self.health.get(f"fabric.peer.{owner}")
            if comp is not None:
                comp.beat()
        if self.gossip_merge is not None:
            piggy = rpayload.get("gossip")
            if piggy:
                self.gossip_merge(piggy)

    def owner_of(self, ip: str) -> Optional[str]:
        """Current owner of one key under the alive view (the
        cross-shard /decisions/explain proxy asks this before deciding
        whether to answer locally or over the peer wire)."""
        with self._lock:
            if not self.alive:
                return None
            return self.ring.owner(ip, self.alive)

    def alive_peers(self) -> Dict[str, PeerClient]:
        """{peer_id: client} for every ALIVE remote member — the fleet
        scrape / incident-capture fan-out set."""
        with self._lock:
            return {
                pid: c for pid, c in self.peers.items()
                if c is not None and pid in self.alive
            }

    # ---- pipelined data path plumbing ----

    def _pipe_for_locked(self, owner: str) -> Optional[LinePipe]:
        if self._pipe_factory is None:
            return None
        pipe = self._pipes.get(owner)
        if pipe is None:
            client = self.peers.get(owner)
            if client is None:
                return None
            pipe = self._pipe_factory(
                owner, client.host, client.port, self._ack_handler(owner)
            )
            self._pipes[owner] = pipe
        return pipe

    def _ack_handler(self, owner: str) -> Callable[[Dict[str, object]], None]:
        """Runs on the pipe's I/O thread: liveness beat + gossip
        piggyback capture.  MUST NOT take the router lock (route() may
        hold it while waiting for this very thread to open window
        space)."""
        def _on_ack(payload: Dict[str, object]) -> None:
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{owner}")
                if comp is not None:
                    comp.beat()
            piggy = payload.get("gossip")
            if piggy:
                self._gossip_inbox.append(piggy)
        return _on_ack

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Drain every pipe: all submitted groups sent AND acked.  The
        routed feed path (`route:true` chunk handlers) and the
        settle/leave audits call this so an upstream ack means LANDED
        at the final owner, not parked in a window — the replay dedupe
        filter's soundness rests on exactly that.  A pipe found dead
        here triggers its peer's takeover immediately (journal replay
        through live routing) and the reroutes are drained in the next
        pass.  True iff fully drained."""
        deadline = time.monotonic() + timeout_s
        while True:
            self.poll()  # complete any takeover whose deadline passed
            with self._lock:
                pipes = dict(self._pipes)
            ok = True
            for pipe in pipes.values():
                if pipe.dead:
                    continue
                left = deadline - time.monotonic()
                ok = pipe.flush(max(0.0, left)) and ok
            dead = [pid for pid, p in pipes.items() if p.dead]
            if not dead:
                return ok
            for pid in dead:
                self.mark_dead(pid, reason="pipe dead at flush")
                with self._lock:
                    self._drop_pipe_locked(pid)  # even if already !alive
            if time.monotonic() >= deadline:
                return False

    def _drop_pipe_locked(self, owner: str) -> None:
        pipe = self._pipes.pop(owner, None)
        if pipe is not None:
            pipe.close()

    def close(self) -> None:
        """Shut down every pipe (service/worker teardown)."""
        with self._lock:
            pipes = list(self._pipes.values())
            self._pipes.clear()
        for pipe in pipes:
            pipe.close()

    # ---- membership / takeover ----

    def mark_dead(self, peer_id: str, reason: str = "") -> None:
        """Declare a peer dead and schedule the takeover of its range.
        Returns immediately: the grace window is a deadline (completed
        by route()/poll()/the grace timer), never an inline sleep — a
        death event mid-flood must not stall the routing caller."""
        with self._lock:
            if peer_id not in self.alive or peer_id == self.node_id:
                return
            try:
                failpoints.check("fabric.takeover")
            except failpoints.FaultInjected:
                # chaos: the takeover path itself faults once — the
                # takeover must still complete (retried immediately;
                # the episode is visible in failpoints.snapshot())
                pass
            self.alive.discard(peer_id)
            self._crashed.add(peer_id)
            self._drop_pipe_locked(peer_id)
            self.stats.note_peer(peer_id, False)
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{peer_id}")
                if comp is not None:
                    comp.failed(reason or "declared dead")
            t0 = self._clock()
            self._pending_takeover[peer_id] = (
                t0, t0 + self.takeover_grace_s
            )
        if self.takeover_grace_s <= 0:
            self._complete_takeover(peer_id)
            return
        threading.Thread(
            target=self._grace_then_complete, args=(peer_id,),
            name=f"fabric-takeover-{peer_id}", daemon=True,
        ).start()

    def _grace_then_complete(self, peer_id: str) -> None:
        self._sleep(self.takeover_grace_s)
        self._complete_takeover(peer_id)

    def poll(self) -> None:
        """Complete every pending takeover whose grace deadline has
        passed, and merge gossip digests captured from pipelined acks.
        Cheap when nothing is pending; called at route() entry and
        from the gossip tick."""
        if self.gossip_merge is not None:
            while self._gossip_inbox:
                try:
                    piggy = self._gossip_inbox.popleft()
                except IndexError:
                    break
                self.gossip_merge(piggy)
        if not self._pending_takeover:
            return
        now = self._clock()
        with self._lock:
            due = [
                p for p, (_t0, deadline)
                in self._pending_takeover.items() if now >= deadline
            ]
        for peer_id in due:
            self._complete_takeover(peer_id)

    def _complete_takeover(self, peer_id: str) -> None:
        """Drain the dead peer's journal through normal routing —
        idempotent: the pending entry is popped under the lock, so the
        grace timer, route() and poll() can race without replaying
        twice."""
        with self._lock:
            ent = self._pending_takeover.pop(peer_id, None)
            if ent is None:
                return
            t0, _deadline = ent
            chunks = list(self._journal[peer_id])
            self._journal[peer_id].clear()
            replayed = 0
            out = {"local": 0, "forwarded": 0, "shed": 0, "skipped": 0}
            for chunk in chunks:
                replayed += len(chunk)
                self.stats.note_replayed(len(chunk))
                self._route_locked(list(chunk), out, replay=True)
            self.stats.note_takeover(peer_id, self._clock() - t0, replayed)

    def takeover_pending(self, peer_id: Optional[str] = None) -> bool:
        with self._lock:
            if peer_id is None:
                return bool(self._pending_takeover)
            return peer_id in self._pending_takeover

    def mark_alive(
        self, peer_id: str,
        host: Optional[str] = None, port: Optional[int] = None,
    ) -> None:
        """A peer rejoined (possibly at a new address).  Its old ranges
        return to it by ring recomputation alone — no journal replay, so
        a rejoin never double-processes."""
        with self._lock:
            if peer_id == self.node_id:
                self.alive.add(peer_id)  # undo a self-drain (aborted leave)
                return
            # a revival during the grace window voids the takeover: the
            # peer is back, its journal is its own again
            self._pending_takeover.pop(peer_id, None)
            self._crashed.discard(peer_id)
            self._drop_pipe_locked(peer_id)  # a fresh pipe dials the new addr
            client = self.peers.get(peer_id)
            if client is not None and host is not None and port is not None:
                client.connect_to(host, port)
            self.alive.add(peer_id)
            self.stats.note_peer(peer_id, True)
            if self.health is not None and peer_id in self.ring.node_ids:
                self.health.register(f"fabric.peer.{peer_id}").ok("rejoined")

    def add_node(
        self, peer_id: str, client: Optional[PeerClient],
    ) -> None:
        """A brand-new member (gossip join): rebuild the ring to
        include it.  Ring insertion steals keys only from the joiner's
        ring successors (tests/unit/test_fabric.py proves the bound);
        nobody else's ownership moves."""
        with self._lock:
            if peer_id in self.ring.node_ids:
                self.mark_alive(
                    peer_id,
                    host=getattr(client, "host", None),
                    port=getattr(client, "port", None),
                )
                return
            self.ring = ConsistentHashRing(
                self.ring.node_ids + (peer_id,), vnodes=self.ring.vnodes
            )
            if peer_id != self.node_id:
                self.peers[peer_id] = client
            self._journal[peer_id] = deque(maxlen=self._journal_chunks)
            self._crashed.discard(peer_id)
            self.alive.add(peer_id)
            self.stats.note_peer(peer_id, True)
            if self.health is not None and peer_id != self.node_id:
                self.health.register(f"fabric.peer.{peer_id}").ok("joined")

    def mark_left(self, peer_id: str, reason: str = "graceful leave") -> None:
        """A peer departed gracefully: it drained its pipeline and
        replicated its decisions before announcing LEFT, so its journal
        is CLEARED without replay — a replay could only double-process.
        Calling it on our own id is the leaver's self-drain: drop out
        of the alive set so every subsequent line forwards to its new
        owner (the pure-membership handback)."""
        with self._lock:
            self.alive.discard(peer_id)
            self._crashed.discard(peer_id)
            self._pending_takeover.pop(peer_id, None)
            self._drop_pipe_locked(peer_id)
            journal = self._journal.get(peer_id)
            if journal is not None:
                journal.clear()
            if peer_id == self.node_id:
                return
            self.stats.note_peer(peer_id, False)
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{peer_id}")
                if comp is not None:
                    comp.ok(reason)  # a planned leave is not a failure

    # ---- introspection (fabric.json / /metrics) ----

    def describe(self) -> Dict[str, object]:
        with self._lock:
            alive = sorted(self.alive)
            pending = sorted(self._pending_takeover)
            peers = {
                pid: {
                    "alive": pid in self.alive,
                    "addr": (
                        f"{self.peers[pid].host}:{self.peers[pid].port}"
                        if self.peers.get(pid) is not None else "local"
                    ),
                    "journal_chunks": len(self._journal.get(pid, ())),
                    "breaker": (
                        self.peers[pid].breaker.state
                        if self.peers.get(pid) is not None else ""
                    ),
                    "transport": (
                        f"{self._pipes[pid].mode}/{self._pipes[pid].transport}"
                        f"[{self._pipes[pid].inflight()}]"
                        if pid in self._pipes and not self._pipes[pid].dead
                        else "sync-json"
                    ),
                }
                for pid in self.ring.node_ids
            }
        return {
            "node_id": self.node_id,
            "vnodes": self.ring.vnodes,
            "alive": alive,
            "pending_takeovers": pending,
            "peers": peers,
            "ownership": self.ring.ownership_fractions(set(alive)),
            "last_takeover": self.stats.last_takeover,
        }
