"""Ownership routing, the per-peer line journal, and takeover.

The router is the zero-lost-ban mechanism.  Every chunk successfully
forwarded to a peer is also appended to that peer's journal (bounded
deque of recent chunks).  When a peer is declared dead — a send
exhausted its retry budget, its breaker opened, or a membership frame
said so — the router:

  1. passes the `fabric.takeover` failpoint (armable chaos),
  2. removes the peer from the alive set (the consistent-hash ring
     then hands its ranges to the next alive points automatically),
  3. waits `fabric_takeover_grace_ms` for in-flight work to drain,
  4. replays the dead peer's entire journal through normal routing, so
     the successor re-derives every window state the dead shard held.

Replayed lines are counted (`FabricReplayedLines`), re-journaled
against their new owners (cascading failures still replay), and may
double-process lines a survivor already saw — that can only ADD bans
(a precision cost the harness reports), never lose one: recall vs the
oracle stays 1.0.  Lines with no alive owner are counted shed, never
silently dropped.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.health import HealthRegistry


def ip_of_line(line: str) -> str:
    """The reference log format's client address (field 2)."""
    parts = line.split(" ", 2)
    return parts[1] if len(parts) > 2 else line


class FabricRouter:
    def __init__(
        self,
        node_id: str,
        ring: ConsistentHashRing,
        peers: Dict[str, PeerClient],
        local_submit: Callable[[Sequence[str]], int],
        stats: Optional[FabricStats] = None,
        health: Optional[HealthRegistry] = None,
        takeover_grace_ms: float = 500.0,
        journal_chunks: int = 4096,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.node_id = node_id
        self.ring = ring
        self.peers = peers
        self.local_submit = local_submit
        self.stats = stats or FabricStats()
        self.health = health
        self.takeover_grace_s = float(takeover_grace_ms) / 1000.0
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.RLock()
        self.alive = set(ring.node_ids)
        self._journal: Dict[str, deque] = {
            p: deque(maxlen=journal_chunks) for p in ring.node_ids
        }
        for pid in ring.node_ids:
            self.stats.note_peer(pid, True)
            if self.health is not None and pid != node_id:
                self.health.register(f"fabric.peer.{pid}").ok()

    # ---- routing ----

    def route(self, lines: Sequence[str], replay: bool = False) -> Dict[str, int]:
        """Deliver every line to its owner.  Returns the disposition
        ledger {local, forwarded, shed} — their sum is always
        len(lines)."""
        out = {"local": 0, "forwarded": 0, "shed": 0}
        with self._lock:
            self._route_locked(list(lines), out, replay)
        return out

    def _route_locked(
        self, lines: List[str], out: Dict[str, int], replay: bool
    ) -> None:
        if not lines:
            return
        if not self.alive:
            self.stats.note_shed(len(lines))
            out["shed"] += len(lines)
            return
        by_owner = self.ring.partition(
            [ip_of_line(ln) for ln in lines], self.alive
        )
        for owner, idxs in by_owner.items():
            group = [lines[i] for i in idxs]
            if owner == self.node_id or self.peers.get(owner) is None:
                self.local_submit(group)
                self.stats.note_local(len(group))
                out["local"] += len(group)
                continue
            try:
                self.peers[owner].request(
                    wire.T_LINES, {"lines": group, "replay": replay}
                )
            except PeerUnavailable:
                self.mark_dead(owner, reason="send failed")
                self._route_locked(group, out, replay)
                continue
            self.stats.note_forwarded(len(group))
            out["forwarded"] += len(group)
            self._journal[owner].append(tuple(group))
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{owner}")
                if comp is not None:
                    comp.beat()

    # ---- membership / takeover ----

    def mark_dead(self, peer_id: str, reason: str = "") -> None:
        """Declare a peer dead and take over its range: grace, then
        journal replay through normal routing."""
        with self._lock:
            if peer_id not in self.alive or peer_id == self.node_id:
                return
            t0 = self._clock()
            try:
                failpoints.check("fabric.takeover")
            except failpoints.FaultInjected:
                # chaos: the takeover path itself faults once — the
                # takeover must still complete (retried immediately;
                # the episode is visible in failpoints.snapshot())
                pass
            self.alive.discard(peer_id)
            self.stats.note_peer(peer_id, False)
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{peer_id}")
                if comp is not None:
                    comp.failed(reason or "declared dead")
            if self.takeover_grace_s > 0:
                self._sleep(self.takeover_grace_s)
            chunks = list(self._journal[peer_id])
            self._journal[peer_id].clear()
            replayed = 0
            out = {"local": 0, "forwarded": 0, "shed": 0}
            for chunk in chunks:
                replayed += len(chunk)
                self.stats.note_replayed(len(chunk))
                self._route_locked(list(chunk), out, replay=True)
            self.stats.note_takeover(peer_id, self._clock() - t0, replayed)

    def mark_alive(
        self, peer_id: str,
        host: Optional[str] = None, port: Optional[int] = None,
    ) -> None:
        """A peer rejoined (possibly at a new address).  Its old ranges
        return to it by ring recomputation alone — no journal replay, so
        a rejoin never double-processes."""
        with self._lock:
            if peer_id == self.node_id:
                return
            client = self.peers.get(peer_id)
            if client is not None and host is not None and port is not None:
                client.connect_to(host, port)
            self.alive.add(peer_id)
            self.stats.note_peer(peer_id, True)
            if self.health is not None and peer_id in self.ring.node_ids:
                self.health.register(f"fabric.peer.{peer_id}").ok("rejoined")

    # ---- introspection (fabric.json / /metrics) ----

    def describe(self) -> Dict[str, object]:
        with self._lock:
            alive = sorted(self.alive)
            peers = {
                pid: {
                    "alive": pid in self.alive,
                    "addr": (
                        f"{self.peers[pid].host}:{self.peers[pid].port}"
                        if self.peers.get(pid) is not None else "local"
                    ),
                    "journal_chunks": len(self._journal.get(pid, ())),
                    "breaker": (
                        self.peers[pid].breaker.state
                        if self.peers.get(pid) is not None else ""
                    ),
                }
                for pid in self.ring.node_ids
            }
        return {
            "node_id": self.node_id,
            "vnodes": self.ring.vnodes,
            "alive": alive,
            "peers": peers,
            "ownership": self.ring.ownership_fractions(set(alive)),
            "last_takeover": self.stats.last_takeover,
        }
