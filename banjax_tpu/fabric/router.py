"""Ownership routing, the per-peer line journal, and takeover.

The router is the zero-lost-ban mechanism.  Every chunk successfully
forwarded to a peer is also appended to that peer's journal (bounded
deque of recent chunks).  When a peer is declared dead — a send
exhausted its retry budget, its breaker opened, a membership frame
said so, or gossip confirmed a suspicion (fabric/membership.py) — the
router:

  1. passes the `fabric.takeover` failpoint (armable chaos),
  2. removes the peer from the alive set (the consistent-hash ring
     then hands its ranges to the next alive points automatically),
  3. schedules the journal replay for `fabric_takeover_grace_ms`
     later — the grace is a DEADLINE, not a sleep: `mark_dead`
     returns immediately, so a death event mid-flood never stalls the
     routing caller.  The replay fires from whichever comes first of
     a `route()` call observing the deadline passed, a `poll()` tick
     (the gossip loop calls it every interval), or the dedicated
     grace timer thread,
  4. replays the dead peer's entire journal through normal routing, so
     the successor re-derives every window state the dead shard held.

Replayed lines are counted (`FabricReplayedLines`), re-journaled
against their new owners (cascading failures still replay), and may
double-process lines a survivor already saw — that can only ADD bans
(a precision cost the harness reports), never lose one: recall vs the
oracle stays 1.0.  Lines with no alive owner are counted shed, never
silently dropped.

Dynamic membership adds two transitions the static fabric never
needed: `add_node` (a gossip-discovered joiner — the ring is rebuilt
to include it, which steals keys only from the joiner's ring
successors) and `mark_left` (a graceful leaver — removed from the
alive set with its journal CLEARED, no replay: the leaver drained its
pipeline and replicated its decisions before departing, so a replay
could only double-process).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.health import HealthRegistry


def ip_of_line(line: str) -> str:
    """The reference log format's client address (field 2)."""
    parts = line.split(" ", 2)
    return parts[1] if len(parts) > 2 else line


class FabricRouter:
    def __init__(
        self,
        node_id: str,
        ring: ConsistentHashRing,
        peers: Dict[str, PeerClient],
        local_submit: Callable[[Sequence[str]], int],
        stats: Optional[FabricStats] = None,
        health: Optional[HealthRegistry] = None,
        takeover_grace_ms: float = 500.0,
        journal_chunks: int = 4096,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.node_id = node_id
        self.ring = ring
        self.peers = peers
        self.local_submit = local_submit
        self.stats = stats or FabricStats()
        self.health = health
        self.takeover_grace_s = float(takeover_grace_ms) / 1000.0
        self._journal_chunks = int(journal_chunks)
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.RLock()
        self.alive = set(ring.node_ids)
        # graceful-membership hook: a merge callable installed by
        # SwimMembership so digests piggybacked on T_LINES acks feed
        # the membership table (convergence rides the data path)
        self.gossip_merge: Optional[Callable[[list], None]] = None
        # peer -> (declared_dead_at, replay_deadline): takeovers whose
        # grace window is still open (deadline-polled, never slept-on)
        self._pending_takeover: Dict[str, tuple] = {}
        self._journal: Dict[str, deque] = {
            p: deque(maxlen=journal_chunks) for p in ring.node_ids
        }
        for pid in ring.node_ids:
            self.stats.note_peer(pid, True)
            if self.health is not None and pid != node_id:
                self.health.register(f"fabric.peer.{pid}").ok()

    # ---- routing ----

    def route(self, lines: Sequence[str], replay: bool = False) -> Dict[str, int]:
        """Deliver every line to its owner.  Returns the disposition
        ledger {local, forwarded, shed} — their sum is always
        len(lines)."""
        self.poll()  # complete any takeover whose grace deadline passed
        out = {"local": 0, "forwarded": 0, "shed": 0}
        with self._lock:
            self._route_locked(list(lines), out, replay)
        return out

    def _route_locked(
        self, lines: List[str], out: Dict[str, int], replay: bool
    ) -> None:
        if not lines:
            return
        if not self.alive:
            self.stats.note_shed(len(lines))
            out["shed"] += len(lines)
            return
        by_owner = self.ring.partition(
            [ip_of_line(ln) for ln in lines], self.alive
        )
        for owner, idxs in by_owner.items():
            group = [lines[i] for i in idxs]
            if owner == self.node_id or self.peers.get(owner) is None:
                self.local_submit(group)
                self.stats.note_local(len(group))
                out["local"] += len(group)
                continue
            try:
                _rt, rpayload = self.peers[owner].request(
                    wire.T_LINES, {"lines": group, "replay": replay}
                )
            except PeerUnavailable:
                self.mark_dead(owner, reason="send failed")
                self._route_locked(group, out, replay)
                continue
            self.stats.note_forwarded(len(group))
            out["forwarded"] += len(group)
            self._journal[owner].append(tuple(group))
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{owner}")
                if comp is not None:
                    comp.beat()
            if self.gossip_merge is not None:
                piggy = rpayload.get("gossip")
                if piggy:
                    self.gossip_merge(piggy)

    # ---- membership / takeover ----

    def mark_dead(self, peer_id: str, reason: str = "") -> None:
        """Declare a peer dead and schedule the takeover of its range.
        Returns immediately: the grace window is a deadline (completed
        by route()/poll()/the grace timer), never an inline sleep — a
        death event mid-flood must not stall the routing caller."""
        with self._lock:
            if peer_id not in self.alive or peer_id == self.node_id:
                return
            try:
                failpoints.check("fabric.takeover")
            except failpoints.FaultInjected:
                # chaos: the takeover path itself faults once — the
                # takeover must still complete (retried immediately;
                # the episode is visible in failpoints.snapshot())
                pass
            self.alive.discard(peer_id)
            self.stats.note_peer(peer_id, False)
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{peer_id}")
                if comp is not None:
                    comp.failed(reason or "declared dead")
            t0 = self._clock()
            self._pending_takeover[peer_id] = (
                t0, t0 + self.takeover_grace_s
            )
        if self.takeover_grace_s <= 0:
            self._complete_takeover(peer_id)
            return
        threading.Thread(
            target=self._grace_then_complete, args=(peer_id,),
            name=f"fabric-takeover-{peer_id}", daemon=True,
        ).start()

    def _grace_then_complete(self, peer_id: str) -> None:
        self._sleep(self.takeover_grace_s)
        self._complete_takeover(peer_id)

    def poll(self) -> None:
        """Complete every pending takeover whose grace deadline has
        passed.  Cheap when nothing is pending; called at route()
        entry and from the gossip tick."""
        if not self._pending_takeover:
            return
        now = self._clock()
        with self._lock:
            due = [
                p for p, (_t0, deadline)
                in self._pending_takeover.items() if now >= deadline
            ]
        for peer_id in due:
            self._complete_takeover(peer_id)

    def _complete_takeover(self, peer_id: str) -> None:
        """Drain the dead peer's journal through normal routing —
        idempotent: the pending entry is popped under the lock, so the
        grace timer, route() and poll() can race without replaying
        twice."""
        with self._lock:
            ent = self._pending_takeover.pop(peer_id, None)
            if ent is None:
                return
            t0, _deadline = ent
            chunks = list(self._journal[peer_id])
            self._journal[peer_id].clear()
            replayed = 0
            out = {"local": 0, "forwarded": 0, "shed": 0}
            for chunk in chunks:
                replayed += len(chunk)
                self.stats.note_replayed(len(chunk))
                self._route_locked(list(chunk), out, replay=True)
            self.stats.note_takeover(peer_id, self._clock() - t0, replayed)

    def takeover_pending(self, peer_id: Optional[str] = None) -> bool:
        with self._lock:
            if peer_id is None:
                return bool(self._pending_takeover)
            return peer_id in self._pending_takeover

    def mark_alive(
        self, peer_id: str,
        host: Optional[str] = None, port: Optional[int] = None,
    ) -> None:
        """A peer rejoined (possibly at a new address).  Its old ranges
        return to it by ring recomputation alone — no journal replay, so
        a rejoin never double-processes."""
        with self._lock:
            if peer_id == self.node_id:
                self.alive.add(peer_id)  # undo a self-drain (aborted leave)
                return
            # a revival during the grace window voids the takeover: the
            # peer is back, its journal is its own again
            self._pending_takeover.pop(peer_id, None)
            client = self.peers.get(peer_id)
            if client is not None and host is not None and port is not None:
                client.connect_to(host, port)
            self.alive.add(peer_id)
            self.stats.note_peer(peer_id, True)
            if self.health is not None and peer_id in self.ring.node_ids:
                self.health.register(f"fabric.peer.{peer_id}").ok("rejoined")

    def add_node(
        self, peer_id: str, client: Optional[PeerClient],
    ) -> None:
        """A brand-new member (gossip join): rebuild the ring to
        include it.  Ring insertion steals keys only from the joiner's
        ring successors (tests/unit/test_fabric.py proves the bound);
        nobody else's ownership moves."""
        with self._lock:
            if peer_id in self.ring.node_ids:
                self.mark_alive(
                    peer_id,
                    host=getattr(client, "host", None),
                    port=getattr(client, "port", None),
                )
                return
            self.ring = ConsistentHashRing(
                self.ring.node_ids + (peer_id,), vnodes=self.ring.vnodes
            )
            if peer_id != self.node_id:
                self.peers[peer_id] = client
            self._journal[peer_id] = deque(maxlen=self._journal_chunks)
            self.alive.add(peer_id)
            self.stats.note_peer(peer_id, True)
            if self.health is not None and peer_id != self.node_id:
                self.health.register(f"fabric.peer.{peer_id}").ok("joined")

    def mark_left(self, peer_id: str, reason: str = "graceful leave") -> None:
        """A peer departed gracefully: it drained its pipeline and
        replicated its decisions before announcing LEFT, so its journal
        is CLEARED without replay — a replay could only double-process.
        Calling it on our own id is the leaver's self-drain: drop out
        of the alive set so every subsequent line forwards to its new
        owner (the pure-membership handback)."""
        with self._lock:
            self.alive.discard(peer_id)
            self._pending_takeover.pop(peer_id, None)
            journal = self._journal.get(peer_id)
            if journal is not None:
                journal.clear()
            if peer_id == self.node_id:
                return
            self.stats.note_peer(peer_id, False)
            if self.health is not None:
                comp = self.health.get(f"fabric.peer.{peer_id}")
                if comp is not None:
                    comp.ok(reason)  # a planned leave is not a failure

    # ---- introspection (fabric.json / /metrics) ----

    def describe(self) -> Dict[str, object]:
        with self._lock:
            alive = sorted(self.alive)
            pending = sorted(self._pending_takeover)
            peers = {
                pid: {
                    "alive": pid in self.alive,
                    "addr": (
                        f"{self.peers[pid].host}:{self.peers[pid].port}"
                        if self.peers.get(pid) is not None else "local"
                    ),
                    "journal_chunks": len(self._journal.get(pid, ())),
                    "breaker": (
                        self.peers[pid].breaker.state
                        if self.peers.get(pid) is not None else ""
                    ),
                }
                for pid in self.ring.node_ids
            }
        return {
            "node_id": self.node_id,
            "vnodes": self.ring.vnodes,
            "alive": alive,
            "pending_takeovers": pending,
            "peers": peers,
            "ownership": self.ring.ownership_fractions(set(alive)),
            "last_takeover": self.stats.last_takeover,
        }
