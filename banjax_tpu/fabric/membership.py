"""SWIM-style gossip membership for the decision fabric.

PR 11 froze the fabric topology at startup: `fabric_peers` was the
authority and a dead shard was only discovered when a *forwarded line*
failed to send — unbounded detection latency on quiet keyspace ranges,
and no way to grow or shrink the fleet without restarting it.  This
module turns `fabric_peers` into a seed list and makes membership a
live, gossiped protocol:

  * **Probing** — every `fabric_gossip_interval_ms` the node direct-
    pings one member (round-robin over a per-round shuffled order, the
    SWIM schedule that bounds time-to-first-probe).  A failed direct
    ping fans out `fabric_indirect_probes` ping-req relays through
    other members; only when nobody can reach the target does it become
    SUSPECT.
  * **Suspicion + incarnation** — a SUSPECT member has
    `fabric_suspect_timeout_ms` to produce liveness evidence before it
    is confirmed DEAD.  Every member carries an incarnation number; a
    slow-but-alive node that learns of its own suspicion (the suspicion
    rides every digest) refutes it by bumping its incarnation and
    gossiping ALIVE(i+1), which outranks SUSPECT(i) everywhere.
  * **Piggybacking** — the membership digest rides every gossip frame
    AND every forwarded-chunk ack (router.py merges it), so under load
    convergence is carried by the data path for free and the dedicated
    probe traffic stays a few hundred bytes per interval.
  * **Events drive the existing machinery** — confirmed-dead calls
    `router.mark_dead` (journal-replay takeover, now deadline-polled),
    refuted/revived calls `router.mark_alive`, a brand-new member calls
    `router.add_node` (ring insertion), and a graceful LEFT calls
    `router.mark_left` (journal cleared, NO replay: the leaver drained
    before departing, so replay could only double-process).

State precedence is standard SWIM: a higher incarnation always wins;
at equal incarnation the more severe status wins
(alive < suspect < dead < left).  LEFT is terminal per incarnation —
only the node itself (rejoining with a bumped incarnation) can revive
it.

Failpoints: `fabric.gossip.ping` (before every outgoing probe frame),
`fabric.gossip.ack` (before answering a probe — arm it with
mode=sleep to fake a slow node and drive the suspect/refute cycle),
`fabric.membership.update` (before merging a received digest; an
injected fault drops that update — gossip re-delivers).
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.resilience import failpoints

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"
LEFT = "left"

# severity order at EQUAL incarnation; a higher incarnation beats all
_RANK = {ALIVE: 0, SUSPECT: 1, DEAD: 2, LEFT: 3}


class Member:
    __slots__ = ("node_id", "host", "port", "incarnation", "status")

    def __init__(self, node_id: str, host: str, port: int,
                 incarnation: int = 0, status: str = ALIVE):
        self.node_id = node_id
        self.host = host
        self.port = int(port)
        self.incarnation = int(incarnation)
        self.status = status

    def entry(self) -> List[Any]:
        """One digest row: [id, status, incarnation, host, port]."""
        return [self.node_id, self.status, self.incarnation,
                self.host, self.port]


class SwimMembership:
    """The per-node membership table + probe loop.

    Thread-safe; the probe loop runs on one daemon thread.  All
    transitions funnel through `_apply`, which is what makes the
    announce-once contract hold: the harness READY/PEER_UP handshake
    and gossip discovery both land here, and only an actual status
    transition fires a router action — a rejoining worker is announced
    exactly once no matter how many paths observe it.
    """

    def __init__(
        self,
        node_id: str,
        host: str,
        port: int,
        router: Any = None,
        stats: Optional[FabricStats] = None,
        gossip_interval_ms: float = 1000.0,
        suspect_timeout_ms: float = 3000.0,
        indirect_probes: int = 2,
        peer_factory: Optional[Callable[[str, str, int], Any]] = None,
        clock: Callable[[], float] = time.monotonic,
        rng_seed: Optional[int] = None,
        health_provider: Optional[Callable[[], int]] = None,
    ):
        self.node_id = node_id
        self.router = router
        self.stats = stats or FabricStats()
        # compact per-node health bits (obs/fleet.py encoding) that ride
        # every gossip frame as a parallel "health" key — digest rows
        # stay the strict 5-tuple old nodes unpack
        self.health_provider = health_provider
        self.interval_s = float(gossip_interval_ms) / 1000.0
        self.suspect_timeout_s = float(suspect_timeout_ms) / 1000.0
        self.indirect_probes = int(indirect_probes)
        self.peer_factory = peer_factory
        self._clock = clock
        self._rng = random.Random(
            rng_seed if rng_seed is not None else node_id
        )
        self._lock = threading.RLock()
        self._members: Dict[str, Member] = {
            node_id: Member(node_id, host, port)
        }
        self._suspect_deadline: Dict[str, float] = {}
        self._last_alive: Dict[str, float] = {}
        self._probe_order: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats.note_member_state(node_id, ALIVE)

    # ---- seeding / lifecycle ----

    def seed(self, peers: Dict[str, Tuple[str, int]]) -> None:
        """Install the static seed list (fabric_peers / HELLO payload)
        as ALIVE members at incarnation 0."""
        now = self._clock()
        with self._lock:
            for nid, (host, port) in peers.items():
                if nid == self.node_id:
                    me = self._members[self.node_id]
                    me.host, me.port = host, int(port)
                    continue
                if nid not in self._members:
                    self._members[nid] = Member(nid, host, int(port))
                    self._last_alive[nid] = now
                    self.stats.note_member_state(nid, ALIVE)

    def start(self) -> "SwimMembership":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="fabric-gossip", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # ---- digests ----

    def digest(self) -> List[List[Any]]:
        with self._lock:
            return [m.entry() for m in self._members.values()]

    def merge(self, digest: Optional[Sequence[Sequence[Any]]],
              via: str = "") -> List[Tuple[str, str]]:
        """Apply a received digest; returns [(event, node_id), ...].
        The `fabric.membership.update` failpoint drops the whole update
        (gossip re-delivers it on a later frame)."""
        if not digest:
            return []
        try:
            failpoints.check("fabric.membership.update")
        except failpoints.FaultInjected:
            return []
        events: List[Tuple[str, str]] = []
        for row in digest:
            try:
                nid, status, inc, host, port = row
            except (TypeError, ValueError):
                continue
            events.extend(self._apply(
                str(nid), str(status), int(inc), str(host), int(port)
            ))
        self._dispatch(events)
        return events

    def _health_map(self) -> Dict[str, int]:
        """Everything this node knows about fleet health: learned peer
        bits plus its own freshly-sampled bits (last writer wins on the
        receiving side; our own entry is always recomputed, never
        echoed back stale)."""
        out = self.stats.peer_health_snapshot()
        if self.health_provider is not None:
            try:
                out[self.node_id] = int(self.health_provider())
            except Exception:  # health must never break gossip
                pass
        return out

    def merge_health(self, health: Any) -> None:
        """Absorb a received "health" piggyback map."""
        if not isinstance(health, dict):
            return
        for nid, bits in health.items():
            if str(nid) == self.node_id:
                continue  # own bits come from health_provider only
            try:
                self.stats.note_peer_health(str(nid), int(bits))
            except (TypeError, ValueError):
                continue

    # ---- transitions (the one funnel) ----

    def _apply(self, nid: str, status: str, inc: int,
               host: str, port: int) -> List[Tuple[str, str]]:
        """Pure state transition under the membership lock; returns the
        committed events WITHOUT firing side effects — callers dispatch
        after releasing the lock (the router path re-enters membership
        via ack piggybacks, so calling the router under this lock would
        be an ABBA deadlock)."""
        if status not in _RANK:
            return []
        actions: List[Tuple[str, str]] = []
        with self._lock:
            if nid == self.node_id:
                # refutation: someone thinks we are suspect/dead/left at
                # an incarnation that covers ours — outbid it
                me = self._members[nid]
                if status != ALIVE and inc >= me.incarnation:
                    me.incarnation = inc + 1
                    me.status = ALIVE
                    actions.append(("self_refute", nid))
                elif inc > me.incarnation:
                    me.incarnation = inc
                return actions
            cur = self._members.get(nid)
            if cur is None:
                m = Member(nid, host, port, inc, status)
                self._members[nid] = m
                self.stats.note_member_state(nid, status)
                if status == ALIVE:
                    self._last_alive[nid] = self._clock()
                    actions.append(("joined", nid))
                elif status == SUSPECT:
                    self._suspect_deadline[nid] = (
                        self._clock() + self.suspect_timeout_s
                    )
                    actions.append(("suspect", nid))
                return actions
            if inc < cur.incarnation or (
                inc == cur.incarnation
                and _RANK[status] <= _RANK[cur.status]
            ):
                if status == ALIVE and inc == cur.incarnation \
                        and cur.status == ALIVE:
                    self._last_alive[nid] = self._clock()
                return actions
            prev = cur.status
            cur.incarnation = inc
            cur.status = status
            if host and port:
                cur.host, cur.port = host, int(port)
            self.stats.note_member_state(nid, status)
            now = self._clock()
            if status == ALIVE:
                self._suspect_deadline.pop(nid, None)
                self._last_alive[nid] = now
                if prev == SUSPECT:
                    actions.append(("refuted", nid))
                elif prev in (DEAD, LEFT):
                    actions.append(("joined", nid))
            elif status == SUSPECT:
                self._suspect_deadline.setdefault(
                    nid, now + self.suspect_timeout_s
                )
                if prev == ALIVE:
                    actions.append(("suspect", nid))
            elif status == DEAD:
                self._suspect_deadline.pop(nid, None)
                if prev != DEAD:
                    self.stats.note_detection(
                        now - self._last_alive.get(nid, now)
                    )
                    actions.append(("confirmed_dead", nid))
            elif status == LEFT:
                self._suspect_deadline.pop(nid, None)
                if prev != LEFT:
                    actions.append(("left", nid))
            return actions

    def _dispatch(self, actions: List[Tuple[str, str]]
                  ) -> List[Tuple[str, str]]:
        """Fire the router/stats side effects for committed transitions.
        MUST be called without self._lock held (see _apply)."""
        for event, nid in actions:
            if event == "self_refute":
                self.stats.note_membership_event("refuted")
                continue
            self.stats.note_membership_event(event)
            if self.router is None:
                continue
            with self._lock:
                m = self._members.get(nid)
            if event == "confirmed_dead":
                self.router.mark_dead(nid, reason="gossip: suspicion "
                                                  "timeout expired")
            elif event in ("refuted", "joined"):
                if m is not None and nid not in self.router.ring.node_ids:
                    client = (
                        self.peer_factory(nid, m.host, m.port)
                        if self.peer_factory is not None else None
                    )
                    self.router.add_node(nid, client)
                elif m is not None:
                    self.router.mark_alive(nid, host=m.host, port=m.port)
            elif event == "left":
                self.router.mark_left(nid)
            elif event == "suspect":
                # suspicion alone does not reroute: the member keeps its
                # ranges until confirmed dead (or refutes)
                pass
        return actions

    # ---- externally-driven transitions ----

    def note_peer_up(self, nid: str, host: Optional[str] = None,
                     port: Optional[int] = None) -> bool:
        """The harness/admin PEER_UP path.  Revives a non-alive member
        by outbidding its current incarnation; a second notification
        for an already-alive member is a no-op — this is the
        exactly-once announcement funnel."""
        with self._lock:
            cur = self._members.get(nid)
            if cur is not None and cur.status == ALIVE:
                if host and port:
                    cur.host, cur.port = host, int(port)
                return False
            inc = cur.incarnation + 1 if cur is not None else 0
            h = host or (cur.host if cur is not None else "")
            p = port or (cur.port if cur is not None else 0)
            actions = self._apply(nid, ALIVE, inc, h, int(p or 0))
        self._dispatch(actions)
        return bool(actions)

    def note_peer_down(self, nid: str) -> bool:
        """The harness/admin PEER_DOWN path: declare dead at the
        member's current incarnation (a live node will refute)."""
        with self._lock:
            cur = self._members.get(nid)
            if cur is None or cur.status in (DEAD, LEFT):
                return False
            actions = self._apply(
                nid, DEAD, cur.incarnation, cur.host, cur.port
            )
        self._dispatch(actions)
        return bool(actions)

    def begin_leave(self) -> List[List[Any]]:
        """Mark self LEFT at a bumped incarnation and return the digest
        to announce.  The caller drains first (stop owning, flush);
        this is the final goodbye."""
        with self._lock:
            me = self._members[self.node_id]
            me.incarnation += 1
            me.status = LEFT
            self.stats.note_member_state(self.node_id, LEFT)
            self.stats.note_membership_event("left")
            return [m.entry() for m in self._members.values()]

    # ---- wire handlers (installed on the FabricNode) ----

    def handle_ping(self, payload: dict) -> Tuple[int, dict]:
        """T_GOSSIP_PING: merge the prober's digest, answer ours.  The
        `fabric.gossip.ack` failpoint sits before the answer — arm it
        with mode=sleep to fake a slow-but-alive node."""
        failpoints.check("fabric.gossip.ack")
        self.merge(payload.get("digest"), via=str(payload.get("from", "")))
        self.merge_health(payload.get("health"))
        return wire.T_GOSSIP_ACK, {
            "node_id": self.node_id, "digest": self.digest(),
            "health": self._health_map(),
        }

    def handle_ping_req(self, payload: dict) -> Tuple[int, dict]:
        """T_GOSSIP_PING_REQ: probe `target` on the requester's behalf
        (SWIM indirect probe — a one-hop path around a partitioned
        direct link)."""
        self.merge(payload.get("digest"), via=str(payload.get("from", "")))
        self.merge_health(payload.get("health"))
        target = str(payload.get("target", ""))
        with self._lock:
            m = self._members.get(target)
            addr = (m.host, m.port) if m is not None else None
        ok = False
        if addr is not None:
            ok = self._probe(target, addr[0], addr[1])
        return wire.T_GOSSIP_ACK, {
            "node_id": self.node_id, "ok": ok, "digest": self.digest(),
            "health": self._health_map(),
        }

    def handle_join(self, payload: dict) -> Tuple[int, dict]:
        """T_JOIN: a newcomer announces itself to this seed.  Insert it
        (gossip spreads the news) and answer the full membership so the
        joiner starts convergent."""
        nid = str(payload.get("node_id", ""))
        host = str(payload.get("host", ""))
        port = int(payload.get("port", 0))
        if nid:
            self.note_peer_up(nid, host=host, port=port)
        return wire.T_JOIN_R, {
            "node_id": self.node_id, "members": self.digest()
        }

    # ---- the probe loop ----

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # the gossip loop must never die
                pass

    def tick(self) -> None:
        """One protocol round: expire suspicions, complete any pending
        deadline-polled takeovers, probe the next member."""
        self._expire_suspicions()
        if self.router is not None:
            self.router.poll()
        target = self._next_probe_target()
        if target is None:
            return
        nid, host, port = target
        if self._probe(nid, host, port):
            self._apply_alive_evidence(nid)
            return
        if self._indirect_probe(nid):
            self._apply_alive_evidence(nid)
            return
        self._suspect_locally(nid)

    def _expire_suspicions(self) -> None:
        now = self._clock()
        actions: List[Tuple[str, str]] = []
        with self._lock:
            due = [nid for nid, dl in self._suspect_deadline.items()
                   if now >= dl]
            for nid in due:
                cur = self._members.get(nid)
                if cur is None or cur.status != SUSPECT:
                    self._suspect_deadline.pop(nid, None)
                    continue
                actions.extend(self._apply(
                    nid, DEAD, cur.incarnation, cur.host, cur.port
                ))
        self._dispatch(actions)

    def _next_probe_target(self) -> Optional[Tuple[str, str, int]]:
        with self._lock:
            candidates = {
                nid: m for nid, m in self._members.items()
                if nid != self.node_id and m.status in (ALIVE, SUSPECT)
            }
            if not candidates:
                return None
            self._probe_order = [
                nid for nid in self._probe_order if nid in candidates
            ]
            if not self._probe_order:
                self._probe_order = list(candidates)
                self._rng.shuffle(self._probe_order)
            nid = self._probe_order.pop(0)
            m = candidates[nid]
            return nid, m.host, m.port

    def _apply_alive_evidence(self, nid: str) -> None:
        with self._lock:
            cur = self._members.get(nid)
            if cur is None:
                return
            actions = self._apply(
                nid, ALIVE, cur.incarnation, cur.host, cur.port
            )
            self._last_alive[nid] = self._clock()
        self._dispatch(actions)

    def _suspect_locally(self, nid: str) -> None:
        with self._lock:
            cur = self._members.get(nid)
            if cur is None or cur.status != ALIVE:
                return
            actions = self._apply(
                nid, SUSPECT, cur.incarnation, cur.host, cur.port
            )
        self._dispatch(actions)

    def _indirect_probe(self, target: str) -> bool:
        """Ask up to `indirect_probes` other alive members to probe the
        target for us; any success is liveness evidence."""
        with self._lock:
            relays = [
                m for nid, m in self._members.items()
                if nid not in (self.node_id, target) and m.status == ALIVE
            ]
            self._rng.shuffle(relays)
            relays = relays[: self.indirect_probes]
        for relay in relays:
            resp = self._send(
                relay.host, relay.port, wire.T_GOSSIP_PING_REQ,
                {"from": self.node_id, "target": target,
                 "digest": self.digest(), "health": self._health_map()},
            )
            if resp is not None:
                self.merge(resp.get("digest"), via=relay.node_id)
                self.merge_health(resp.get("health"))
                if resp.get("ok"):
                    return True
        return False

    def _probe(self, nid: str, host: str, port: int) -> bool:
        resp = self._send(
            host, port, wire.T_GOSSIP_PING,
            {"from": self.node_id, "digest": self.digest(),
             "health": self._health_map()},
        )
        if resp is None:
            return False
        self.merge(resp.get("digest"), via=nid)
        self.merge_health(resp.get("health"))
        return True

    def _send(self, host: str, port: int, ftype: int,
              payload: dict) -> Optional[dict]:
        """One ephemeral request/response exchange.  Deliberately NOT
        the data-path PeerClient: a probe must not queue behind a large
        forwarded chunk, and its timeout is the gossip interval, not
        the send timeout."""
        try:
            failpoints.check("fabric.gossip.ping")
        except failpoints.FaultInjected:
            return None
        timeout = max(0.05, self.interval_s)
        try:
            with socket.create_connection(
                (host, port), timeout=timeout
            ) as sock:
                sock.settimeout(timeout)
                wire.send_frame(sock, ftype, payload)
                rtype, rpayload = wire.recv_frame(sock)
        except (OSError, ValueError):
            return None
        self.stats.note_gossip_bytes(
            len(json.dumps(payload, separators=(",", ":"))) + 5
        )
        if rtype != wire.T_GOSSIP_ACK:
            return None
        return rpayload

    # ---- introspection (fabric.json / T_STATS) ----

    def describe(self) -> Dict[str, object]:
        with self._lock:
            return {
                "node_id": self.node_id,
                "incarnation": self._members[self.node_id].incarnation,
                "members": {
                    nid: {
                        "status": m.status,
                        "incarnation": m.incarnation,
                        "addr": f"{m.host}:{m.port}",
                    }
                    for nid, m in sorted(self._members.items())
                },
                "suspects": sorted(self._suspect_deadline),
            }

    def status_of(self, nid: str) -> Optional[str]:
        with self._lock:
            m = self._members.get(nid)
            return m.status if m is not None else None
