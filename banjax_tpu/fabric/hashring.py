"""Consistent-hash ring over the IP keyspace.

Each node contributes `vnodes` points on a 64-bit ring; an IP is owned
by the first ALIVE point clockwise from its hash.  Excluding a dead
node from the alive set makes its ranges fall to the next alive points
automatically — takeover needs no explicit reassignment table, and a
rejoined node reclaims exactly its old ranges (the ring is a pure
function of the node-id set).

blake2b keeps placement identical across processes and Python runs
(`hash()` is salted per-process and useless here).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


def _h64(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class ConsistentHashRing:
    """Deterministic vnode ring.  Immutable after construction; alive
    sets are passed per-lookup so every caller (driver, each worker)
    converges on the same ownership from the same membership view."""

    def __init__(self, node_ids: Iterable[str], vnodes: int = 64):
        self.node_ids: Tuple[str, ...] = tuple(sorted(set(node_ids)))
        if not self.node_ids:
            raise ValueError("ring needs at least one node")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        points: List[Tuple[int, str]] = []
        for nid in self.node_ids:
            for v in range(self.vnodes):
                points.append((_h64(f"{nid}#{v}"), nid))
        points.sort()
        self._points = points
        self._hashes = [p[0] for p in points]

    def owner(self, key: str, alive: Optional[Set[str]] = None) -> str:
        """First alive node clockwise from hash(key)."""
        if alive is None:
            live = self.node_ids
        else:
            live = tuple(n for n in self.node_ids if n in alive)
            if not live:
                raise ValueError("no alive nodes in ring")
        h = _h64(key)
        start = bisect.bisect_right(self._hashes, h)
        n = len(self._points)
        for off in range(n):
            nid = self._points[(start + off) % n][1]
            if alive is None or nid in alive:
                return nid
        return live[0]  # unreachable: live is non-empty

    def partition(
        self, keys: Sequence[str], alive: Optional[Set[str]] = None
    ) -> Dict[str, List[int]]:
        """Indices of `keys` grouped by owning node."""
        out: Dict[str, List[int]] = {}
        for i, k in enumerate(keys):
            out.setdefault(self.owner(k, alive), []).append(i)
        return out

    def ownership_fractions(
        self, alive: Optional[Set[str]] = None, samples: int = 4096
    ) -> Dict[str, float]:
        """Sampled keyspace share per node — introspection only
        (fabric.json, /metrics), never used for routing."""
        counts: Dict[str, int] = {}
        for i in range(samples):
            nid = self.owner(f"sample-{i}", alive)
            counts[nid] = counts.get(nid, 0) + 1
        return {n: c / samples for n, c in sorted(counts.items())}
