"""Production assembly of the decision fabric for cli.BanjaxApp.

One FabricService per process, built only when `fabric_enabled`.  It
owns the fabric pieces and exposes exactly the seams the app needs:

  * ``submit(lines)`` — the tailer's consume path: lines this shard
    owns go down the local pipeline, everything else rides a peer
    socket to its owner (router.py);
  * ``wrap_banner(banner)`` — decisions fan out to the command topic
    (replication.py) on top of whatever the inner banner effects;
  * ``dispatch_raw(raw)`` — the KafkaReader drain hook: own-origin
    echoes and duplicate (origin, seq) pairs are suppressed, fresh
    peer decisions are applied (idempotently) to the dynamic lists;
  * ``describe()`` — the flight recorder's fabric.json and the
    /metrics peer table.

Topology: `fabric_peers` seeds the ring.  With gossip membership on
(`fabric_gossip_interval_ms > 0`, the default) the SWIM layer
(membership.py) owns liveness from there — periodic probes confirm
deaths within the suspect timeout without waiting for a forwarded line
to fail, newcomers announce with T_JOIN and are ring-inserted live,
and graceful leavers gossip LEFT.  PEER_DOWN/PEER_UP admin frames
funnel through the same membership table so a rejoining worker is
announced exactly once.  With gossip off the fabric degrades to
PR 11's static behavior (death discovered by a failed send or an admin
frame only).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.membership import SwimMembership
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric.peer import LinePipe, PeerClient
from banjax_tpu.fabric.replication import (
    DecisionReplicator,
    FabricDeduper,
    ReplicatingBanner,
)
from banjax_tpu.fabric.router import FabricRouter
from banjax_tpu.fabric.stats import FabricStats


def _split_addr(addr: str) -> tuple:
    host, _, port = str(addr).rpartition(":")
    return host or "127.0.0.1", int(port)


class FabricService:
    def __init__(
        self,
        config: Any,
        local_submit: Callable[[Sequence[str]], int],
        apply_command: Callable[[Dict[str, Any]], None],
        health=None,
        transport: Any = None,
    ):
        if transport is None:
            from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

            transport = WireKafkaTransport()
        self.node_id = config.fabric_node_id
        self.stats = FabricStats()
        self._send_timeout_ms = config.fabric_send_timeout_ms
        peers_cfg = dict(config.fabric_peers or {})
        node_ids = sorted(peers_cfg) if peers_cfg else [self.node_id]
        ring = ConsistentHashRing(node_ids, vnodes=config.fabric_vnodes)
        clients: Dict[str, Optional[PeerClient]] = {}
        for pid in node_ids:
            if pid == self.node_id:
                clients[pid] = None
                continue
            phost, pport = _split_addr(peers_cfg[pid])
            clients[pid] = PeerClient(
                pid, phost, pport,
                send_timeout_ms=config.fabric_send_timeout_ms,
            )
        self.replicator = DecisionReplicator(
            self.node_id, transport, config.kafka_command_topic,
            stats=self.stats, config=config, local_apply=apply_command,
        )
        self.deduper = FabricDeduper(
            self.node_id, apply_command, stats=self.stats
        )
        self._config = config
        self.router = FabricRouter(
            self.node_id, ring, clients, local_submit,
            stats=self.stats, health=health,
            takeover_grace_ms=config.fabric_takeover_grace_ms,
            pipe_factory=(
                self._make_pipe
                if getattr(config, "fabric_inflight_frames", 0) > 0
                else None
            ),
        )
        lhost, lport = _split_addr(config.fabric_listen)
        self.membership: Optional[SwimMembership] = None
        handlers = {
            wire.T_LINES: self._h_lines,
            wire.T_LINES_V2: self._h_lines_v2,
            wire.T_PING: self._h_ping,
            wire.T_PEER_DOWN: self._h_peer_down,
            wire.T_PEER_UP: self._h_peer_up,
            wire.T_STATS: self._h_stats,
        }
        if getattr(config, "fabric_gossip_interval_ms", 0) > 0:
            self.membership = SwimMembership(
                self.node_id, lhost, lport,
                router=self.router, stats=self.stats,
                gossip_interval_ms=config.fabric_gossip_interval_ms,
                suspect_timeout_ms=config.fabric_suspect_timeout_ms,
                indirect_probes=config.fabric_indirect_probes,
                peer_factory=self._make_client,
            )
            self.membership.seed({
                pid: _split_addr(addr) for pid, addr in peers_cfg.items()
            })
            # convergence rides the data path: digests piggybacked on
            # forwarded-chunk acks feed the membership table
            self.router.gossip_merge = self.membership.merge
            handlers[wire.T_GOSSIP_PING] = self.membership.handle_ping
            handlers[wire.T_GOSSIP_PING_REQ] = self.membership.handle_ping_req
            handlers[wire.T_JOIN] = self.membership.handle_join
        self.node = FabricNode(lhost, lport, handlers=handlers)
        self._local_submit = local_submit

    def _make_client(self, pid: str, host: str, port: int) -> PeerClient:
        return PeerClient(
            pid, host, port, send_timeout_ms=self._send_timeout_ms
        )

    def _make_pipe(self, pid: str, host: str, port: int, on_ack) -> LinePipe:
        """Router's pipelined data-path factory (fabric_inflight_frames
        > 0): one windowed LinePipe per forwarded-to peer."""
        c = self._config
        return LinePipe(
            pid, host, port, node_id=self.node_id,
            send_timeout_ms=c.fabric_send_timeout_ms,
            inflight_frames=c.fabric_inflight_frames,
            frame_max_bytes=c.fabric_frame_max_bytes,
            wire_v2=c.fabric_wire_v2,
            shm=c.fabric_shm_enabled,
            shm_ring_bytes=c.fabric_shm_ring_bytes,
            stats=self.stats, on_ack=on_ack,
        )

    # ---- lifecycle ----

    def start(self) -> "FabricService":
        self.node.start()
        if self.membership is not None:
            self.membership.start()
        return self

    def stop(self) -> None:
        if self.membership is not None:
            self.membership.stop()
        self.router.flush(2.0)  # land in-flight forwards, best effort
        self.router.close()
        self.node.stop()
        for client in self.router.peers.values():
            if client is not None:
                client.close()

    # ---- app seams ----

    def submit(self, lines: Sequence[str]) -> Dict[str, int]:
        """The tailer's consume path: route every line to its owner."""
        return self.router.route(lines)

    def wrap_banner(self, banner: Any) -> ReplicatingBanner:
        return ReplicatingBanner(banner, self.replicator)

    def dispatch_raw(self, raw: Any) -> None:
        """KafkaReader drain hook (replaces the default dispatch)."""
        self.deduper.dispatch(raw)

    def describe(self) -> Dict[str, object]:
        out: Dict[str, object] = {"enabled": True}
        out.update(self.router.describe())
        out["stats"] = self.stats.peek()
        if self.membership is not None:
            out["membership"] = self.membership.describe()
        return out

    # ---- wire handlers (peer side) ----

    def _h_lines(self, payload: dict):
        lines = payload.get("lines", [])
        self.stats.note_received(len(lines))
        piggy = (
            {"gossip": self.membership.digest()}
            if self.membership is not None else {}
        )
        if "seq" in payload:
            # a pipelined JSON-mode sender matches acks FIFO by seq
            piggy["seq"] = payload["seq"]
        if payload.get("route"):
            out = self.router.route(
                lines, replay=bool(payload.get("replay"))
            )
            if out["forwarded"]:
                # ack upstream == landed at the final owner (the replay
                # dedupe filter's soundness rests on this; see worker.py)
                self.router.flush(15.0)
            return wire.T_ACK, {"n": len(lines), **out, **piggy}
        self._local_submit(lines)
        self.stats.note_local(len(lines))
        return wire.T_ACK, {"n": len(lines), "local": len(lines), **piggy}

    def _h_lines_v2(self, fr):
        # binary data frame (wire.LinesV2): a peer's pipelined forward —
        # the sender computed ownership, the lines are ours
        lines = list(fr.lines)
        self.stats.note_received(len(lines))
        self._local_submit(lines)
        self.stats.note_local(len(lines))
        ack = {"seq": fr.seq, "n": len(lines), "local": len(lines)}
        if self.membership is not None:
            ack["gossip"] = self.membership.digest()
        return wire.T_ACK, ack

    def _h_ping(self, payload: dict):
        return wire.T_PONG, {"node_id": self.node_id}

    def _h_peer_down(self, payload: dict):
        pid = str(payload.get("peer", ""))
        if self.membership is not None:
            self.membership.note_peer_down(pid)
        else:
            self.router.mark_dead(pid, reason="peer_down frame")
        return wire.T_ACK, {}

    def _h_peer_up(self, payload: dict):
        pid = str(payload.get("peer", ""))
        host, port = payload.get("host"), payload.get("port")
        if self.membership is not None:
            # exactly-once funnel: a duplicate notification (harness
            # handshake racing gossip discovery) is a no-op
            self.membership.note_peer_up(pid, host=host, port=port)
        else:
            self.router.mark_alive(pid, host=host, port=port)
        return wire.T_ACK, {}

    def _h_stats(self, payload: dict):
        out = {
            "node_id": self.node_id,
            "fabric": self.stats.peek(),
            "router": self.router.describe(),
        }
        if self.membership is not None:
            out["membership"] = self.membership.describe()
        return wire.T_STATS_R, out
