"""Production assembly of the decision fabric for cli.BanjaxApp.

One FabricService per process, built only when `fabric_enabled`.  It
owns the fabric pieces and exposes exactly the seams the app needs:

  * ``submit(lines)`` — the tailer's consume path: lines this shard
    owns go down the local pipeline, everything else rides a peer
    socket to its owner (router.py);
  * ``wrap_banner(banner)`` — decisions fan out to the command topic
    (replication.py) on top of whatever the inner banner effects;
  * ``dispatch_raw(raw)`` — the KafkaReader drain hook: own-origin
    echoes and duplicate (origin, seq) pairs are suppressed, fresh
    peer decisions are applied (idempotently) to the dynamic lists;
  * ``describe()`` — the flight recorder's fabric.json and the
    /metrics peer table.

Topology: `fabric_peers` seeds the ring.  With gossip membership on
(`fabric_gossip_interval_ms > 0`, the default) the SWIM layer
(membership.py) owns liveness from there — periodic probes confirm
deaths within the suspect timeout without waiting for a forwarded line
to fail, newcomers announce with T_JOIN and are ring-inserted live,
and graceful leavers gossip LEFT.  PEER_DOWN/PEER_UP admin frames
funnel through the same membership table so a rejoining worker is
announced exactly once.  With gossip off the fabric degrades to
PR 11's static behavior (death discovered by a failed send or an admin
frame only).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional, Sequence

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.membership import SwimMembership
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric.peer import LinePipe, PeerClient, PeerUnavailable
from banjax_tpu.fabric.replication import (
    DecisionReplicator,
    FabricDeduper,
    ReplicatingBanner,
)
from banjax_tpu.fabric.router import FabricRouter, ip_of_line
from banjax_tpu.fabric.stats import FabricStats


def _split_addr(addr: str) -> tuple:
    host, _, port = str(addr).rpartition(":")
    return host or "127.0.0.1", int(port)


class FabricService:
    def __init__(
        self,
        config: Any,
        local_submit: Callable[[Sequence[str]], int],
        apply_command: Callable[[Dict[str, Any]], None],
        health=None,
        transport: Any = None,
        metrics_text_fn: Optional[Callable[[], str]] = None,
        explain_fn: Optional[Callable[[str], Dict[str, Any]]] = None,
        health_bits_fn: Optional[Callable[[], int]] = None,
    ):
        if transport is None:
            from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

            transport = WireKafkaTransport()
        self.node_id = config.fabric_node_id
        self.stats = FabricStats()
        self._send_timeout_ms = config.fabric_send_timeout_ms
        self._metrics_text_fn = metrics_text_fn
        self._explain_fn = explain_fn
        peers_cfg = dict(config.fabric_peers or {})
        node_ids = sorted(peers_cfg) if peers_cfg else [self.node_id]
        ring = ConsistentHashRing(node_ids, vnodes=config.fabric_vnodes)
        clients: Dict[str, Optional[PeerClient]] = {}
        for pid in node_ids:
            if pid == self.node_id:
                clients[pid] = None
                continue
            phost, pport = _split_addr(peers_cfg[pid])
            clients[pid] = PeerClient(
                pid, phost, pport,
                send_timeout_ms=config.fabric_send_timeout_ms,
            )
        self.replicator = DecisionReplicator(
            self.node_id, transport, config.kafka_command_topic,
            stats=self.stats, config=config, local_apply=apply_command,
        )
        self.deduper = FabricDeduper(
            self.node_id, apply_command, stats=self.stats
        )
        self._config = config
        self.router = FabricRouter(
            self.node_id, ring, clients, local_submit,
            stats=self.stats, health=health,
            takeover_grace_ms=config.fabric_takeover_grace_ms,
            pipe_factory=(
                self._make_pipe
                if getattr(config, "fabric_inflight_frames", 0) > 0
                else None
            ),
            trace_propagation=getattr(
                config, "fabric_trace_propagation", False
            ),
        )
        lhost, lport = _split_addr(config.fabric_listen)
        self.membership: Optional[SwimMembership] = None
        handlers = {
            wire.T_LINES: self._h_lines,
            wire.T_LINES_V2: self._h_lines_v2,
            wire.T_PING: self._h_ping,
            wire.T_PEER_DOWN: self._h_peer_down,
            wire.T_PEER_UP: self._h_peer_up,
            wire.T_STATS: self._h_stats,
            wire.T_EXPLAIN: self._h_explain,
            wire.T_FLIGHTREC: self._h_flightrec,
        }
        if getattr(config, "fabric_gossip_interval_ms", 0) > 0:
            self.membership = SwimMembership(
                self.node_id, lhost, lport,
                router=self.router, stats=self.stats,
                gossip_interval_ms=config.fabric_gossip_interval_ms,
                suspect_timeout_ms=config.fabric_suspect_timeout_ms,
                indirect_probes=config.fabric_indirect_probes,
                peer_factory=self._make_client,
                health_provider=health_bits_fn,
            )
            self.membership.seed({
                pid: _split_addr(addr) for pid, addr in peers_cfg.items()
            })
            # convergence rides the data path: digests piggybacked on
            # forwarded-chunk acks feed the membership table
            self.router.gossip_merge = self.membership.merge
            handlers[wire.T_GOSSIP_PING] = self.membership.handle_ping
            handlers[wire.T_GOSSIP_PING_REQ] = self.membership.handle_ping_req
            handlers[wire.T_JOIN] = self.membership.handle_join
        self.node = FabricNode(lhost, lport, handlers=handlers)
        self._local_submit = local_submit
        # keyword-capable submit seam (pipeline e2e latency, PR 20):
        # probed ONCE — a plain `lambda lines: n` test double keeps
        # working, a (lines, t_read=, hop=) callable gets the hop stamp
        try:
            params = inspect.signature(local_submit).parameters
            self._local_kw = "t_read" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            self._local_kw = False

    def _make_client(self, pid: str, host: str, port: int) -> PeerClient:
        return PeerClient(
            pid, host, port, send_timeout_ms=self._send_timeout_ms
        )

    def _make_pipe(self, pid: str, host: str, port: int, on_ack) -> LinePipe:
        """Router's pipelined data-path factory (fabric_inflight_frames
        > 0): one windowed LinePipe per forwarded-to peer."""
        c = self._config
        return LinePipe(
            pid, host, port, node_id=self.node_id,
            send_timeout_ms=c.fabric_send_timeout_ms,
            inflight_frames=c.fabric_inflight_frames,
            frame_max_bytes=c.fabric_frame_max_bytes,
            wire_v2=c.fabric_wire_v2,
            shm=c.fabric_shm_enabled,
            shm_ring_bytes=c.fabric_shm_ring_bytes,
            stats=self.stats, on_ack=on_ack,
            trace_propagation=getattr(c, "fabric_trace_propagation", False),
        )

    # ---- lifecycle ----

    def start(self) -> "FabricService":
        self.node.start()
        if self.membership is not None:
            self.membership.start()
        return self

    def stop(self) -> None:
        if self.membership is not None:
            self.membership.stop()
        self.router.flush(2.0)  # land in-flight forwards, best effort
        self.router.close()
        self.node.stop()
        for client in self.router.peers.values():
            if client is not None:
                client.close()

    # ---- app seams ----

    def submit(self, lines: Sequence[str],
               t_read: Optional[float] = None) -> Dict[str, int]:
        """The tailer's consume path: route every line to its owner.
        ``t_read`` is the tailer's monotonic read stamp — it rides the
        wire with forwarded chunks so the owner's e2e histogram charges
        the fabric hop its true cost."""
        return self.router.route(lines, t_read=t_read)

    def wrap_banner(self, banner: Any) -> ReplicatingBanner:
        return ReplicatingBanner(banner, self.replicator)

    def dispatch_raw(self, raw: Any) -> None:
        """KafkaReader drain hook (replaces the default dispatch)."""
        self.deduper.dispatch(raw)

    def describe(self) -> Dict[str, object]:
        out: Dict[str, object] = {"enabled": True}
        out.update(self.router.describe())
        out["stats"] = self.stats.peek()
        if self.membership is not None:
            out["membership"] = self.membership.describe()
        return out

    # ---- wire handlers (peer side) ----

    def _drain_forwarded(self, lines, origin_node: str = "",
                         origin_runs=(), origin_t_read=None) -> None:
        """Owner-side drain of a forwarded chunk.

        When the sender propagated origin attribution, three things
        happen here (the cross-host half of the tentpole): each line's
        IP is noted in the OriginIndex so a ban fired from this chunk
        carries ``(origin_node, origin_trace_id)`` in its provenance
        record; a linked ``fabric.remote-drain`` span opens under the
        ORIGIN trace id (same trace as the admission batch tailed on
        the sender); and the submit is stamped hop="fabric" with the
        sender's read time so the e2e histogram spans the wire."""
        from banjax_tpu.obs import trace

        spans = []
        if origin_node:
            runs = [(int(t), int(c)) for t, c in (origin_runs or ())]
            if not runs:
                runs = [(0, len(lines))]
            from banjax_tpu.obs import fleet

            idx = fleet.get_origin_index()
            pos = 0
            for tid, count in runs:
                for ln in lines[pos:pos + count]:
                    idx.note(ip_of_line(ln), origin_node, tid)
                if tid:
                    spans.append(trace.begin(
                        "fabric.remote-drain", tid,
                        args={"origin_node": origin_node, "lines": count},
                    ))
                pos += count
        try:
            if self._local_kw:
                # 0.0 is the wire's "unset" stamp (monotonic time is
                # never 0 on a live sender) — don't charge the epoch
                t_read = float(origin_t_read) if origin_t_read else None
                self._local_submit(lines, t_read=t_read, hop="fabric")
            else:
                self._local_submit(lines)
        finally:
            for sp in spans:
                trace.end(sp)

    @staticmethod
    def _parse_json_origin(payload: dict):
        """(origin_node, runs, t_read) from a JSON T_LINES ``origin``
        key; empty/None triple when absent or malformed."""
        origin = payload.get("origin")
        if not isinstance(origin, dict):
            return "", (), None
        node = str(origin.get("node", ""))
        runs = []
        try:
            for t, c in origin.get("runs") or ():
                runs.append((int(t), int(c)))
        except (TypeError, ValueError):
            runs = []
        t_read = origin.get("t_read")
        try:
            t_read = float(t_read) if t_read is not None else None
        except (TypeError, ValueError):
            t_read = None
        return node, tuple(runs), t_read

    def _h_lines(self, payload: dict):
        lines = payload.get("lines", [])
        self.stats.note_received(len(lines))
        piggy = (
            {"gossip": self.membership.digest()}
            if self.membership is not None else {}
        )
        if "seq" in payload:
            # a pipelined JSON-mode sender matches acks FIFO by seq
            piggy["seq"] = payload["seq"]
        if payload.get("route"):
            out = self.router.route(
                lines, replay=bool(payload.get("replay"))
            )
            if out["forwarded"]:
                # ack upstream == landed at the final owner (the replay
                # dedupe filter's soundness rests on this; see worker.py)
                self.router.flush(15.0)
            return wire.T_ACK, {"n": len(lines), **out, **piggy}
        node, runs, t_read = self._parse_json_origin(payload)
        self._drain_forwarded(lines, node, runs, t_read)
        self.stats.note_local(len(lines))
        return wire.T_ACK, {"n": len(lines), "local": len(lines), **piggy}

    def _h_lines_v2(self, fr):
        # binary data frame (wire.LinesV2): a peer's pipelined forward —
        # the sender computed ownership, the lines are ours
        lines = list(fr.lines)
        self.stats.note_received(len(lines))
        self._drain_forwarded(
            lines, fr.origin_node, fr.origin_runs,
            fr.origin_t_read if fr.origin_node else None,
        )
        self.stats.note_local(len(lines))
        ack = {"seq": fr.seq, "n": len(lines), "local": len(lines)}
        if self.membership is not None:
            ack["gossip"] = self.membership.digest()
        return wire.T_ACK, ack

    def _h_ping(self, payload: dict):
        return wire.T_PONG, {"node_id": self.node_id}

    def _h_peer_down(self, payload: dict):
        pid = str(payload.get("peer", ""))
        if self.membership is not None:
            self.membership.note_peer_down(pid)
        else:
            self.router.mark_dead(pid, reason="peer_down frame")
        return wire.T_ACK, {}

    def _h_peer_up(self, payload: dict):
        pid = str(payload.get("peer", ""))
        host, port = payload.get("host"), payload.get("port")
        if self.membership is not None:
            # exactly-once funnel: a duplicate notification (harness
            # handshake racing gossip discovery) is a no-op
            self.membership.note_peer_up(pid, host=host, port=port)
        else:
            self.router.mark_alive(pid, host=host, port=port)
        return wire.T_ACK, {}

    def _h_stats(self, payload: dict):
        out = {
            "node_id": self.node_id,
            "fabric": self.stats.peek(),
            "router": self.router.describe(),
        }
        if self.membership is not None:
            out["membership"] = self.membership.describe()
        if payload.get("metrics") and self._metrics_text_fn is not None:
            # federated scrape pull (obs/fleet.py FleetScraper): the
            # peer's FULL exposition rides the stats reply — one frame,
            # no second HTTP surface to reach into the fleet
            try:
                out["metrics_text"] = self._metrics_text_fn()
            except Exception as e:  # noqa: BLE001 — a render bug must not kill the link
                out["metrics_error"] = str(e)
        return wire.T_STATS_R, out

    def _h_explain(self, payload: dict):
        # cross-shard /decisions/explain: the shard that OWNS the IP
        # answers from its local ledger; the asking node tags the
        # response with our id (httpapi/server.py proxy branch)
        ip = str(payload.get("ip", ""))
        if self._explain_fn is None:
            raise ValueError("explain unavailable on this node")
        out = dict(self._explain_fn(ip) or {})
        out["node_id"] = self.node_id
        return wire.T_EXPLAIN_R, out

    def _h_flightrec(self, payload: dict):
        # a peer's incident capture fan-out: answer with THIS node's
        # snapshot files (never re-fan-out — the origin node owns the
        # incident; a capture storm cannot echo)
        from banjax_tpu.obs import fleet

        return wire.T_FLIGHTREC_R, {
            "node_id": self.node_id,
            "incident": str(payload.get("incident", "")),
            "files": fleet.local_capture_files(
                metrics_text_fn=self._metrics_text_fn,
                fabric_fn=self.describe,
            ),
        }

    # ---- fleet observability seams (obs/fleet.py) ----

    def fleet_pull_peers(self) -> Dict[str, Callable[[], str]]:
        """{node_id: pull} over every ALIVE remote member for the
        federated scrape — pull() raises on an unreachable/mute peer."""
        out: Dict[str, Callable[[], str]] = {}
        for pid, client in sorted(self.router.alive_peers().items()):
            def pull(c=client) -> str:
                rtype, rpayload = c.request(wire.T_STATS, {"metrics": True})
                text = rpayload.get("metrics_text")
                if rtype != wire.T_STATS_R or not isinstance(text, str):
                    raise OSError(
                        rpayload.get("metrics_error", "no metrics in reply")
                    )
                return text
            out[pid] = pull
        return out

    def fleet_capture_peers(
        self,
    ) -> Dict[str, Callable[[str], Dict[str, str]]]:
        """{node_id: capture} for obs.fleet.capture_fleet — capture()
        performs the T_FLIGHTREC exchange and returns the peer's file
        map for the bundle's peers/<node_id>/ tree."""
        out: Dict[str, Callable[[str], Dict[str, str]]] = {}
        for pid, client in sorted(self.router.alive_peers().items()):
            def cap(incident_id: str, c=client) -> Dict[str, str]:
                rtype, rpayload = c.request(
                    wire.T_FLIGHTREC,
                    {"incident": incident_id, "from": self.node_id},
                )
                files = rpayload.get("files")
                if rtype != wire.T_FLIGHTREC_R or not isinstance(files, dict):
                    raise OSError("no capture files in reply")
                return files
            out[pid] = cap
        return out

    def explain_remote(self, owner: str, ip: str) -> Dict[str, Any]:
        """One cross-shard explain exchange (httpapi proxy branch);
        raises on an unreachable owner."""
        client = self.router.alive_peers().get(owner)
        if client is None:
            raise PeerUnavailable(f"owner {owner} has no alive client")
        rtype, rpayload = client.request(wire.T_EXPLAIN, {"ip": ip})
        if rtype != wire.T_EXPLAIN_R:
            raise OSError(f"unexpected explain reply type {rtype}")
        return rpayload
