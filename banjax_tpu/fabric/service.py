"""Production assembly of the decision fabric for cli.BanjaxApp.

One FabricService per process, built only when `fabric_enabled`.  It
owns the four fabric pieces and exposes exactly the seams the app
needs:

  * ``submit(lines)`` — the tailer's consume path: lines this shard
    owns go down the local pipeline, everything else rides a peer
    socket to its owner (router.py);
  * ``wrap_banner(banner)`` — decisions fan out to the command topic
    (replication.py) on top of whatever the inner banner effects;
  * ``dispatch_raw(raw)`` — the KafkaReader drain hook: own-origin
    echoes and duplicate (origin, seq) pairs are suppressed, fresh
    peer decisions are applied (idempotently) to the dynamic lists;
  * ``describe()`` — the flight recorder's fabric.json and the
    /metrics peer table.

The wire server handles peer frames only (LINES / PING / PEER_DOWN /
PEER_UP / STATS); topology is static from `fabric_peers` — dynamic
membership changes arrive as PEER_DOWN/PEER_UP frames or are detected
locally by a failed send.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric.peer import PeerClient
from banjax_tpu.fabric.replication import (
    DecisionReplicator,
    FabricDeduper,
    ReplicatingBanner,
)
from banjax_tpu.fabric.router import FabricRouter
from banjax_tpu.fabric.stats import FabricStats


def _split_addr(addr: str) -> tuple:
    host, _, port = str(addr).rpartition(":")
    return host or "127.0.0.1", int(port)


class FabricService:
    def __init__(
        self,
        config: Any,
        local_submit: Callable[[Sequence[str]], int],
        apply_command: Callable[[Dict[str, Any]], None],
        health=None,
        transport: Any = None,
    ):
        if transport is None:
            from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

            transport = WireKafkaTransport()
        self.node_id = config.fabric_node_id
        self.stats = FabricStats()
        peers_cfg = dict(config.fabric_peers or {})
        node_ids = sorted(peers_cfg) if peers_cfg else [self.node_id]
        ring = ConsistentHashRing(node_ids, vnodes=config.fabric_vnodes)
        clients: Dict[str, Optional[PeerClient]] = {}
        for pid in node_ids:
            if pid == self.node_id:
                clients[pid] = None
                continue
            phost, pport = _split_addr(peers_cfg[pid])
            clients[pid] = PeerClient(
                pid, phost, pport,
                send_timeout_ms=config.fabric_send_timeout_ms,
            )
        self.replicator = DecisionReplicator(
            self.node_id, transport, config.kafka_command_topic,
            stats=self.stats, config=config, local_apply=apply_command,
        )
        self.deduper = FabricDeduper(
            self.node_id, apply_command, stats=self.stats
        )
        self.router = FabricRouter(
            self.node_id, ring, clients, local_submit,
            stats=self.stats, health=health,
            takeover_grace_ms=config.fabric_takeover_grace_ms,
        )
        lhost, lport = _split_addr(config.fabric_listen)
        self.node = FabricNode(lhost, lport, handlers={
            wire.T_LINES: self._h_lines,
            wire.T_PING: self._h_ping,
            wire.T_PEER_DOWN: self._h_peer_down,
            wire.T_PEER_UP: self._h_peer_up,
            wire.T_STATS: self._h_stats,
        })
        self._local_submit = local_submit

    # ---- lifecycle ----

    def start(self) -> "FabricService":
        self.node.start()
        return self

    def stop(self) -> None:
        self.node.stop()
        for client in self.router.peers.values():
            if client is not None:
                client.close()

    # ---- app seams ----

    def submit(self, lines: Sequence[str]) -> Dict[str, int]:
        """The tailer's consume path: route every line to its owner."""
        return self.router.route(lines)

    def wrap_banner(self, banner: Any) -> ReplicatingBanner:
        return ReplicatingBanner(banner, self.replicator)

    def dispatch_raw(self, raw: Any) -> None:
        """KafkaReader drain hook (replaces the default dispatch)."""
        self.deduper.dispatch(raw)

    def describe(self) -> Dict[str, object]:
        out: Dict[str, object] = {"enabled": True}
        out.update(self.router.describe())
        out["stats"] = self.stats.peek()
        return out

    # ---- wire handlers (peer side) ----

    def _h_lines(self, payload: dict):
        lines = payload.get("lines", [])
        self.stats.note_received(len(lines))
        if payload.get("route"):
            out = self.router.route(lines)
            return wire.T_ACK, {"n": len(lines), **out}
        self._local_submit(lines)
        self.stats.note_local(len(lines))
        return wire.T_ACK, {"n": len(lines), "local": len(lines)}

    def _h_ping(self, payload: dict):
        return wire.T_PONG, {"node_id": self.node_id}

    def _h_peer_down(self, payload: dict):
        self.router.mark_dead(
            str(payload.get("peer", "")), reason="peer_down frame"
        )
        return wire.T_ACK, {}

    def _h_peer_up(self, payload: dict):
        self.router.mark_alive(
            str(payload.get("peer", "")),
            host=payload.get("host"), port=payload.get("port"),
        )
        return wire.T_ACK, {}

    def _h_stats(self, payload: dict):
        return wire.T_STATS_R, {
            "node_id": self.node_id,
            "fabric": self.stats.peek(),
            "router": self.router.describe(),
        }
