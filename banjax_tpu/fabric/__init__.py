"""Keyspace-sharded multi-host decision fabric.

N banjax processes split the IP keyspace by consistent hash
(`hashring`), each running the full single-process engine for its
range.  Lines a process does not own travel to the owning shard over a
length-prefixed socket protocol (`wire`, `peer`, `node`); resulting
expiring Decisions replicate to every peer through the existing Kafka
command path (`replication`) so any shard can answer for any IP.

Failover is the point: a peer that stops answering (send timeout,
breaker trip, health probe) has its hash range taken over by its ring
successors (`router`), which re-derive the moved range's window state
from the replayed line journal plus the replicated decisions already
in their dynamic lists.  In-flight lines for the moving range are
drained or counted shed — never silently lost: the PR 2 accounting
contract (admitted == processed + shed) holds fabric-wide, summed
across processes (`stats`).

`worker` is the per-shard process entry; `harness` is the
`dryrun_fabric` driver that proves recall 1.0 against the scenario
oracle with a shard SIGKILLed mid-flood.
"""

from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable
from banjax_tpu.fabric.replication import (
    DecisionReplicator,
    FabricDeduper,
    ReplicatingBanner,
)
from banjax_tpu.fabric.router import FabricRouter
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric import wire

__all__ = [
    "ConsistentHashRing",
    "DecisionReplicator",
    "FabricDeduper",
    "FabricNode",
    "FabricRouter",
    "FabricStats",
    "PeerClient",
    "PeerUnavailable",
    "ReplicatingBanner",
    "wire",
]
