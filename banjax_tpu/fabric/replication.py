"""Decision replication over the existing Kafka command path.

Every ban/challenge a shard emits is ALSO produced to the Kafka
command topic as the reference's own command shape (`block_ip` /
`challenge_ip`, ingest/kafka_io.handle_command) tagged with a
`fabric_origin` + `fabric_seq` pair.  Every shard consumes the topic,
so any shard can answer for any IP, and a takeover successor
warm-starts from decisions already in its dynamic lists.

Idempotency lives in two layers: `FabricDeduper` drops a shard's own
commands and already-seen (origin, seq) pairs before dispatch, and
DynamicDecisionLists.update() is monotonic-severity, so a duplicate
that slips past the deduper (restart, bounded seen-set eviction) is a
no-op insert — duplicate decision inserts are suppressed or
idempotent, never double-applied.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from banjax_tpu.decisions.model import Decision
from banjax_tpu.fabric.stats import FabricStats


class DecisionReplicator:
    """Produces decision commands to the command topic.  The transport
    is the same duck type KafkaReader/Writer use (`send(config, topic,
    value)`), so the in-memory transport serves unit tests and the wire
    transport serves real brokers."""

    def __init__(
        self,
        origin: str,
        transport: Any,
        topic: str,
        stats: Optional[FabricStats] = None,
        config: Any = None,
        local_apply: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self.origin = origin
        self.transport = transport
        self.topic = topic
        self.stats = stats or FabricStats()
        self.config = config
        # the origin applies its own decision directly (its kafka echo
        # is suppressed by the deduper) — a shard's dynamic lists must
        # hold its OWN bans even when the broker is down
        self.local_apply = local_apply
        self._lock = threading.Lock()
        self._seq = 0

    def configure(self, config: Any) -> None:
        self.config = config

    def publish(self, ip: str, decision: Decision, domain: str) -> None:
        name = (
            "challenge_ip" if decision == Decision.CHALLENGE else "block_ip"
        )
        with self._lock:
            self._seq += 1
            seq = self._seq
        cmd_dict = {
            "Name": name,
            "Value": ip,
            "host": domain or "",
            "fabric_origin": self.origin,
            "fabric_seq": seq,
        }
        if self.local_apply is not None:
            self.local_apply(dict(cmd_dict))
        cmd = json.dumps(cmd_dict).encode()
        for attempt in (0, 1):
            try:
                self.transport.send(self.config, self.topic, cmd)
                self.stats.note_replicated()
                return
            except OSError:
                self.stats.note_replication_error()
                if attempt:
                    return  # counted, dropped: local decision still holds


class ReplicatingBanner:
    """Wraps any banner; decisions pass through to the inner banner and
    fan out to the fabric via the replicator."""

    def __init__(self, inner: Any, replicator: DecisionReplicator):
        self.inner = inner
        self.replicator = replicator

    def ban_or_challenge_ip(self, config, ip, decision, domain) -> None:
        self.inner.ban_or_challenge_ip(config, ip, decision, domain)
        self.replicator.publish(ip, decision, domain)

    def __getattr__(self, name: str) -> Any:
        # everything else (regex-ban logging, ipset ops) is host-local
        return getattr(self.inner, name)


class FabricDeduper:
    """Bounded (origin, seq) seen-set in front of command dispatch.

    `dispatch(raw)` is shaped for KafkaReader.dispatch_raw: fabric-
    tagged commands from this shard's own origin or already seen are
    suppressed (counted); fresh ones go to the wrapped handler.
    Untagged commands (operator curl, Baskerville) pass straight
    through."""

    def __init__(
        self,
        origin: str,
        apply_command: Callable[[Dict[str, Any]], None],
        stats: Optional[FabricStats] = None,
        max_seen: int = 65536,
    ):
        self.origin = origin
        self.apply_command = apply_command
        self.stats = stats or FabricStats()
        self.max_seen = int(max_seen)
        self._lock = threading.Lock()
        self._seen: "OrderedDict[tuple, bool]" = OrderedDict()

    def dispatch(self, raw: Any) -> None:
        try:
            cmd = json.loads(raw if isinstance(raw, str) else raw.decode())
        except (ValueError, AttributeError):
            return
        if not isinstance(cmd, dict):
            return
        origin = cmd.get("fabric_origin")
        if origin is not None:
            key = (origin, cmd.get("fabric_seq"))
            with self._lock:
                dup = origin == self.origin or key in self._seen
                if not dup:
                    self._seen[key] = True
                    while len(self._seen) > self.max_seen:
                        self._seen.popitem(last=False)
            if dup:
                self.stats.note_duplicate_suppressed()
                return
            self.apply_command(cmd)
            self.stats.note_replicated_applied()
            return
        self.apply_command(cmd)
