"""dryrun_fabric driver: N real worker processes, real sockets, one box.

Mirrors `__graft_entry__.dryrun_multichip` for the decision fabric: the
driver spawns N `banjax_tpu.fabric.worker` processes (each a FULL
engine — TPU matcher with device windows, pipeline scheduler, tiered
state — on the CPU backend), wires them into a fabric over real TCP
sockets plus an in-process Kafka broker for decision replication, and
feeds a PR 9 scenario shape round-robin at the workers.  Each worker
routes non-owned lines to the owning shard itself, so worker→worker
socket traffic is real, not simulated.

The chaos move is a mid-flood SIGKILL of one worker.  Detection is a
failed send; recovery is deterministic journal replay from BOTH sides:

  * the driver broadcasts T_PEER_DOWN so every survivor replays its
    own forward-journal for the victim (lines survivors had routed to
    it), and
  * the driver replays its per-worker chunk journal (chunks it had fed
    the victim directly).

The two journals are disjoint line sets whose union is every line the
victim ever held, so the consistent-hash successors re-derive every
ban the victim would have emitted: recall vs the oracle is 1.0, by
construction, with a shard killed mid-flood.  Double-processing can
only ADD bans (precision is reported, recall is gated).

Accounting is the fabric-wide ledger: every driver chunk is acked by a
live worker (fed == acked), every worker satisfies
admitted == processed + shed + drain_errors (pipeline) and
local + forwarded + shed == received + replayed (fabric) — admitted
work is processed or counted shed, never silently lost.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# driver→worker requests ride the same PeerClient as worker→worker
# forwards; the driver's timeout must cover a synchronous takeover
# (grace + full journal replay) behind a T_PEER_DOWN ack
_DRIVER_TIMEOUT_MS = 120_000.0


def _fake_broker():
    try:
        from tests.fake_kafka_broker import FakeKafkaBroker
    except ImportError:  # pragma: no cover — installed-package layout
        sys.path.insert(0, _REPO)
        from tests.fake_kafka_broker import FakeKafkaBroker
    return FakeKafkaBroker()


class _Worker:
    """One spawned shard process + the driver's client to it."""

    def __init__(self, wid: str, proc: subprocess.Popen):
        self.wid = wid
        self.proc = proc
        self.port: Optional[int] = None
        self.client: Optional[PeerClient] = None
        self.ready_error: Optional[str] = None

    def read_ready(self, timeout_s: float) -> None:
        """Block until the worker prints its READY line (post-warmup,
        post-kafka-attach) — in a thread so N workers warm in parallel."""
        result: Dict[str, object] = {}

        def _read():
            for raw in iter(self.proc.stdout.readline, b""):
                try:
                    msg = json.loads(raw)
                except ValueError:
                    continue  # stray non-JSON noise on stdout
                if isinstance(msg, dict) and "ready" in msg:
                    result.update(msg)
                    return

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(timeout_s)
        if not result.get("ready"):
            self.ready_error = str(
                result.get("error") or f"no READY within {timeout_s}s"
            )
            return
        self.port = int(result["port"])
        self.client = PeerClient(
            self.wid, "127.0.0.1", self.port,
            send_timeout_ms=_DRIVER_TIMEOUT_MS, max_attempts=2,
        )

    def request(self, ftype: int, payload: dict) -> dict:
        assert self.client is not None, f"{self.wid} has no client"
        _rtype, rpayload = self.client.request(ftype, payload)
        return rpayload

    def kill(self) -> None:
        self.proc.kill()

    def shutdown(self) -> None:
        try:
            if self.client is not None:
                self.client.request(wire.T_SHUTDOWN, {})
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5)
        if self.client is not None:
            self.client.close()


def _spawn(wid: str, broker_port: int, stderr_path: Optional[str]) -> _Worker:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if stderr_path:
        os.makedirs(os.path.dirname(stderr_path), exist_ok=True)
        stderr = open(stderr_path, "ab")
    else:
        stderr = subprocess.DEVNULL
    proc = subprocess.Popen(
        [sys.executable, "-m", "banjax_tpu.fabric.worker",
         "--node-id", wid, "--broker-port", str(broker_port)],
        stdout=subprocess.PIPE, stderr=stderr, cwd=_REPO, env=env,
    )
    return _Worker(wid, proc)


class FabricDryrun:
    """One dryrun episode.  `run()` returns the report dict; every
    invariant it computes is in report["invariants"] (all must hold)."""

    def __init__(
        self,
        n_workers: int = 2,
        shape: str = "flash_crowd",
        seed: int = 20260804,
        scale: float = 1.0,
        kill: bool = True,
        rejoin: bool = False,
        kill_frac: float = 0.45,
        ready_timeout_s: float = 420.0,
        settle_timeout_s: float = 120.0,
        log_dir: Optional[str] = None,
    ):
        if kill and n_workers < 2:
            raise ValueError("kill needs n_workers >= 2")
        self.n_workers = n_workers
        self.shape = shape
        self.seed = seed
        self.scale = scale
        self.kill = kill
        self.rejoin = rejoin
        self.kill_frac = kill_frac
        self.ready_timeout_s = ready_timeout_s
        self.settle_timeout_s = settle_timeout_s
        self.log_dir = log_dir
        self.workers: Dict[str, _Worker] = {}
        self.alive: List[str] = []
        self.victim: Optional[str] = None
        # driver-side journal: every chunk acked per worker, so the
        # driver can replay a dead worker's direct feed
        self._journal: Dict[str, List[List[str]]] = {}
        self._rr = 0
        self.fed_lines = 0
        self.acked_lines = 0
        self.takeover: Dict[str, object] = {}

    # ---- plumbing ----

    def _stats(self, wid: str) -> dict:
        return self.workers[wid].request(wire.T_STATS, {})

    def _broadcast(self, ftype: int, payload: dict,
                   only: Optional[List[str]] = None) -> None:
        for wid in list(only if only is not None else self.alive):
            self.workers[wid].request(ftype, payload)

    def _send_chunk(self, lines: List[str], count_ack: bool = True) -> str:
        """Round-robin one chunk at a live worker; a dead target turns
        into detection + takeover + reroute, never a lost chunk.
        Replayed chunks pass count_ack=False: the victim already acked
        them once, so the fed==acked ledger counts each chunk once."""
        while True:
            if not self.alive:
                raise RuntimeError("no live workers left")
            target = self.alive[self._rr % len(self.alive)]
            self._rr += 1
            try:
                self.workers[target].request(
                    wire.T_LINES, {"lines": lines, "route": True}
                )
            except (PeerUnavailable, OSError):
                self._on_death(target)
                continue
            self._journal[target].append(lines)
            if count_ack:
                self.acked_lines += len(lines)
            return target

    def _on_death(self, wid: str) -> None:
        """A send to `wid` failed: declare it dead fabric-wide and
        replay the driver's direct feed to the survivors."""
        if wid not in self.alive:
            return
        self.alive.remove(wid)
        t0 = time.perf_counter()
        pre = {w: self._stats(w) for w in self.alive}
        # survivors replay their forward-journals inside this ack
        self._broadcast(wire.T_PEER_DOWN, {"peer": wid})
        replayed = 0
        for chunk in self._journal[wid]:
            self._send_chunk(chunk, count_ack=False)
            replayed += len(chunk)
        self._journal[wid] = []
        post = {w: self._stats(w) for w in self.alive}

        def _shed(snap: dict) -> int:
            return int(snap["sched"]["PipelineShedLines"]) + int(
                snap["fabric"]["FabricShedLines"]
            )

        shed_in_window = sum(
            _shed(post[w]) - _shed(pre[w]) for w in post
        )
        survivor_replayed = sum(
            int(post[w]["fabric"]["FabricReplayedLines"])
            - int(pre[w]["fabric"]["FabricReplayedLines"])
            for w in post
        )
        fed_in_window = replayed + survivor_replayed
        self.takeover = {
            "victim": wid,
            "detect_after_lines": self.fed_lines,
            "driver_replayed_lines": replayed,
            "survivor_replayed_lines": survivor_replayed,
            "shed_in_window": shed_in_window,
            "fed_in_window": fed_in_window,
            "shed_ratio_in_window": round(
                shed_in_window / max(1, fed_in_window), 6
            ),
            "window_s": round(time.perf_counter() - t0, 3),
        }

    def _settle(self, tagged_floor: Optional[int] = None,
                skip_kafka_check: Optional[List[str]] = None) -> None:
        """FLUSH everyone, then poll STATS until counters quiesce (and
        each long-lived worker has consumed every fabric-tagged command
        the broker holds — suppressed + applied covers the topic)."""
        self._broadcast(wire.T_FLUSH, {"timeout": 600})
        deadline = time.monotonic() + self.settle_timeout_s
        stable, prev = 0, None
        skip = set(skip_kafka_check or ())
        while stable < 3:
            if time.monotonic() > deadline:
                raise RuntimeError(f"fabric settle timed out: {prev}")
            snaps = {w: self._stats(w) for w in self.alive}
            kafka_ok = True
            if tagged_floor is not None:
                tagged = self._tagged_commands()
                for w, s in snaps.items():
                    if w in skip:
                        continue
                    seen = int(
                        s["fabric"]["FabricDuplicatesSuppressed"]
                    ) + int(s["fabric"]["FabricReplicatedApplied"])
                    if seen < tagged:
                        kafka_ok = False
            key = tuple(
                (w,
                 s["sched"]["PipelineAdmittedLines"],
                 s["sched"]["PipelineProcessedLines"],
                 s["sched"]["PipelineShedLines"],
                 len(s["bans"]),
                 s["fabric"]["FabricReplicatedApplied"],
                 s["fabric"]["FabricDuplicatesSuppressed"])
                for w, s in sorted(snaps.items())
            )
            if key == prev and kafka_ok:
                stable += 1
            else:
                stable = 0
            prev = key
            time.sleep(0.2)

    def _tagged_commands(self) -> int:
        log = self.broker.logs.get(("fabric.commands", 0), [])
        return sum(
            1 for m in log
            if b"fabric_origin" in m and b"fabric_ping" not in m
        )

    # ---- the run ----

    def run(self) -> dict:
        from banjax_tpu.config.schema import config_from_yaml_text
        from banjax_tpu.scenarios import oracle as oracle_mod
        from banjax_tpu.scenarios.shapes import LineChunk, generate

        sc = generate(self.shape, self.seed, self.scale)
        chunks = [
            list(ev.lines) for ev in sc.events if isinstance(ev, LineChunk)
        ]
        n_lines = sum(len(c) for c in chunks)

        self.broker = _fake_broker().start()
        wids = [f"w{i}" for i in range(self.n_workers)]
        try:
            return self._run_inner(sc, chunks, n_lines, wids,
                                   config_from_yaml_text, oracle_mod)
        finally:
            for w in self.workers.values():
                w.shutdown()
            self.broker.stop()

    def _hello_payload(self) -> dict:
        return {
            "peers": {
                w.wid: ["127.0.0.1", w.port]
                for w in self.workers.values() if w.port is not None
            },
            "vnodes": 64,
            "send_timeout_ms": 2000.0,
            "grace_ms": 200.0,
        }

    def _spawn_and_hello(self, wids: List[str]) -> None:
        for wid in wids:
            err_path = (
                os.path.join(self.log_dir, f"{wid}.err")
                if self.log_dir else None
            )
            self.workers[wid] = _spawn(wid, self.broker.port, err_path)
        threads = [
            threading.Thread(
                target=w.read_ready, args=(self.ready_timeout_s,),
                daemon=True,
            )
            for w in self.workers.values() if w.wid in wids
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.ready_timeout_s + 5)
        bad = [
            f"{w.wid}: {w.ready_error}"
            for w in self.workers.values() if w.wid in wids and w.port is None
        ]
        if bad:
            raise RuntimeError(f"workers failed to start: {bad}")

    def _run_inner(self, sc, chunks, n_lines, wids,
                   config_from_yaml_text, oracle_mod) -> dict:
        self._spawn_and_hello(wids)
        self.alive = list(wids)
        self._journal = {w: [] for w in wids}
        hello = self._hello_payload()
        self._broadcast(wire.T_HELLO, hello)
        base = {w: self._stats(w) for w in self.alive}

        kill_at = (
            int(self.kill_frac * len(chunks)) if self.kill else -1
        )
        self.victim = wids[-1] if self.kill else None

        t_feed = time.perf_counter()
        for i, chunk in enumerate(chunks):
            if i == kill_at and self.victim in self.alive:
                # SIGKILL mid-flood: no goodbye, no flush — the next
                # send to it is the detection
                self.workers[self.victim].kill()
            self._send_chunk(chunk)
            self.fed_lines += len(chunk)
        # a victim killed on the very last chunks may never be hit by
        # the round-robin again: force detection so takeover happens
        if self.victim is not None and self.victim in self.alive:
            try:
                self.workers[self.victim].request(wire.T_PING, {})
            except (PeerUnavailable, OSError):
                self._on_death(self.victim)
        self._settle(tagged_floor=0)
        # second settle pass with the final tagged count: every
        # survivor must have consumed the whole replicated topic
        self._settle(tagged_floor=self._tagged_commands())
        feed_s = max(1e-9, time.perf_counter() - t_feed)

        final = {w: self._stats(w) for w in self.alive}
        report = self._report(
            sc, n_lines, feed_s, base, final,
            config_from_yaml_text, oracle_mod,
        )
        if self.rejoin and self.victim is not None:
            report["rejoin"] = self._rejoin_phase()
        return report

    # ---- rejoin / handback ----

    def _rejoin_phase(self) -> dict:
        from banjax_tpu.scenarios.shapes import LineChunk, generate

        victim = self.victim
        survivor = self.alive[0]
        # warm-start state for the newcomer: a survivor's decision
        # snapshot, applied idempotently over the wire
        snap = self.workers[survivor].request(wire.T_SNAPSHOT, {})
        self._spawn_and_hello([victim])
        newcomer = self.workers[victim]
        newcomer.request(wire.T_HELLO, self._hello_payload())
        sync_ack = newcomer.request(
            wire.T_SYNC, {"decisions": snap["decisions"]}
        )
        # handback is pure membership: ring recomputation, NO replay
        self._broadcast(
            wire.T_PEER_UP,
            {"peer": victim, "host": "127.0.0.1", "port": newcomer.port},
        )
        self.alive.append(victim)

        base = {w: self._stats(w) for w in self.alive}
        wave = generate(self.shape, self.seed + 1,
                        max(0.25, self.scale * 0.25))
        wave_chunks = [
            list(ev.lines) for ev in wave.events
            if isinstance(ev, LineChunk)
        ]
        wave_lines = sum(len(c) for c in wave_chunks)
        for chunk in wave_chunks:
            self._send_chunk(chunk)
            self.fed_lines += len(chunk)
        # the rejoined worker's reader attached at the topic tail; the
        # whole-topic floor only applies to the original survivors
        self._settle(tagged_floor=self._tagged_commands(),
                     skip_kafka_check=[victim])
        final = {w: self._stats(w) for w in self.alive}

        def _local(w: str) -> int:
            return int(final[w]["fabric"]["FabricLocalLines"]) - int(
                base[w]["fabric"]["FabricLocalLines"]
            )

        locals_sum = sum(_local(w) for w in self.alive)
        return {
            "snapshot_decisions": len(snap["decisions"]),
            "sync_applied": int(sync_ack.get("applied", 0)),
            "wave_lines": wave_lines,
            "wave_locals_sum": locals_sum,
            "newcomer_local_lines": _local(victim),
            "invariants": {
                # every handed-back line processed EXACTLY once
                # fabric-wide — no double-processing on rejoin
                "wave_exactly_once": locals_sum == wave_lines,
                "newcomer_took_lines": _local(victim) > 0,
                "sync_idempotent_applied":
                    int(sync_ack.get("applied", 0))
                    == len(snap["decisions"]),
            },
        }

    # ---- reporting ----

    def _report(self, sc, n_lines, feed_s, base, final,
                config_from_yaml_text, oracle_mod) -> dict:
        engine_bans: List[Tuple[str, str]] = []
        for w in self.alive:
            engine_bans.extend(
                (ip, rule) for ip, rule in final[w]["bans"]
            )
        cfg = config_from_yaml_text(sc.rules_yaml)
        oracle_bans = oracle_mod.expected_bans(sc, cfg)
        precision, recall, tp = oracle_mod.precision_recall(
            engine_bans, oracle_bans
        )

        per_worker = {}
        invariants: Dict[str, bool] = {}
        dup_total = 0
        for w in self.alive:
            sched_d = {
                k: int(final[w]["sched"][k]) - int(base[w]["sched"][k])
                for k in ("PipelineAdmittedLines", "PipelineProcessedLines",
                          "PipelineShedLines", "PipelineDrainErrorLines")
            }
            fab = {k: int(v) for k, v in final[w]["fabric"].items()}
            dup_total += fab["FabricDuplicatesSuppressed"]
            per_worker[w] = {"sched_delta": sched_d, "fabric": fab,
                             "router": final[w]["router"]}
            invariants[f"{w}_pipeline_accounting"] = (
                sched_d["PipelineAdmittedLines"]
                == sched_d["PipelineProcessedLines"]
                + sched_d["PipelineShedLines"]
                + sched_d["PipelineDrainErrorLines"]
            )
            # fabric ledger: every line that ENTERED this worker
            # (received over the wire, or re-materialized from its
            # journal at takeover) left as exactly one of
            # local/forwarded/shed
            invariants[f"{w}_fabric_ledger"] = (
                fab["FabricLocalLines"] + fab["FabricForwardedLines"]
                + fab["FabricShedLines"]
                == fab["FabricReceivedLines"] + fab["FabricReplayedLines"]
            )
        invariants["driver_fed_equals_acked"] = (
            self.fed_lines == self.acked_lines
        )
        invariants["recall_one"] = recall == 1.0
        if self.kill:
            invariants["takeover_happened"] = bool(self.takeover)
            invariants["survivors_took_over"] = all(
                per_worker[w]["fabric"]["FabricTakeovers"] >= 1
                for w in self.alive
            )
            invariants["victim_in_last_takeover"] = all(
                ((final[w]["router"] or {}).get("last_takeover") or {})
                .get("peer") == self.victim
                for w in self.alive
            )
        if self.n_workers > 1 and engine_bans:
            # every replicated decision echoes back to its origin and
            # is suppressed there: the idempotency witness
            invariants["duplicates_suppressed"] = dup_total > 0

        return {
            "harness": "dryrun_fabric",
            "n_workers": self.n_workers,
            "shape": self.shape,
            "seed": self.seed,
            "scale": self.scale,
            "killed": self.victim,
            "n_lines": n_lines,
            "fed_lines": self.fed_lines,
            "acked_lines": self.acked_lines,
            "feed_s": round(feed_s, 3),
            "lines_per_sec": round(n_lines / feed_s, 1),
            "engine_bans": len(engine_bans),
            "oracle_bans": len(oracle_bans),
            "true_positives": tp,
            "precision": round(precision, 6),
            "recall": round(recall, 6),
            "duplicates_suppressed": dup_total,
            "takeover": self.takeover,
            "per_worker": per_worker,
            "invariants": invariants,
        }


def run_fabric(**kwargs) -> dict:
    """Convenience wrapper: one episode, report dict back."""
    return FabricDryrun(**kwargs).run()
