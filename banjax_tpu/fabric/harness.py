"""dryrun_fabric driver: N real worker processes, real sockets, one box.

Mirrors `__graft_entry__.dryrun_multichip` for the decision fabric: the
driver spawns N `banjax_tpu.fabric.worker` processes (each a FULL
engine — TPU matcher with device windows, pipeline scheduler, tiered
state — on the CPU backend), wires them into a fabric over real TCP
sockets plus an in-process Kafka broker for decision replication, and
feeds a PR 9 scenario shape round-robin at the workers.  Each worker
routes non-owned lines to the owning shard itself, so worker→worker
socket traffic is real, not simulated.

The chaos move is a mid-flood SIGKILL of one worker.  Detection is a
failed send; recovery is deterministic journal replay from BOTH sides:

  * the driver broadcasts T_PEER_DOWN so every survivor replays its
    own forward-journal for the victim (lines survivors had routed to
    it), and
  * the driver replays its per-worker chunk journal (chunks it had fed
    the victim directly).

The two journals are disjoint line sets whose union is every line the
victim ever held, so the consistent-hash successors re-derive every
ban the victim would have emitted: recall vs the oracle is 1.0, by
construction, with a shard killed mid-flood.  Double-processing can
only ADD bans (precision is reported, recall is gated).

Accounting is the fabric-wide ledger: every driver chunk is acked by a
live worker (fed == acked), every worker satisfies
admitted == processed + shed + drain_errors (pipeline) and
local + forwarded + shed + replay_skipped == received + replayed
(fabric) — admitted work is processed or counted shed, never silently
lost.  Driver-replayed chunks carry `replay: true` so the receiving
router skips lines whose pre-death owner is still alive (they were
processed once already — re-routing them double-counts rate-limit hits
and mints duplicate bans, the banked n2 precision bug).

The `transport` knob picks the worker-to-worker data path: "json" is
the PR 11 synchronous per-group path (the differential oracle), "v2"
the pipelined binary frame path over TCP, "shm" the same frames over
co-located shared-memory rings.  `run_forward_path` is the transport
micro-benchmark: two shards, every line owned by the remote peer, so
the measured rate is pure forwarding.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# driver→worker requests ride the same PeerClient as worker→worker
# forwards; the driver's timeout must cover a synchronous takeover
# (grace + full journal replay) behind a T_PEER_DOWN ack
_DRIVER_TIMEOUT_MS = 120_000.0


def _fake_broker():
    try:
        from tests.fake_kafka_broker import FakeKafkaBroker
    except ImportError:  # pragma: no cover — installed-package layout
        sys.path.insert(0, _REPO)
        from tests.fake_kafka_broker import FakeKafkaBroker
    return FakeKafkaBroker()


class _Worker:
    """One spawned shard process + the driver's client to it."""

    def __init__(self, wid: str, proc: subprocess.Popen):
        self.wid = wid
        self.proc = proc
        self.port: Optional[int] = None
        self.client: Optional[PeerClient] = None
        self.ready_error: Optional[str] = None
        self.ready_info: Dict[str, object] = {}

    def read_ready(self, timeout_s: float) -> None:
        """Block until the worker prints its READY line (post-warmup,
        post-kafka-attach) — in a thread so N workers warm in parallel."""
        result: Dict[str, object] = {}

        def _read():
            for raw in iter(self.proc.stdout.readline, b""):
                try:
                    msg = json.loads(raw)
                except ValueError:
                    continue  # stray non-JSON noise on stdout
                if isinstance(msg, dict) and "ready" in msg:
                    result.update(msg)
                    return

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(timeout_s)
        if not result.get("ready"):
            self.ready_error = str(
                result.get("error") or f"no READY within {timeout_s}s"
            )
            return
        self.ready_info = dict(result)
        self.port = int(result["port"])
        self.client = PeerClient(
            self.wid, "127.0.0.1", self.port,
            send_timeout_ms=_DRIVER_TIMEOUT_MS, max_attempts=2,
        )

    def request(self, ftype: int, payload: dict) -> dict:
        assert self.client is not None, f"{self.wid} has no client"
        _rtype, rpayload = self.client.request(ftype, payload)
        return rpayload

    def kill(self) -> None:
        self.proc.kill()

    def shutdown(self) -> None:
        try:
            if self.client is not None:
                self.client.request(wire.T_SHUTDOWN, {})
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5)
        if self.client is not None:
            self.client.close()


def _spawn(wid: str, broker_port: int, stderr_path: Optional[str],
           extra_args: Tuple[str, ...] = ()) -> _Worker:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if stderr_path:
        os.makedirs(os.path.dirname(stderr_path), exist_ok=True)
        stderr = open(stderr_path, "ab")
    else:
        stderr = subprocess.DEVNULL
    proc = subprocess.Popen(
        [sys.executable, "-m", "banjax_tpu.fabric.worker",
         "--node-id", wid, "--broker-port", str(broker_port),
         *extra_args],
        stdout=subprocess.PIPE, stderr=stderr, cwd=_REPO, env=env,
    )
    return _Worker(wid, proc)


class FabricDryrun:
    """One dryrun episode.  `run()` returns the report dict; every
    invariant it computes is in report["invariants"] (all must hold)."""

    def __init__(
        self,
        n_workers: int = 2,
        shape: str = "flash_crowd",
        seed: int = 20260804,
        scale: float = 1.0,
        kill: bool = True,
        rejoin: bool = False,
        churn: bool = False,
        gossip_interval_ms: float = 250.0,
        suspect_timeout_ms: float = 1200.0,
        kill_frac: float = 0.45,
        ready_timeout_s: float = 420.0,
        settle_timeout_s: float = 120.0,
        log_dir: Optional[str] = None,
        transport: str = "v2",
        inflight_frames: int = 8,
        fleet_obs: bool = False,
    ):
        if transport not in ("json", "v2", "shm"):
            raise ValueError(f"unknown transport {transport!r}")
        self.schedule = None
        if churn:
            from banjax_tpu.scenarios.chaos import MembershipChurnSchedule

            kill, rejoin = True, False  # churn runs its own join phase
            self.schedule = MembershipChurnSchedule(seed)
            kill_frac = self.schedule.kill_frac
        if kill and n_workers < 2:
            raise ValueError("kill needs n_workers >= 2")
        self.n_workers = n_workers
        self.shape = shape
        self.seed = seed
        self.scale = scale
        self.kill = kill
        self.rejoin = rejoin
        self.churn = churn
        self.gossip_interval_ms = gossip_interval_ms
        self.suspect_timeout_ms = suspect_timeout_ms
        self.kill_frac = kill_frac
        self.transport = transport
        self.inflight_frames = inflight_frames
        # fleet observability drill arm: workers propagate origin trace
        # context on every forward and serve the T_EXPLAIN/T_FLIGHTREC/
        # T_STATS-metrics fleet surface — decisions must not change
        self.fleet_obs = fleet_obs
        self.ready_timeout_s = ready_timeout_s
        self.settle_timeout_s = settle_timeout_s
        self.log_dir = log_dir
        self.workers: Dict[str, _Worker] = {}
        self.alive: List[str] = []
        self.victim: Optional[str] = None
        # driver-side journal: every chunk acked per worker, so the
        # driver can replay a dead worker's direct feed
        self._journal: Dict[str, List[List[str]]] = {}
        self._rr = 0
        self.fed_lines = 0
        self.acked_lines = 0
        self.takeover: Dict[str, object] = {}
        # churn mode: per-survivor kill -> gossip-confirmed-dead seconds
        self.detection: Dict[str, float] = {}

    # ---- plumbing ----

    def _stats(self, wid: str) -> dict:
        return self.workers[wid].request(wire.T_STATS, {})

    def _broadcast(self, ftype: int, payload: dict,
                   only: Optional[List[str]] = None) -> None:
        for wid in list(only if only is not None else self.alive):
            self.workers[wid].request(ftype, payload)

    def _send_chunk(self, lines: List[str], count_ack: bool = True,
                    replay: bool = False) -> str:
        """Round-robin one chunk at a live worker; a dead target turns
        into detection + takeover + reroute, never a lost chunk.
        Replayed chunks pass count_ack=False (the victim already acked
        them once, so the fed==acked ledger counts each chunk once) and
        replay=True (the receiving router skips lines whose pre-death
        owner is still alive — the duplicate-ban dedupe)."""
        while True:
            if not self.alive:
                raise RuntimeError("no live workers left")
            target = self.alive[self._rr % len(self.alive)]
            self._rr += 1
            try:
                payload = {"lines": lines, "route": True}
                if replay:
                    payload["replay"] = True
                self.workers[target].request(wire.T_LINES, payload)
            except (PeerUnavailable, OSError):
                self._on_death(target)
                continue
            self._journal[target].append(lines)
            if count_ack:
                self.acked_lines += len(lines)
            return target

    def _on_death(self, wid: str) -> None:
        """A send to `wid` failed: declare it dead fabric-wide and
        replay the driver's direct feed to the survivors."""
        if wid not in self.alive:
            return
        self.alive.remove(wid)
        t0 = time.perf_counter()
        pre = {w: self._stats(w) for w in self.alive}
        # survivors schedule their forward-journal replays behind the
        # deadline-polled grace — the ack returns promptly, so wait for
        # the takeovers to actually complete before auditing the window
        self._broadcast(wire.T_PEER_DOWN, {"peer": wid})
        self._await_takeovers(wid)
        replayed = 0
        for chunk in self._journal[wid]:
            self._send_chunk(chunk, count_ack=False, replay=True)
            replayed += len(chunk)
        self._journal[wid] = []
        post = {w: self._stats(w) for w in self.alive}

        def _shed(snap: dict) -> int:
            return int(snap["sched"]["PipelineShedLines"]) + int(
                snap["fabric"]["FabricShedLines"]
            )

        shed_in_window = sum(
            _shed(post[w]) - _shed(pre[w]) for w in post
        )
        survivor_replayed = sum(
            int(post[w]["fabric"]["FabricReplayedLines"])
            - int(pre[w]["fabric"]["FabricReplayedLines"])
            for w in post
        )
        fed_in_window = replayed + survivor_replayed
        self.takeover = {
            "victim": wid,
            "detect_after_lines": self.fed_lines,
            "driver_replayed_lines": replayed,
            "survivor_replayed_lines": survivor_replayed,
            "shed_in_window": shed_in_window,
            "fed_in_window": fed_in_window,
            "shed_ratio_in_window": round(
                shed_in_window / max(1, fed_in_window), 6
            ),
            "window_s": round(time.perf_counter() - t0, 3),
        }

    def _await_takeovers(self, victim: str, timeout_s: float = 60.0) -> None:
        """Block until every live worker has removed `victim` from its
        alive set AND completed (not merely scheduled) any pending
        takeover — mark_dead no longer replays inline."""
        deadline = time.monotonic() + timeout_s
        while True:
            done = True
            for w in self.alive:
                r = self._stats(w).get("router") or {}
                if victim in (r.get("alive") or ()) or r.get(
                    "pending_takeovers"
                ):
                    done = False
                    break
            if done:
                return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"takeover of {victim} never completed on {w}"
                )
            time.sleep(0.05)

    def _settle(self, tagged_floor: Optional[int] = None,
                skip_kafka_check: Optional[List[str]] = None) -> None:
        """FLUSH everyone, then poll STATS until counters quiesce (and
        each long-lived worker has consumed every fabric-tagged command
        the broker holds — suppressed + applied covers the topic)."""
        self._broadcast(wire.T_FLUSH, {"timeout": 600})
        deadline = time.monotonic() + self.settle_timeout_s
        stable, prev = 0, None
        skip = set(skip_kafka_check or ())
        while stable < 3:
            if time.monotonic() > deadline:
                raise RuntimeError(f"fabric settle timed out: {prev}")
            snaps = {w: self._stats(w) for w in self.alive}
            kafka_ok = True
            if tagged_floor is not None:
                tagged = self._tagged_commands()
                for w, s in snaps.items():
                    if w in skip:
                        continue
                    seen = int(
                        s["fabric"]["FabricDuplicatesSuppressed"]
                    ) + int(s["fabric"]["FabricReplicatedApplied"])
                    if seen < tagged:
                        kafka_ok = False
            key = tuple(
                (w,
                 s["sched"]["PipelineAdmittedLines"],
                 s["sched"]["PipelineProcessedLines"],
                 s["sched"]["PipelineShedLines"],
                 len(s["bans"]),
                 s["fabric"]["FabricReplicatedApplied"],
                 s["fabric"]["FabricDuplicatesSuppressed"])
                for w, s in sorted(snaps.items())
            )
            if key == prev and kafka_ok:
                stable += 1
            else:
                stable = 0
            prev = key
            time.sleep(0.2)

    def _tagged_commands(self) -> int:
        log = self.broker.logs.get(("fabric.commands", 0), [])
        return sum(
            1 for m in log
            if b"fabric_origin" in m and b"fabric_ping" not in m
        )

    # ---- the run ----

    def run(self) -> dict:
        from banjax_tpu.config.schema import config_from_yaml_text
        from banjax_tpu.scenarios import oracle as oracle_mod
        from banjax_tpu.scenarios.shapes import LineChunk, generate

        sc = generate(self.shape, self.seed, self.scale)
        chunks = [
            list(ev.lines) for ev in sc.events if isinstance(ev, LineChunk)
        ]
        n_lines = sum(len(c) for c in chunks)

        self.broker = _fake_broker().start()
        wids = [f"w{i}" for i in range(self.n_workers)]
        try:
            return self._run_inner(sc, chunks, n_lines, wids,
                                   config_from_yaml_text, oracle_mod)
        finally:
            for w in self.workers.values():
                w.shutdown()
            self.broker.stop()

    def _hello_payload(self) -> dict:
        payload = {
            "peers": {
                w.wid: ["127.0.0.1", w.port]
                for w in self.workers.values() if w.port is not None
            },
            "vnodes": 64,
            "send_timeout_ms": 2000.0,
            "grace_ms": 200.0,
            # worker-to-worker data path ("json" = inflight 0, the
            # synchronous PR 11 oracle)
            "inflight_frames": (
                0 if self.transport == "json" else self.inflight_frames
            ),
            "wire_v2": self.transport != "json",
            "shm": self.transport == "shm",
            "trace_propagation": self.fleet_obs,
        }
        if self.churn:
            payload.update({
                "gossip_interval_ms": self.gossip_interval_ms,
                "suspect_timeout_ms": self.suspect_timeout_ms,
                "indirect_probes": 2,
            })
        return payload

    def _spawn_and_hello(self, wids: List[str]) -> None:
        for wid in wids:
            err_path = (
                os.path.join(self.log_dir, f"{wid}.err")
                if self.log_dir else None
            )
            extra = (
                ("--trace-propagation", "1") if self.fleet_obs else ()
            )
            self.workers[wid] = _spawn(
                wid, self.broker.port, err_path, extra_args=extra
            )
        threads = [
            threading.Thread(
                target=w.read_ready, args=(self.ready_timeout_s,),
                daemon=True,
            )
            for w in self.workers.values() if w.wid in wids
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self.ready_timeout_s + 5)
        bad = [
            f"{w.wid}: {w.ready_error}"
            for w in self.workers.values() if w.wid in wids and w.port is None
        ]
        if bad:
            raise RuntimeError(f"workers failed to start: {bad}")

    def _run_inner(self, sc, chunks, n_lines, wids,
                   config_from_yaml_text, oracle_mod) -> dict:
        self._spawn_and_hello(wids)
        self.alive = list(wids)
        self._journal = {w: [] for w in wids}
        hello = self._hello_payload()
        self._broadcast(wire.T_HELLO, hello)
        base = {w: self._stats(w) for w in self.alive}

        kill_at = (
            int(self.kill_frac * len(chunks)) if self.kill else -1
        )
        self.victim = wids[-1] if self.kill else None

        t_feed = time.perf_counter()
        for i, chunk in enumerate(chunks):
            if i == kill_at and self.victim in self.alive:
                if self.churn:
                    # churn mode: SIGKILL with the feed PAUSED — no
                    # forwarded line ever touches the victim again, so
                    # detection is gossip's alone (the acceptance gate)
                    self._churn_kill()
                else:
                    # SIGKILL mid-flood: no goodbye, no flush — the next
                    # send to it is the detection
                    self.workers[self.victim].kill()
            self._send_chunk(chunk)
            self.fed_lines += len(chunk)
        # a victim killed on the very last chunks may never be hit by
        # the round-robin again: force detection so takeover happens
        if self.victim is not None and self.victim in self.alive:
            try:
                self.workers[self.victim].request(wire.T_PING, {})
            except (PeerUnavailable, OSError):
                self._on_death(self.victim)
        self._settle(tagged_floor=0)
        # second settle pass with the final tagged count: every
        # survivor must have consumed the whole replicated topic
        self._settle(tagged_floor=self._tagged_commands())
        feed_s = max(1e-9, time.perf_counter() - t_feed)

        final = {w: self._stats(w) for w in self.alive}
        report = self._report(
            sc, n_lines, feed_s, base, final,
            config_from_yaml_text, oracle_mod,
        )
        if self.rejoin and self.victim is not None:
            report["rejoin"] = self._rejoin_phase()
        if self.churn:
            report["join"] = self._join_phase()
            report["suspect_refute"] = self._suspect_refute_phase()
            report["leave"] = self._leave_phase()
            if self.schedule is not None:
                self.schedule.record("kill", dict(self.takeover))
                self.schedule.record("join", {
                    k: v for k, v in report["join"].items()
                    if k != "invariants"
                })
                self.schedule.record(
                    "slow_node",
                    {k: v for k, v in report["suspect_refute"].items()
                     if k != "invariants"},
                )
                self.schedule.record("leave", {
                    k: v for k, v in report["leave"].items()
                    if k != "invariants"
                })
                report["churn_schedule"] = self.schedule.rows()
            report["invariants"].update({
                f"join_{k}": v
                for k, v in report["join"]["invariants"].items()
            })
            report["invariants"].update({
                f"churn_{k}": v
                for k, v in report["suspect_refute"]["invariants"].items()
            })
            report["invariants"].update({
                f"leave_{k}": v
                for k, v in report["leave"]["invariants"].items()
            })
        return report

    # ---- rejoin / handback ----

    def _rejoin_phase(self) -> dict:
        from banjax_tpu.scenarios.shapes import LineChunk, generate

        victim = self.victim
        survivor = self.alive[0]
        # warm-start state for the newcomer: a survivor's decision
        # snapshot, applied idempotently over the wire
        snap = self.workers[survivor].request(wire.T_SNAPSHOT, {})
        self._spawn_and_hello([victim])
        newcomer = self.workers[victim]
        newcomer.request(wire.T_HELLO, self._hello_payload())
        sync_ack = newcomer.request(
            wire.T_SYNC, {"decisions": snap["decisions"]}
        )
        # handback is pure membership: ring recomputation, NO replay
        self._broadcast(
            wire.T_PEER_UP,
            {"peer": victim, "host": "127.0.0.1", "port": newcomer.port},
        )
        self.alive.append(victim)

        base = {w: self._stats(w) for w in self.alive}
        wave = generate(self.shape, self.seed + 1,
                        max(0.25, self.scale * 0.25))
        wave_chunks = [
            list(ev.lines) for ev in wave.events
            if isinstance(ev, LineChunk)
        ]
        wave_lines = sum(len(c) for c in wave_chunks)
        for chunk in wave_chunks:
            self._send_chunk(chunk)
            self.fed_lines += len(chunk)
        # the rejoined worker's reader attached at the topic tail; the
        # whole-topic floor only applies to the original survivors
        self._settle(tagged_floor=self._tagged_commands(),
                     skip_kafka_check=[victim])
        final = {w: self._stats(w) for w in self.alive}

        def _local(w: str) -> int:
            return int(final[w]["fabric"]["FabricLocalLines"]) - int(
                base[w]["fabric"]["FabricLocalLines"]
            )

        locals_sum = sum(_local(w) for w in self.alive)
        return {
            "snapshot_decisions": len(snap["decisions"]),
            "sync_applied": int(sync_ack.get("applied", 0)),
            "wave_lines": wave_lines,
            "wave_locals_sum": locals_sum,
            "newcomer_local_lines": _local(victim),
            "invariants": {
                # every handed-back line processed EXACTLY once
                # fabric-wide — no double-processing on rejoin
                "wave_exactly_once": locals_sum == wave_lines,
                "newcomer_took_lines": _local(victim) > 0,
                "sync_idempotent_applied":
                    int(sync_ack.get("applied", 0))
                    == len(snap["decisions"]),
            },
        }

    # ---- membership churn (gossip mode) ----

    def _member_status(self, observer: str, target: str) -> Optional[str]:
        snap = self._stats(observer)
        members = (snap.get("membership") or {}).get("members") or {}
        entry = members.get(target)
        return entry.get("status") if entry else None

    def _churn_kill(self) -> None:
        """SIGKILL the victim with the feed paused: detection must come
        from the gossip probe schedule alone (no forwarded line ever
        fails against it).  Returns once every survivor has confirmed
        the death AND completed its takeover."""
        victim = self.victim
        self.workers[victim].kill()
        t_kill = time.monotonic()
        self.alive.remove(victim)  # driver stops feeding it; NO broadcast
        pre = {w: self._stats(w) for w in self.alive}
        suspect_s = self.suspect_timeout_ms / 1000.0
        interval_s = self.gossip_interval_ms / 1000.0
        # worst case: full probe rotation to reach the victim, a failed
        # direct + indirect round, then the suspicion window — plus CI
        # slack (the measured distribution is what gets banked)
        deadline = t_kill + suspect_s + interval_s * (
            len(self.alive) + 6
        ) + 30.0
        confirmed: Dict[str, float] = {}
        while len(confirmed) < len(self.alive):
            if time.monotonic() > deadline:
                missing = [w for w in self.alive if w not in confirmed]
                raise RuntimeError(
                    f"gossip never confirmed {victim} dead on {missing}"
                )
            for w in self.alive:
                if w in confirmed:
                    continue
                if self._member_status(w, victim) in ("dead", "left"):
                    confirmed[w] = round(time.monotonic() - t_kill, 3)
            time.sleep(0.05)
        self.detection = confirmed
        self._await_takeovers(victim)
        # the driver's own direct-feed journal for the victim
        replayed = 0
        for chunk in self._journal[victim]:
            self._send_chunk(chunk, count_ack=False, replay=True)
            replayed += len(chunk)
        self._journal[victim] = []
        post = {w: self._stats(w) for w in self.alive}
        survivor_replayed = sum(
            int(post[w]["fabric"]["FabricReplayedLines"])
            - int(pre[w]["fabric"]["FabricReplayedLines"])
            for w in post
        )
        self.takeover = {
            "victim": victim,
            "mode": "gossip",
            "detect_after_lines": self.fed_lines,
            "detect_s": dict(confirmed),
            "max_detect_s": max(confirmed.values()),
            "suspect_timeout_s": suspect_s,
            "gossip_interval_s": interval_s,
            "driver_replayed_lines": replayed,
            "survivor_replayed_lines": survivor_replayed,
            "window_s": round(time.monotonic() - t_kill, 3),
        }

    def _join_phase(self) -> dict:
        """Automatic join: a brand-new worker announces itself to ONE
        live member (T_JOIN + snapshot pull, no driver HELLO, no
        PEER_UP broadcast) and the fleet discovers it by gossip — then
        a feed wave proves exactly-once handoff of its new ranges."""
        from banjax_tpu.scenarios.shapes import LineChunk, generate

        nid = f"w{self.n_workers}"
        seed_worker = self.workers[self.alive[0]]
        err_path = (
            os.path.join(self.log_dir, f"{nid}.err")
            if self.log_dir else None
        )
        extra = [
            "--join", f"127.0.0.1:{seed_worker.port}",
            "--gossip-interval-ms", str(self.gossip_interval_ms),
            "--suspect-timeout-ms", str(self.suspect_timeout_ms),
            "--grace-ms", "200.0",
        ]
        if self.transport == "json":
            extra += ["--inflight-frames", "0", "--wire-v2", "0"]
        elif self.transport == "shm":
            extra += ["--shm", "1"]
        newcomer = _spawn(
            nid, self.broker.port, err_path, extra_args=tuple(extra),
        )
        self.workers[nid] = newcomer
        newcomer.read_ready(self.ready_timeout_s)
        if newcomer.port is None:
            raise RuntimeError(f"join worker failed: {newcomer.ready_error}")
        # the fleet must converge on the newcomer WITHOUT any broadcast:
        # the seed learned it from T_JOIN, everyone else from gossip
        deadline = time.monotonic() + 60.0
        while any(
            self._member_status(w, nid) != "alive" for w in self.alive
        ):
            if time.monotonic() > deadline:
                raise RuntimeError(f"fleet never converged on joiner {nid}")
            time.sleep(0.05)
        self.alive.append(nid)
        self._journal[nid] = []

        base = {w: self._stats(w) for w in self.alive}
        wave = generate(self.shape, self.seed + 1,
                        max(0.25, self.scale * 0.25))
        wave_chunks = [
            list(ev.lines) for ev in wave.events
            if isinstance(ev, LineChunk)
        ]
        wave_lines = sum(len(c) for c in wave_chunks)
        for chunk in wave_chunks:
            self._send_chunk(chunk)
            self.fed_lines += len(chunk)
        # the joiner's kafka reader attached at the topic tail
        self._settle(tagged_floor=self._tagged_commands(),
                     skip_kafka_check=[nid])
        final = {w: self._stats(w) for w in self.alive}

        def _local(w: str) -> int:
            cur = int(final[w]["fabric"]["FabricLocalLines"])
            prev = int(base[w]["fabric"]["FabricLocalLines"]) \
                if w in base else 0
            return cur - prev

        locals_sum = sum(_local(w) for w in self.alive)
        synced = int(newcomer.ready_info.get("synced", 0))
        return {
            "joiner": nid,
            "seed_member": seed_worker.wid,
            "synced_decisions": synced,
            "wave_lines": wave_lines,
            "wave_locals_sum": locals_sum,
            "joiner_local_lines": _local(nid),
            "invariants": {
                "wave_exactly_once": locals_sum == wave_lines,
                "joiner_took_lines": _local(nid) > 0,
                "snapshot_synced": synced > 0,
                "no_survivor_restart": True,  # by construction: no
                # respawn, no HELLO re-push — convergence was gossip
            },
        }

    def _suspect_refute_phase(self) -> dict:
        """Slow-node cycle: arm a sleep failpoint on one member's gossip
        ack path so every probe against it times out — the fleet must
        SUSPECT it, and once disarmed the member must refute its own
        suspicion (incarnation bump) and return to ALIVE everywhere.
        Confirmed-dead during the window is tolerated (a slow node CAN
        time out — refute-after-dead heals it; recall is unaffected)."""
        target = self.alive[-1]
        observers = [w for w in self.alive if w != target]
        pre = {w: self._stats(w) for w in self.alive}
        delay_x = self.schedule.slow_delay_x if self.schedule else 3.0
        self.workers[target].request(wire.T_FAILPOINT, {
            "name": "fabric.gossip.ack", "mode": "sleep",
            "delay_s": (self.gossip_interval_ms / 1000.0) * delay_x,
        })
        suspected = False
        deadline = time.monotonic() + 60.0
        while not suspected and time.monotonic() < deadline:
            for w in observers:
                snap = self._stats(w)
                d = int(snap["fabric"]["FabricMembershipSuspects"]) - int(
                    pre[w]["fabric"]["FabricMembershipSuspects"]
                )
                if d >= 1:
                    suspected = True
                    break
            time.sleep(0.05)
        self.workers[target].request(wire.T_FAILPOINT, {
            "name": "fabric.gossip.ack", "disarm": True,
        })
        deadline = time.monotonic() + 60.0
        while any(
            self._member_status(w, target) != "alive" for w in observers
        ):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"{target} never refuted back to alive"
                )
            time.sleep(0.05)
        final = {w: self._stats(w) for w in self.alive}

        def _delta(key: str) -> int:
            return sum(
                int(final[w]["fabric"][key]) - int(pre[w]["fabric"][key])
                for w in self.alive
            )

        refuted = _delta("FabricMembershipRefuted")
        return {
            "target": target,
            "suspects_delta": _delta("FabricMembershipSuspects"),
            "refuted_delta": refuted,
            "confirmed_dead_delta": _delta("FabricMembershipConfirmedDead"),
            "invariants": {
                "suspicion_observed": suspected,
                "refutation_observed": refuted >= 1,
                "target_alive_everywhere": True,  # the wait above gates it
            },
        }

    def _leave_phase(self) -> dict:
        """Planned leave: the newest member drains and departs.  Zero
        shed, zero replay, LEFT visible everywhere, and a follow-up wave
        lands exactly-once on the remaining fleet."""
        from banjax_tpu.scenarios.shapes import LineChunk, generate

        leaver = self.alive[-1]
        rest = [w for w in self.alive if w != leaver]
        pre = {w: self._stats(w) for w in self.alive}
        ack = self.workers[leaver].request(wire.T_LEAVE, {})
        self.alive.remove(leaver)
        try:
            self.workers[leaver].proc.wait(timeout=30)
            departed = True
        except subprocess.TimeoutExpired:
            departed = False
        # the LEFT digest was announced synchronously before the ack:
        # nobody may still believe the leaver owns anything
        observed_left = all(
            self._member_status(w, leaver) == "left" for w in rest
        )

        base = {w: self._stats(w) for w in rest}
        wave = generate(self.shape, self.seed + 2,
                        max(0.25, self.scale * 0.25))
        wave_chunks = [
            list(ev.lines) for ev in wave.events
            if isinstance(ev, LineChunk)
        ]
        wave_lines = sum(len(c) for c in wave_chunks)
        for chunk in wave_chunks:
            self._send_chunk(chunk)
            self.fed_lines += len(chunk)
        self._settle(tagged_floor=self._tagged_commands())
        final = {w: self._stats(w) for w in rest}

        def _shed(snap: dict) -> int:
            return int(snap["sched"]["PipelineShedLines"]) + int(
                snap["fabric"]["FabricShedLines"]
            )

        # the leaver's own final ledger rides the T_LEAVE ack (the
        # process is gone by now)
        leaver_shed = _shed(ack) - _shed(pre[leaver])
        rest_shed = sum(_shed(final[w]) - _shed(pre[w]) for w in rest)
        replay_delta = sum(
            int(final[w]["fabric"]["FabricReplayedLines"])
            - int(pre[w]["fabric"]["FabricReplayedLines"])
            for w in rest
        ) + (
            int(ack["fabric"]["FabricReplayedLines"])
            - int(pre[leaver]["fabric"]["FabricReplayedLines"])
        )
        locals_sum = sum(
            int(final[w]["fabric"]["FabricLocalLines"])
            - int(base[w]["fabric"]["FabricLocalLines"])
            for w in rest
        )
        return {
            "leaver": leaver,
            "drain_ms": ack.get("drain_ms"),
            "announced": ack.get("announced"),
            "wave_lines": wave_lines,
            "wave_locals_sum": locals_sum,
            "shed_leaver": leaver_shed,
            "shed_rest": rest_shed,
            "replayed_lines": replay_delta,
            "invariants": {
                "drain_flushed": bool(ack.get("flushed")),
                "departed": departed,
                "left_observed_everywhere": observed_left,
                "zero_shed": leaver_shed == 0 and rest_shed == 0,
                "zero_replay": replay_delta == 0,
                "wave_exactly_once": locals_sum == wave_lines,
            },
        }

    # ---- reporting ----

    def _report(self, sc, n_lines, feed_s, base, final,
                config_from_yaml_text, oracle_mod) -> dict:
        engine_bans: List[Tuple[str, str]] = []
        for w in self.alive:
            engine_bans.extend(
                (ip, rule) for ip, rule in final[w]["bans"]
            )
        cfg = config_from_yaml_text(sc.rules_yaml)
        oracle_bans = oracle_mod.expected_bans(sc, cfg)
        precision, recall, tp = oracle_mod.precision_recall(
            engine_bans, oracle_bans
        )

        per_worker = {}
        invariants: Dict[str, bool] = {}
        dup_total = 0
        for w in self.alive:
            sched_d = {
                k: int(final[w]["sched"][k]) - int(base[w]["sched"][k])
                for k in ("PipelineAdmittedLines", "PipelineProcessedLines",
                          "PipelineShedLines", "PipelineDrainErrorLines")
            }
            fab = {k: int(v) for k, v in final[w]["fabric"].items()}
            dup_total += fab["FabricDuplicatesSuppressed"]
            per_worker[w] = {"sched_delta": sched_d, "fabric": fab,
                             "router": final[w]["router"]}
            invariants[f"{w}_pipeline_accounting"] = (
                sched_d["PipelineAdmittedLines"]
                == sched_d["PipelineProcessedLines"]
                + sched_d["PipelineShedLines"]
                + sched_d["PipelineDrainErrorLines"]
            )
            # fabric ledger: every line that ENTERED this worker
            # (received over the wire, or re-materialized from its
            # journal at takeover) left as exactly one of
            # local/forwarded/shed/replay-skipped
            invariants[f"{w}_fabric_ledger"] = (
                fab["FabricLocalLines"] + fab["FabricForwardedLines"]
                + fab["FabricShedLines"]
                + fab.get("FabricReplaySkippedLines", 0)
                == fab["FabricReceivedLines"] + fab["FabricReplayedLines"]
            )
        invariants["driver_fed_equals_acked"] = (
            self.fed_lines == self.acked_lines
        )
        invariants["recall_one"] = recall == 1.0
        if self.kill:
            invariants["takeover_happened"] = bool(self.takeover)
            invariants["survivors_took_over"] = all(
                per_worker[w]["fabric"]["FabricTakeovers"] >= 1
                for w in self.alive
            )
            invariants["victim_in_last_takeover"] = all(
                ((final[w]["router"] or {}).get("last_takeover") or {})
                .get("peer") == self.victim
                for w in self.alive
            )
        if self.n_workers > 1 and engine_bans:
            # every replicated decision echoes back to its origin and
            # is suppressed there: the idempotency witness
            invariants["duplicates_suppressed"] = dup_total > 0

        return {
            "harness": "dryrun_fabric",
            "n_workers": self.n_workers,
            "transport": self.transport,
            "shape": self.shape,
            "seed": self.seed,
            "scale": self.scale,
            "killed": self.victim,
            "n_lines": n_lines,
            "fed_lines": self.fed_lines,
            "acked_lines": self.acked_lines,
            "feed_s": round(feed_s, 3),
            "lines_per_sec": round(n_lines / feed_s, 1),
            "engine_bans": len(engine_bans),
            # canonical ban log: the transport-differential suites
            # compare this byte-for-byte between wire encodings
            "ban_log": sorted(f"{ip} {rule}" for ip, rule in engine_bans),
            "oracle_bans": len(oracle_bans),
            "true_positives": tp,
            "precision": round(precision, 6),
            "recall": round(recall, 6),
            "duplicates_suppressed": dup_total,
            "takeover": self.takeover,
            "per_worker": per_worker,
            "invariants": invariants,
        }


def run_fabric(**kwargs) -> dict:
    """Convenience wrapper: one episode, report dict back."""
    return FabricDryrun(**kwargs).run()


def run_forward_path(
    transport: str = "v2",
    n_chunks: int = 200,
    chunk_lines: int = 64,
    inflight_frames: int = 8,
    ready_timeout_s: float = 420.0,
    log_dir: Optional[str] = None,
) -> dict:
    """Transport micro-benchmark: two shards, w0 fed chunks whose lines
    are ALL owned by w1, so every line crosses the peer data path
    ("json" sync / "v2" pipelined TCP / "shm" rings).  The measured
    window covers feed AND drain (T_FLUSH lands every in-flight frame),
    so pipelining cannot hide undelivered lines; the audit is
    transport-lossless delivery (w1 received == w0 forwarded == fed).
    The destination pipeline buffer (131072 lines) is sized above the
    row, so acks measure the wire, not the matcher."""
    from banjax_tpu.fabric.hashring import ConsistentHashRing
    from banjax_tpu.scenarios.shapes import T0

    ring = ConsistentHashRing(("w0", "w1"), vnodes=64)
    ips: List[str] = []
    i = 0
    while len(ips) < 64:
        ip = f"10.{(i >> 8) & 255}.{i & 255}.7"
        if ring.owner(ip) == "w1":
            ips.append(ip)
        i += 1
    chunks = [
        [
            f"{T0 + c * 0.001:.6f} "
            f"{ips[(c * chunk_lines + j) % len(ips)]} "
            "GET fwd.example GET /about HTTP/1.1 fp -"
            for j in range(chunk_lines)
        ]
        for c in range(n_chunks)
    ]
    n_lines = n_chunks * chunk_lines

    workers: Dict[str, _Worker] = {}
    try:
        for wid in ("w0", "w1"):
            err = (
                os.path.join(log_dir, f"fwd_{wid}.err")
                if log_dir else None
            )
            workers[wid] = _spawn(wid, 0, err)
        threads = [
            threading.Thread(
                target=w.read_ready, args=(ready_timeout_s,), daemon=True
            )
            for w in workers.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(ready_timeout_s + 5)
        bad = [
            f"{w.wid}: {w.ready_error}"
            for w in workers.values() if w.port is None
        ]
        if bad:
            raise RuntimeError(f"forward-path workers failed: {bad}")
        hello = {
            "peers": {
                w.wid: ["127.0.0.1", w.port] for w in workers.values()
            },
            "vnodes": 64,
            "send_timeout_ms": 2000.0,
            "grace_ms": 200.0,
            "inflight_frames": (
                0 if transport == "json" else inflight_frames
            ),
            "wire_v2": transport != "json",
            "shm": transport == "shm",
        }
        for w in workers.values():
            w.request(wire.T_HELLO, hello)

        t0 = time.perf_counter()
        for chunk in chunks:
            workers["w0"].request(
                wire.T_LINES, {"lines": chunk, "route": True}
            )
        drained = workers["w0"].request(wire.T_FLUSH, {"timeout": 600})
        elapsed = max(1e-9, time.perf_counter() - t0)

        s0 = workers["w0"].request(wire.T_STATS, {})
        s1 = workers["w1"].request(wire.T_STATS, {})
        received = int(s1["fabric"]["FabricReceivedLines"])
        forwarded = int(s0["fabric"]["FabricForwardedLines"])
        peer_desc = (
            (s0.get("router") or {}).get("peers") or {}
        ).get("w1", {})
        return {
            "harness": "forward_path",
            "transport": transport,
            "peer_transport": peer_desc.get("transport"),
            "n_lines": n_lines,
            "chunk_lines": chunk_lines,
            "feed_s": round(elapsed, 3),
            "lines_per_sec": round(n_lines / elapsed, 1),
            "forwarded": forwarded,
            "received": received,
            "frames_sent": int(s0["fabric"].get("FabricFramesSent", 0)),
            "invariants": {
                "drained": bool(drained.get("flushed")),
                "all_lines_crossed": (
                    received == n_lines and forwarded == n_lines
                ),
            },
        }
    finally:
        for w in workers.values():
            w.shutdown()
