"""Kernel-edge ban-path counters for the /metrics surfaces.

A LEAF module in the challenge/stats.py mold: obs/exposition.py and
obs/metrics.py import it lazily, and the banjax_ipset_* families in
obs/registry.py keep the schema CI-locked.

Publishers: the netlink batch writer (effectors/ipset_netlink.py) and
the Banner's subprocess path.  The hardening contract lives in the
labels: every failure is COUNTED (`banjax_ipset_errors_total{path}`)
and routed — netlink failures fall back to per-entry subprocess adds
(`fallback_total`), an over-full queue sheds its oldest entries
(`queue_shed_total`) instead of blocking the ban path.
"""

from __future__ import annotations

import threading
from typing import Dict

# where the failure happened: the netlink send or the subprocess shim
ERROR_PATHS = ("netlink", "subprocess")


class IpsetStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.batch_sends_total = 0     # netlink sendmsg calls that acked clean
        self.batch_entries_total = 0   # entries carried by those sends
        self._errors: Dict[str, int] = {}
        self.fallback_total = 0        # entries re-routed netlink → subprocess
        self.queue_shed_total = 0      # oldest entries dropped on overflow
        self._depth_fn = None          # live queue depth, sampled at scrape

    def set_depth_fn(self, fn) -> None:
        with self._lock:
            self._depth_fn = fn

    def note_batch(self, entries: int) -> None:
        with self._lock:
            self.batch_sends_total += 1
            self.batch_entries_total += entries

    def note_error(self, path: str, n: int = 1) -> None:
        with self._lock:
            self._errors[path] = self._errors.get(path, 0) + n

    def note_fallback(self, n: int = 1) -> None:
        with self._lock:
            self.fallback_total += n

    def note_shed(self, n: int = 1) -> None:
        with self._lock:
            self.queue_shed_total += n

    def prom_snapshot(self) -> dict:
        with self._lock:
            depth_fn = self._depth_fn
            out = {
                "batch_sends_total": self.batch_sends_total,
                "batch_entries_total": self.batch_entries_total,
                "errors": dict(self._errors),
                "errors_total": sum(self._errors.values()),
                "fallback_total": self.fallback_total,
                "queue_shed_total": self.queue_shed_total,
            }
        depth = 0
        if depth_fn is not None:
            try:
                depth = int(depth_fn())
            except Exception:  # noqa: BLE001 — a closed writer reads as 0
                depth = 0
        out["queue_depth"] = depth
        return out

    def active(self) -> bool:
        """True once the batch writer exists or anything was counted —
        the render gate, so subprocess-only deployments stay clean."""
        with self._lock:
            return bool(
                self.batch_sends_total or self._errors or self.fallback_total
                or self.queue_shed_total or self._depth_fn is not None
            )

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self.batch_sends_total = 0
            self.batch_entries_total = 0
            self._errors.clear()
            self.fallback_total = 0
            self.queue_shed_total = 0
            self._depth_fn = None


_stats = IpsetStats()


def get_stats() -> IpsetStats:
    return _stats
