"""Kernel-level blocking via ipset/iptables.

Reference behavior: /root/reference/banjax.go:29-64 and internal/iptables.go:
at startup create ipset `banjax_ipset` (hash:ip, default timeout
iptables_ban_seconds) and insert an iptables INPUT rule
`-m set --match-set banjax_ipset src -j DROP`; bans are `ipset add` entries
with per-entry timeouts the kernel expires on its own; admin APIs
test/list/del entries. Standalone-testing mode skips the kernel entirely.

The reference links Go ipset/iptables libraries; here the same operations go
through the `ipset`/`iptables` binaries via subprocess (the "native shim" —
there is no stable Python netlink API in the stdlib, and these calls are rare:
one per ban, not per request).
"""

from __future__ import annotations

import logging
import re
import subprocess
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

IPSET_NAME = "banjax_ipset"


class IpsetError(RuntimeError):
    pass


def _run(args: List[str]) -> Tuple[int, str]:
    try:
        proc = subprocess.run(args, capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise IpsetError(f"{args[0]} invocation failed: {e}") from None
    return proc.returncode, (proc.stdout or "") + (proc.stderr or "")


class IpsetInstance:
    """Operations on one named ipset. Mirrors the subset of gonetx/ipset the
    reference uses (Add with Timeout, Test, List, Del)."""

    def __init__(self, name: str = IPSET_NAME):
        self.name = name

    def add(self, ip: str, timeout_seconds: int) -> None:
        code, out = _run(
            ["ipset", "add", self.name, ip, "timeout", str(timeout_seconds), "-exist"]
        )
        if code != 0:
            raise IpsetError(f"ipset add failed: {out.strip()}")

    def test(self, ip: str) -> bool:
        code, _ = _run(["ipset", "test", self.name, ip])
        return code == 0

    def list_entries(self) -> List[str]:
        """Entries formatted like the reference's API output:
        `1.2.3.4 timeout 298`."""
        code, out = _run(["ipset", "list", self.name])
        if code != 0:
            raise IpsetError(f"ipset list failed: {out.strip()}")
        entries = []
        in_members = False
        for line in out.splitlines():
            if line.startswith("Members:"):
                in_members = True
                continue
            if in_members and line.strip():
                entries.append(line.strip())
        return entries

    def delete(self, ip: str) -> None:
        code, out = _run(["ipset", "del", self.name, ip])
        if code != 0:
            raise IpsetError(f"ipset del failed: {out.strip()}")


def init_ipset(iptables_ban_seconds: int, standalone_testing: bool) -> Optional[IpsetInstance]:
    """Port of banjax.go init_ipset: create the set and the DROP rule.

    Returns None in standalone testing (banjax.go:30-33). Raises on failure
    otherwise (the reference panics)."""
    if standalone_testing:
        log.info("init_ipset: not initializing ipset in testing")
        return None

    code, out = _run(
        ["ipset", "create", IPSET_NAME, "hash:ip",
         "timeout", str(iptables_ban_seconds), "-exist"]
    )
    if code != 0:
        raise IpsetError(f"ipset create failed: {out.strip()}")

    # idempotent insert: only add the DROP rule if it isn't there already
    rule = ["-m", "set", "--match-set", IPSET_NAME, "src", "-j", "DROP"]
    code, _ = _run(["iptables", "-C", "INPUT"] + rule)
    if code != 0:
        code, out = _run(["iptables", "-I", "INPUT", "1"] + rule)
        if code != 0:
            raise IpsetError(f"iptables insert failed: {out.strip()}")

    return IpsetInstance(IPSET_NAME)
